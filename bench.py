#!/usr/bin/env python
"""Headline benchmark (driver contract: ONE JSON line on stdout).

Metric (BASELINE.md): output tokens/sec via /ollama/api/generate. The run
drives the FULL stack in one process — gateway HTTP → scheduler → in-memory
bus → WorkerService → InferenceEngine on whatever accelerator jax sees —
with N concurrent streaming requests (continuous batching), and reports
aggregate decode throughput + p50 TTFT.

vs_baseline anchors to BASELINE.json's comparison point ("Ollama-on-A100
output tokens/sec"); the reference publishes no numbers (BASELINE.md), so
the anchor values below are approximate public single-stream Ollama-on-A100
figures for each model. vs_baseline = measured_aggregate / anchor.

Usage: python bench.py [--model llama3.2:3b] [--requests 8] [--tokens 128]
       [--tiny] (tiny-llama on CPU, smoke test)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import time

# Approximate public Ollama single-stream numbers on A100 (the BASELINE.json
# comparison anchor; nothing is published by the reference itself).
A100_OLLAMA_TOK_S = {
    "llama3:8b": 110.0,
    "llama3.1:8b": 110.0,
    "llama3.2:3b": 220.0,
    "llama3.2:1b": 350.0,
    "tiny-llama": 1.0,  # smoke-test placeholder
}


async def run_bench(model: str, n_requests: int, n_tokens: int,
                    max_slots: int, prompt_len: int) -> dict:
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config, WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    engine = InferenceEngine(EngineConfig(
        model=model,
        max_slots=max_slots,
        page_size=64,
        num_pages=max(256, max_slots * 48),
        max_pages_per_slot=48,
        prefill_buckets=(256, 1024),
    ))
    bus = InMemoryBus()
    await bus.connect()
    config = Config()
    registry = WorkerRegistry(bus, config.scheduler)
    scheduler = JobScheduler(bus, registry, config.scheduler)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(bus, {model: engine}, WorkerConfig(),
                           stream_flush_ms=5)
    await worker.start()
    await asyncio.sleep(0.1)
    client = TestClient(TestServer(app))
    await client.start_server()

    prompt = "The quick brown fox jumps over the lazy dog. " * (prompt_len // 10)

    # warmup: trigger prefill+decode compiles before timing — MUST use the
    # same prompt length as the measured run, or the real bucket's prefill
    # compile (tens of seconds on first use) lands inside the timed window
    warm = await client.post("/ollama/api/generate", json={
        "model": model, "prompt": prompt, "stream": False,
        "options": {"temperature": 0, "num_predict": 4},
    })
    assert warm.status == 200, await warm.text()

    ttfts: list[float] = []
    tokens_out = [0]

    async def one(i: int) -> None:
        t0 = time.perf_counter()
        first = True
        async with client.post("/ollama/api/generate", json={
            "model": model, "prompt": f"[{i}] {prompt}",
            "options": {"temperature": 0.7, "seed": i, "num_predict": n_tokens},
        }) as resp:
            assert resp.status == 200, await resp.text()
            async for line in resp.content:
                if not line.strip():
                    continue
                if first:
                    ttfts.append(time.perf_counter() - t0)
                    first = False
                frame = json.loads(line)
                if frame.get("done"):
                    tokens_out[0] += frame.get("eval_count") or 0

    t_start = time.perf_counter()
    await asyncio.gather(*(one(i) for i in range(n_requests)))
    wall = time.perf_counter() - t_start

    await client.close()
    await worker.stop()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()

    return {
        "tok_s": tokens_out[0] / wall,
        "p50_ttft_ms": statistics.median(ttfts) * 1000,
        "tokens": tokens_out[0],
        "wall_s": wall,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama3.2:3b")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=128)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=120)
    ap.add_argument("--tiny", action="store_true",
                    help="tiny-llama CPU smoke test")
    args = ap.parse_args()
    if args.tiny:
        import os

        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
        args.model = "tiny-llama"
        args.tokens = min(args.tokens, 16)
        args.prompt_len = 20

    r = asyncio.run(run_bench(
        args.model, args.requests, args.tokens, args.slots, args.prompt_len
    ))
    baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
    print(json.dumps({
        "metric": f"output tokens/sec via /ollama/api/generate ({args.model}, "
                  f"{args.requests} concurrent streams)",
        "value": round(r["tok_s"], 2),
        "unit": "tok/s",
        "vs_baseline": round(r["tok_s"] / baseline, 3) if baseline else None,
        "p50_ttft_ms": round(r["p50_ttft_ms"], 1),
        "tokens": r["tokens"],
        "wall_s": round(r["wall_s"], 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
