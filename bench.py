#!/usr/bin/env python
"""Headline benchmark (driver contract: ONE JSON line on stdout).

Metric (BASELINE.md): output tokens/sec via /ollama/api/generate. The run
drives the FULL stack in one process — gateway HTTP → scheduler → in-memory
bus → WorkerService → InferenceEngine on whatever accelerator jax sees —
with N concurrent streaming requests (continuous batching), and reports
aggregate decode throughput + p50 TTFT.

vs_baseline anchors to BASELINE.json's comparison point ("Ollama-on-A100
output tokens/sec"); the reference publishes no numbers (BASELINE.md), so
the anchor values below are approximate public single-stream Ollama-on-A100
figures for each model. vs_baseline = measured_aggregate / anchor.

Usage: python bench.py [--model llama3.2:3b] [--requests 8] [--tokens 128]
       [--tiny] (tiny-llama on CPU, smoke test)

Perf trajectory (ISSUE 4): ``--emit BENCH_rNN.json`` writes a standardized
machine-readable result record (schema gridllm-bench/v1: p50/p95 TTFT, ITL,
tok/s, steady-state recompile count from the jit tripwire, peak HBM);
``--compare old.json`` checks the current run against a previous record and
exits nonzero on a >10% regression in any shared metric — the perf gate CI
runs (.github/workflows/tier1.yml perf-smoke).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import time
import uuid

from gridllm_tpu.utils.config import env_raw

# Approximate public Ollama single-stream numbers on A100 (the BASELINE.json
# comparison anchor; nothing is published by the reference itself).
A100_OLLAMA_TOK_S = {
    "llama3:8b": 110.0,
    "llama3.1:8b": 110.0,
    "llama3.2:3b": 220.0,
    "llama3.2:1b": 350.0,
    "tiny-llama": 1.0,  # smoke-test placeholder
}

# Approximate public Ollama batch-embedding throughput on A100 for the
# BASELINE config #5 anchor (nothing published by the reference itself).
EMBED_BASELINE_QPS = {
    "all-minilm": 2500.0,
    "tiny-bert": 1.0,  # smoke-test placeholder
    "tiny-llama": 1.0,
}


async def _build_stack(engine, model: str, stream_flush_ms: int = 5,
                       trace_capacity: int = 0):
    """The full in-process serving stack (gateway → scheduler → in-memory
    bus → WorkerService → engine) every bench scenario drives — ONE copy
    so harness wiring changes land everywhere at once."""
    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config, WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    bus = InMemoryBus()
    await bus.connect()
    config = Config()
    registry = WorkerRegistry(bus, config.scheduler)
    scheduler = JobScheduler(bus, registry, config.scheduler)
    if trace_capacity:
        # stage stats read measured timelines — outgrow the default trace
        # LRU so large --requests runs aren't silently truncated to its tail
        scheduler.tracer.max_traces = max(scheduler.tracer.max_traces,
                                          trace_capacity)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(bus, {model: engine}, WorkerConfig(),
                           stream_flush_ms=stream_flush_ms)
    return bus, registry, scheduler, app, worker


async def _teardown_stack(bus, registry, scheduler, worker, client=None):
    """Teardown ALSO on failure: the kernel-fallback retry in main()
    rebuilds everything, and a half-alive first stack (engine runner
    thread + HBM weights/KV pool) would make the retry OOM for exactly
    the big models that need the fallback."""
    if client is not None:
        try:
            await client.close()
        except Exception:  # noqa: BLE001
            pass
    try:
        await worker.stop()
    except Exception:  # noqa: BLE001
        pass
    try:
        await scheduler.shutdown()
        await registry.shutdown()
        await bus.disconnect()
    except Exception:  # noqa: BLE001
        pass


async def run_bench(model: str, n_requests: int, n_tokens: int,
                    max_slots: int, prompt_len: int,
                    profile_dir: str | None = None) -> dict:

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.worker.main import resolve_checkpoint

    # bench honesty (VERDICT r03 weak #4): with no checkpoint the run uses
    # random weights + the byte tokenizer (representative compute,
    # unrepresentative tokenization) and the metric string says so. Same
    # resolution logic as the worker entrypoint — one source of truth.
    ckpt, tok = resolve_checkpoint(
        env_raw("GRIDLLM_CHECKPOINT_DIR"), model
    )
    engine = InferenceEngine(EngineConfig(
        model=model,
        checkpoint_path=ckpt,
        tokenizer=tok,
        max_slots=max_slots,
        page_size=64,
        num_pages=max(256, max_slots * 48),
        max_pages_per_slot=48,
        prefill_buckets=(256, 1024),
    ))
    bus, registry, scheduler, app, worker = await _build_stack(
        engine, model, trace_capacity=n_requests * 2 + 16)
    try:
        return await _run_bench_inner(
            client_ctx=(app, worker), engine=engine, model=model,
            n_requests=n_requests, n_tokens=n_tokens,
            prompt_len=prompt_len, profile_dir=profile_dir, ckpt=ckpt,
            scheduler=scheduler,
        )
    finally:
        await _teardown_stack(bus, registry, scheduler, worker)


def _p95(values: list[float]) -> float | None:
    if not values:
        return None
    vs = sorted(values)
    return vs[min(len(vs) - 1, max(0, -(-95 * len(vs) // 100) - 1))]


def _perf_sidecar() -> dict:
    """Recompile + peak-HBM accounting from the obs perf layer (ISSUE 4),
    read BEFORE teardown while the engine's arrays and memory probe are
    still live. recompiles_steady > 0 in a fixed-shape bench run means
    shape bucketing regressed — the perf-smoke CI gate asserts it is 0."""
    from gridllm_tpu.obs import memory_snapshot, recompile_totals

    rec = recompile_totals()
    peak = 0
    source = "none"
    for dev in memory_snapshot()["devices"].values():
        for key, src in (("peakBytesInUse", "allocator_peak"),
                         ("bytesInUse", "allocator_in_use"),
                         ("totalLiveBytes", "end_of_run_live")):
            cand = dev.get(key)
            if cand:
                if int(cand) > peak:
                    peak, source = int(cand), src
                break
    return {
        "recompiles_warmup": rec["warmup"],
        "recompiles_steady": rec["steady"],
        "recompiles_by_fn": rec["byFn"],
        "peak_hbm_bytes": peak,
        # honesty marker: only "allocator_peak" (TPU/GPU memory_stats) is
        # a true high-water mark; CPU backends report end-of-run live
        # bytes, which cannot see transient mid-decode spikes
        "peak_hbm_source": source,
    }


def _stage_stats(tracer, request_ids) -> dict:
    """p50 per-stage durations (ms) from the obs tracer's stitched
    timelines — the per-stage breakdown that explains the end-to-end
    numbers, read from the SAME spans /admin/trace serves instead of being
    re-timed here (ISSUE 1 satellite)."""
    keymap = {"queue.wait": "p50_queue_wait_ms",
              "engine.prefill": "p50_prefill_ms",
              "engine.decode": "p50_decode_ms"}
    stages: dict[str, list[float]] = {k: [] for k in keymap}
    ttfts: list[float] = []
    for rid in request_ids:
        for s in tracer.export(rid) or []:
            if s["name"] in stages and s.get("durationMs") is not None:
                stages[s["name"]].append(s["durationMs"])
            elif s["name"] == "gateway.first_token":
                t = (s.get("meta") or {}).get("ttftMs")
                if t is not None:
                    ttfts.append(float(t))
    out = {keymap[name]: round(statistics.median(vals), 2)
           for name, vals in stages.items() if vals}
    if ttfts:
        # gateway-side TTFT (submit → first stream frame) — the top-level
        # p50_ttft_ms stays the client-observed HTTP number; the delta
        # between them is gateway/HTTP overhead
        out["p50_ttft_gateway_ms"] = round(statistics.median(ttfts), 2)
    return out


def _critical_path_stats(tracer, request_ids) -> dict:
    """p50 per-segment critical-path decomposition (ms) across the
    measured requests (ISSUE 17). Unlike _stage_stats' raw span
    durations these segments are ADDITIVE — per request they sum to the
    traced e2e latency — so the record carries a decomposition that
    explains 100% of the latency, not a set of overlapping timers."""
    from gridllm_tpu.obs.timeline import critical_path

    per_seg: dict[str, list[float]] = {}
    for rid in request_ids:
        segs = critical_path(tracer.export(rid) or [])
        if not segs:
            continue  # root span not sealed (request still in flight)
        for seg, seconds in segs.items():
            per_seg.setdefault(seg, []).append(seconds * 1000.0)
    return {seg: round(statistics.median(vals), 2)
            for seg, vals in sorted(per_seg.items()) if vals}


async def _run_bench_inner(client_ctx, engine, model, n_requests, n_tokens,
                           prompt_len, profile_dir, ckpt,
                           scheduler=None) -> dict:
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    app, worker = client_ctx
    await worker.start()
    await asyncio.sleep(0.1)
    client = TestClient(TestServer(app))
    await client.start_server()

    prompt = "The quick brown fox jumps over the lazy dog. " * (prompt_len // 10)

    # warmup: trigger prefill+decode compiles before timing — MUST use the
    # same prompt length as the measured run, or the real bucket's prefill
    # compile (tens of seconds on first use) lands inside the timed window.
    # Bounded wait: a device-level failure must surface as a fast, retryable
    # error (main() falls back to GRIDLLM_PALLAS=0), not a 300 s job timeout
    # that eats the whole bench window.
    warm = await client.post("/ollama/api/generate", json={
        "model": model, "prompt": prompt, "stream": False,
        "options": {"temperature": 0, "num_predict": 4},
    }, timeout=aiohttp.ClientTimeout(total=240))
    assert warm.status == 200, await warm.text()
    if not engine.running and not engine.embedding_only:
        raise RuntimeError("engine runner died during warmup "
                           "(device-level failure)")
    # stage stats must cover the MEASURED requests only, not the warmup
    warm_ids = set(scheduler.tracer.ids()) if scheduler is not None else set()

    ttfts: list[float] = []
    itls: list[float] = []  # per-stream mean inter-token latency
    tokens_out = [0]

    if profile_dir:
        # SURVEY §5.1 / VERDICT r03 #1: capture a device trace of the
        # measured window for op-level attribution (view with
        # tensorboard --logdir or xprof)
        import jax

        jax.profiler.start_trace(profile_dir)

    async def one(i: int) -> None:
        t0 = time.perf_counter()
        t_first = t_last = None
        async with client.post("/ollama/api/generate", json={
            "model": model, "prompt": f"[{i}] {prompt}",
            "options": {"temperature": 0.7, "seed": i, "num_predict": n_tokens},
        }) as resp:
            assert resp.status == 200, await resp.text()
            async for line in resp.content:
                if not line.strip():
                    continue
                now = time.perf_counter()
                if t_first is None:
                    t_first = now
                    ttfts.append(now - t0)
                t_last = now
                frame = json.loads(line)
                if frame.get("done"):
                    n = frame.get("eval_count") or 0
                    tokens_out[0] += n
                    if n > 1 and t_first is not None:
                        # streaming smoothness: a healthy pipeline spreads
                        # tokens across the window; a burst-at-the-end
                        # pathology (r03's 13 s TTFT) shows up as itl ≈ 0
                        # with huge ttft
                        itls.append((t_last - t_first) / (n - 1) * 1000)

    t_start = time.perf_counter()
    try:
        await asyncio.gather(*(one(i) for i in range(n_requests)))
    finally:
        if profile_dir:  # finalize the trace even when a request fails
            import jax

            jax.profiler.stop_trace()
    wall = time.perf_counter() - t_start

    await client.close()  # remaining teardown is run_bench's finally

    stages = {}
    critical_path_p50: dict = {}
    slo_attainment = None
    goodput_tok_s = None
    capacity = None
    fleet_health = None
    if scheduler is not None:
        # worker-side spans publish on trace:{id} AFTER job:result resolves
        # the HTTP stream — drain the bus so the tail requests' prefill/
        # decode spans are ingested before we read the timelines
        flush = getattr(scheduler.bus, "flush", None)
        if flush is not None:
            await flush()
        measured = [r for r in scheduler.tracer.ids() if r not in warm_ids]
        stages = _stage_stats(scheduler.tracer, measured)
        critical_path_p50 = _critical_path_stats(scheduler.tracer, measured)
        # SLO/goodput from the obs SLO engine (ISSUE 2): the measured
        # streams are the "interactive" class (the warmup is non-streaming
        # → "batch", so it does not pollute these numbers)
        inter = scheduler.slo.snapshot()["classes"].get("interactive") or {}
        slo_attainment = inter.get("attainment")
        if inter.get("goodputTokens") is not None:
            goodput_tok_s = inter["goodputTokens"] / wall
        # usage + capacity (ISSUE 16): the shard's per-tenant token ledger
        # and the per-model demand/headroom snapshot behind /admin/capacity
        # — lets CI gate that the bench traffic was attributed (non-empty
        # token totals) and that demand tracking saw the measured requests
        capacity = {
            "snapshot": scheduler.capacity.snapshot(),
            "usage_tokens": scheduler.usage.token_totals(),
        }
        # fleet health (ISSUE 19): canary probe summary + per-state worker
        # counts — on a healthy single-worker bench this gates to zero
        # quarantines and (when probing is enabled) a 1.0 pass rate
        fleet_health = {
            "canary": scheduler.prober.summary(),
            "worker_states": scheduler.health.counts(),
        }
    p95 = _p95(ttfts)
    return {
        "tok_s": tokens_out[0] / wall,
        "p50_ttft_ms": statistics.median(ttfts) * 1000,
        "p95_ttft_ms": p95 * 1000 if p95 is not None else None,
        "p50_itl_ms": statistics.median(itls) if itls else None,
        "tokens": tokens_out[0],
        "wall_s": wall,
        "stages": stages,
        "critical_path": critical_path_p50,
        "slo_attainment": slo_attainment,
        "goodput_tok_s": goodput_tok_s,
        "capacity": capacity,
        "fleet_health": fleet_health,
        "perf": _perf_sidecar(),
        "weights": "real-checkpoint" if ckpt else "random-weights synthetic",
    }


async def run_long_context_bench(model: str, n_requests: int,
                                 n_tokens: int, max_slots: int,
                                 prefix_len: int,
                                 long_prompt_len: int) -> dict:
    """Long-context / tiered-KV scenario (ISSUE 11), extending
    --shared-prefix with LRU-overflow pressure: N streams share one long
    system prompt (cold round populates the prefix cache, warm round
    measures the warm TTFT), then a burst of max-capacity long prompts
    overflows the HBM reuse LRU — evicting the shared prefix — and a
    final post-eviction round re-issues the shared prompts. Run twice:
    tier OFF (the long burst destroys the warm TTFT — the regression)
    and tier ON (evicted pages spilled to host RAM page back in on
    match, recovering it). Spill dtype is raw for the A/B so both arms'
    streams are byte-comparable; per-tier hit rates and restore counts
    ride the record."""

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.worker.main import resolve_checkpoint

    ckpt, tok = resolve_checkpoint(env_raw("GRIDLLM_CHECKPOINT_DIR"), model)
    tiny = model.startswith("tiny")
    ps = 32 if tiny else 64
    n_requests = max(n_requests, 2)
    max_slots = max(max_slots, n_requests)
    # respect the MODEL context: tiny models cap at 256 tokens, and a
    # prompt past the effective context left-truncates (which would
    # silently shrink the long burst below eviction pressure)
    try:
        from gridllm_tpu.models.configs import get_config as _get_config

        model_ctx = _get_config(model).max_seq_len
    except KeyError:
        model_ctx = 8192
    slot_pages = 8 if tiny else 48
    ctx_cap = min(model_ctx, slot_pages * ps)
    prefix_len = min(prefix_len, ctx_cap - 2 * ps)
    long_cap = min(long_prompt_len, ctx_cap - n_tokens - 2)
    # pool sized so the N COLD streams fit but the long burst must evict
    # the reuse LRU: free-after-warm ≈ pool − shared prefix pages, while
    # the burst wants ≈ N × ctx_cap/ps pages. Page math is char≈token
    # exact for the byte tokenizer (tiny CI models); real tokenizers
    # over-estimate, so the record's eviction count is the honesty marker.
    prefix_pages = prefix_len // ps
    num_pages = n_requests * (prefix_pages + 1)

    async def one_arm(host_bytes: int) -> dict:
        engine = InferenceEngine(EngineConfig(
            model=model,
            checkpoint_path=ckpt,
            tokenizer=tok,
            max_slots=max_slots,
            page_size=ps,
            num_pages=num_pages,
            max_pages_per_slot=slot_pages,
            prefill_buckets=(256, 1024),
            prefill_chunk=64 if tiny else 256,
            kv_host_bytes=host_bytes,
            kv_spill_int8=False,  # raw spill: arms stay byte-comparable
        ))
        bus, registry, scheduler, app, worker = await _build_stack(
            engine, model, trace_capacity=n_requests * 8 + 16)
        client = None
        try:
            await worker.start()
            await asyncio.sleep(0.1)
            client = TestClient(TestServer(app))
            await client.start_server()

            shared = ("You are a meticulous assistant. Policy clause %d: "
                      "the quick brown fox jumps over the lazy dog. ")
            system = "".join(shared % i for i in range(100))[:prefix_len]

            # compile warmup: disjoint prefix, issued twice so the warm
            # path's programs (window seed + mid-prompt chunk) compile
            # outside every measured window; then a burst of long-shape
            # prompts that EVICTS the warmup prefix, and one final
            # re-issue so the tier-on arm's restore path (the kv_install
            # program) also compiles before any measured round
            warm_prompts = ["[warmup] " + system, "[warmup] " + system]
            warm_prompts += [("W%d " % j) + "X" * long_cap
                             for j in range(n_requests)]
            warm_prompts += ["[warmup] " + system]
            for ptxt in warm_prompts:
                warm_up = await client.post("/ollama/api/generate", json={
                    "model": model, "prompt": ptxt, "stream": False,
                    "options": {"temperature": 0, "num_predict": 2},
                }, timeout=aiohttp.ClientTimeout(total=240))
                assert warm_up.status == 200, await warm_up.text()

            async def one(i: int, prompt: str, ttfts: list,
                          tokens_out: list, n_pred: int) -> None:
                t0 = time.perf_counter()
                async with client.post("/ollama/api/generate", json={
                    "model": model, "prompt": prompt,
                    "options": {"temperature": 0, "seed": i,
                                "num_predict": n_pred},
                }) as resp:
                    assert resp.status == 200, await resp.text()
                    first = True
                    async for line in resp.content:
                        if not line.strip():
                            continue
                        if first:
                            first = False
                            ttfts.append(time.perf_counter() - t0)
                        frame = json.loads(line)
                        if frame.get("done"):
                            tokens_out[0] += frame.get("eval_count") or 0

            async def round_(prompts: list[str], n_pred: int) -> dict:
                await asyncio.sleep(0.5)  # drain trailing pipeline blocks
                ttfts: list[float] = []
                tokens_out = [0]
                t0 = time.perf_counter()
                await asyncio.gather(*(one(i, p, ttfts, tokens_out, n_pred)
                                       for i, p in enumerate(prompts)))
                wall = time.perf_counter() - t0
                return {"wall_s": wall, "tokens": tokens_out[0],
                        "tok_s": tokens_out[0] / wall,
                        "p50_ttft_ms": statistics.median(ttfts) * 1000}

            shared_prompts = [f"{system}\nUser {i} asks:"
                              for i in range(n_requests)]
            long_prompts = [("L%d " % i) + "X" * long_cap
                            for i in range(n_requests)]

            cold = await round_(shared_prompts, n_tokens)
            warm = await round_(shared_prompts, n_tokens)
            long_r = await round_(long_prompts, n_tokens)
            evict_mark = engine.alloc.evictions
            h0, m0 = engine.alloc.hits, engine.alloc.misses
            tier0 = (engine.host_tier.stats() if engine.host_tier
                     else {"restores": 0, "spills": 0, "misses": 0})
            post = await round_(shared_prompts, n_tokens)
            dh = engine.alloc.hits - h0
            dm = engine.alloc.misses - m0
            tier1 = (engine.host_tier.stats() if engine.host_tier
                     else {"restores": 0, "spills": 0, "misses": 0,
                           "evictions": 0, "pages": 0, "bytes": 0})
            return {
                "cold": cold, "warm": warm, "long": long_r, "post": post,
                "evictions": evict_mark,
                "post_hbm_hit_rate": round(dh / (dh + dm), 4)
                if (dh + dm) else 0.0,
                "post_restores": tier1["restores"] - tier0["restores"],
                "tier": tier1,
                "perf": _perf_sidecar(),
                "weights": ("real-checkpoint" if ckpt
                            else "random-weights synthetic"),
            }
        finally:
            await _teardown_stack(bus, registry, scheduler, worker,
                                  client=client)

    off = await one_arm(0)
    on = await one_arm(256 * 1024 * 1024)
    post_on = on["post"]["p50_ttft_ms"]
    post_off = off["post"]["p50_ttft_ms"]
    return {
        # headline: the tier-on arm's post-eviction round — warm TTFT
        # recovered under LRU-overflow pressure
        "tok_s": on["post"]["tok_s"],
        "tokens": sum(a[r]["tokens"] for a in (off, on)
                      for r in ("cold", "warm", "long", "post")),
        "wall_s": sum(a[r]["wall_s"] for a in (off, on)
                      for r in ("cold", "warm", "long", "post")),
        "p50_ttft_ms_cold": on["cold"]["p50_ttft_ms"],
        "p50_ttft_ms_warm": on["warm"]["p50_ttft_ms"],
        "p50_ttft_ms_post_on": post_on,
        "p50_ttft_ms_post_off": post_off,
        # ≥ 1 when the tier recovers TTFT the eviction storm destroyed
        "ttft_recovery": (post_off / post_on) if post_on else None,
        # the EFFECTIVE prefix actually measured (the model-context clamp
        # above can shrink the requested one) — the metric string must
        # state this, not the requested value
        "prefix_len": prefix_len,
        "restores": on["post_restores"],
        "kv_tier": {
            "on": {"evictions": on["evictions"],
                   "postHbmHitRate": on["post_hbm_hit_rate"],
                   "postRestores": on["post_restores"],
                   "spills": on["tier"]["spills"],
                   "hostPages": on["tier"]["pages"],
                   "hostBytes": on["tier"]["bytes"],
                   "tierMisses": on["tier"]["misses"]},
            "off": {"evictions": off["evictions"],
                    "postHbmHitRate": off["post_hbm_hit_rate"]},
        },
        "perf": on["perf"],
        "weights": on["weights"],
    }


async def run_shared_prefix_bench(model: str, n_requests: int,
                                  n_tokens: int, max_slots: int,
                                  prefix_len: int) -> dict:
    """Shared-prefix scenario (ISSUE 3): N streams share one long system
    prompt. Round 1 (cold) pays full prefill and populates the prefix
    cache; round 2 (warm) re-issues the same prompts and skips the cached
    prefix. Reports cold vs warm p50 TTFT and the warm round's prompt-page
    hit rate — the headline numbers for automatic prefix caching."""

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.worker.main import resolve_checkpoint

    ckpt, tok = resolve_checkpoint(
        env_raw("GRIDLLM_CHECKPOINT_DIR"), model
    )
    # Chunks sized so BOTH rounds run the chunked-prefill program and the
    # warm round's win is purely the skipped chunk invocations. The tiny
    # CPU models cap context at 256 tokens, so they need page-sized chunks
    # (and a tight page table — the jnp fallback of the prefix-chunk
    # attention gathers the FULL table row, so oversizing it would charge
    # both rounds dense-gather overhead the TPU kernel doesn't pay).
    tiny = model.startswith("tiny")
    # every stream gets a slot: if streams queued behind a full batch, the
    # later "cold" streams would admit AFTER earlier ones completed and
    # registered the shared prefix — silently warming the cold round
    max_slots = max(max_slots, n_requests)
    engine = InferenceEngine(EngineConfig(
        model=model,
        checkpoint_path=ckpt,
        tokenizer=tok,
        max_slots=max_slots,
        page_size=64,
        num_pages=max(384, max_slots * 64),
        max_pages_per_slot=8 if tiny else 48,
        prefill_buckets=(256, 1024),
        prefill_chunk=64 if tiny else 256,
    ))
    bus, registry, scheduler, app, worker = await _build_stack(
        engine, model, trace_capacity=n_requests * 4 + 16)
    client = None
    try:
        await worker.start()
        await asyncio.sleep(0.1)
        client = TestClient(TestServer(app))
        await client.start_server()

        shared = ("You are a meticulous assistant. Policy clause %d: the "
                  "quick brown fox jumps over the lazy dog. " )
        system = "".join(shared % i for i in range(100))[:prefix_len]

        # compile warmup with the same shapes but a DISJOINT prefix so
        # round 1 stays an honest cold measurement. Issued TWICE: the
        # second run matches the first's pages and compiles the warm-path
        # programs (window seed + mid-prompt chunk), so neither round pays
        # first-compile inside its measured window.
        for _ in range(2):
            warm_up = await client.post("/ollama/api/generate", json={
                "model": model, "prompt": "[warmup] " + system,
                "stream": False,
                "options": {"temperature": 0, "num_predict": 2},
            }, timeout=aiohttp.ClientTimeout(total=240))
            assert warm_up.status == 200, await warm_up.text()

        async def one(i: int, ttfts: list, tokens_out: list) -> None:
            t0 = time.perf_counter()
            async with client.post("/ollama/api/generate", json={
                "model": model, "prompt": f"{system}\nUser {i} asks:",
                "options": {"temperature": 0, "seed": i,
                            "num_predict": n_tokens},
            }) as resp:
                assert resp.status == 200, await resp.text()
                first = True
                async for line in resp.content:
                    if not line.strip():
                        continue
                    if first:
                        first = False
                        ttfts.append(time.perf_counter() - t0)
                    frame = json.loads(line)
                    if frame.get("done"):
                        tokens_out[0] += frame.get("eval_count") or 0

        async def round_(ttfts: list[float]) -> dict:
            # drain trailing pipeline blocks from the previous round — the
            # runner keeps dispatching for up to decode_block ×
            # pipeline_depth steps after the last stream resolves, and that
            # tail would otherwise bleed into this round's TTFTs
            await asyncio.sleep(0.5)
            tokens_out = [0]
            t0 = time.perf_counter()
            await asyncio.gather(*(one(i, ttfts, tokens_out)
                                   for i in range(n_requests)))
            wall = time.perf_counter() - t0
            return {"wall_s": wall, "tok_s": tokens_out[0] / wall,
                    "tokens": tokens_out[0]}

        ch0, cm0 = engine.alloc.hits, engine.alloc.misses
        cold_ttfts: list[float] = []
        cold = await round_(cold_ttfts)
        cdh = engine.alloc.hits - ch0
        cdm = engine.alloc.misses - cm0
        hits0, miss0 = engine.alloc.hits, engine.alloc.misses
        # several warm rounds: a single round of n_requests TTFTs is too
        # few samples for a stable p50 on a noisy host
        warm_ttfts: list[float] = []
        warm_rounds = [await round_(warm_ttfts) for _ in range(3)]
        warm = {
            "wall_s": sum(r["wall_s"] for r in warm_rounds),
            "tokens": sum(r["tokens"] for r in warm_rounds),
            "tok_s": statistics.median(r["tok_s"] for r in warm_rounds),
        }
        dh = engine.alloc.hits - hits0
        dm = engine.alloc.misses - miss0
        hit_rate = dh / (dh + dm) if (dh + dm) else 0.0
        # honesty check on the cold round: a nonzero cold hit rate means
        # the rounds are not independent (streams queued past the batch)
        cold_rate = cdh / (cdh + cdm) if (cdh + cdm) else 0.0
        cold["p50_ttft_ms"] = statistics.median(cold_ttfts) * 1000
        warm["p50_ttft_ms"] = statistics.median(warm_ttfts) * 1000
        warm_p95 = _p95(warm_ttfts)
        return {
            "p95_ttft_ms": warm_p95 * 1000 if warm_p95 is not None else None,
            "perf": _perf_sidecar(),
            "tok_s": warm["tok_s"],
            "tokens": cold["tokens"] + warm["tokens"],
            "wall_s": cold["wall_s"] + warm["wall_s"],
            "p50_ttft_ms_cold": cold["p50_ttft_ms"],
            "p50_ttft_ms_warm": warm["p50_ttft_ms"],
            "ttft_speedup": (cold["p50_ttft_ms"] / warm["p50_ttft_ms"]
                             if warm["p50_ttft_ms"] else None),
            "prefix_cache_hit_rate": round(hit_rate, 4),
            "prefix_cache_hit_rate_cold": round(cold_rate, 4),
            "prefix_cache": {"hits": engine.alloc.hits,
                             "misses": engine.alloc.misses,
                             "evictions": engine.alloc.evictions,
                             "cow_copies": engine.alloc.cow_copies},
            "weights": "real-checkpoint" if ckpt
            else "random-weights synthetic",
        }
    finally:
        await _teardown_stack(bus, registry, scheduler, worker,
                              client=client)


async def run_spec_bench(model: str, n_requests: int, n_tokens: int,
                         max_slots: int, spec_k: int) -> dict:
    """Speculative-decoding A/B/C (ISSUE 5 + 18): the SAME
    repetitive-completion workload three ways — speculation off, n-gram
    (prompt-lookup) drafting, and draft-model + token-tree drafting.
    Templated/repetitive output is the n-gram drafter's home turf — the
    workload asks for verbatim repetition and runs greedy with
    repeat_penalty disabled so repetition is not artificially damped.
    Each arm reports tok/s + ITL plus acceptance rate, emitted tokens
    per verify step (> 1 = speculation is paying for its verify
    overhead), and the drafter's own wall overhead per step. The
    draft-model arm uses GRIDLLM_SPEC_DRAFT_MODEL when set, else the
    target config itself (fresh-init tiny targets then draft with
    IDENTICAL weights — the acceptance ceiling, which is the point of
    the harness arm: it isolates tree/verify mechanics from draft-model
    quality)."""

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.worker.main import resolve_checkpoint

    ckpt, tok = resolve_checkpoint(
        env_raw("GRIDLLM_CHECKPOINT_DIR"), model
    )
    draft_name = env_raw("GRIDLLM_SPEC_DRAFT_MODEL") or model
    # tiny CPU models cap context at 256 byte-tokens — the prompt must
    # leave room for the measured decode or every stream dies at capacity
    reps = 2 if model.startswith("tiny") else 5
    prompt = ("Repeat the policy clause verbatim, forever: the quick brown "
              "fox jumps over the lazy dog; ") * reps
    opts = {"temperature": 0, "repeat_penalty": 1.0,
            "num_predict": n_tokens}

    async def arm(spec_on: bool, draft_model: str = "",
                  last: bool = False) -> dict:
        engine = InferenceEngine(EngineConfig(
            model=model, checkpoint_path=ckpt, tokenizer=tok,
            max_slots=max_slots, page_size=64,
            num_pages=max(256, max_slots * 48), max_pages_per_slot=48,
            prefill_buckets=(256, 1024),
            spec_decode=spec_on, spec_k=spec_k,
            draft_model=draft_model,
        ))
        bus, registry, scheduler, app, worker = await _build_stack(
            engine, model)
        client = None
        try:
            await worker.start()
            await asyncio.sleep(0.1)
            client = TestClient(TestServer(app))
            await client.start_server()
            warm = await client.post("/ollama/api/generate", json={
                "model": model, "prompt": prompt, "stream": False,
                "options": {**opts, "num_predict": 4},
            }, timeout=aiohttp.ClientTimeout(total=240))
            assert warm.status == 200, await warm.text()
            s0 = dict(engine.spec_stats)
            ttfts: list[float] = []
            itls: list[float] = []
            tokens_out = [0]

            async def one(i: int) -> None:
                t0 = time.perf_counter()
                t_first = t_last = None
                async with client.post("/ollama/api/generate", json={
                    "model": model, "prompt": f"[{i}] {prompt}",
                    "options": dict(opts),
                }) as resp:
                    assert resp.status == 200, await resp.text()
                    async for line in resp.content:
                        if not line.strip():
                            continue
                        now = time.perf_counter()
                        if t_first is None:
                            t_first = now
                            ttfts.append(now - t0)
                        t_last = now
                        frame = json.loads(line)
                        if frame.get("done"):
                            n = frame.get("eval_count") or 0
                            tokens_out[0] += n
                            if n > 1 and t_first is not None:
                                itls.append(
                                    (t_last - t_first) / (n - 1) * 1000)

            t0 = time.perf_counter()
            await asyncio.gather(*(one(i) for i in range(n_requests)))
            wall = time.perf_counter() - t0
            st = engine.spec_stats
            d = {k: st[k] - s0[k] for k in st}
            out = {
                "tok_s": tokens_out[0] / wall,
                "p50_ttft_ms": statistics.median(ttfts) * 1000,
                "p50_itl_ms": statistics.median(itls) if itls else None,
                "tokens": tokens_out[0],
                "wall_s": wall,
                "spec": d,
            }
            out["drafter"] = (engine.batch_state().get("specDecode") or
                              {}).get("drafter", "off")
            if last:
                # the final arm is the LAST engine alive — read the perf
                # sidecar (recompiles across ALL arms, peak HBM) here
                out["perf"] = _perf_sidecar()
            return out
        finally:
            await _teardown_stack(bus, registry, scheduler, worker,
                                  client=client)

    def derived(a: dict) -> dict:
        spec = a["spec"]
        steps = spec["steps"]
        return {
            "drafter": a["drafter"],
            "tok_s": round(a["tok_s"], 2),
            "p50_ttft_ms": round(a["p50_ttft_ms"], 2),
            "p50_itl_ms": (round(a["p50_itl_ms"], 2)
                           if a["p50_itl_ms"] is not None else None),
            "acceptance_rate": round(
                spec["accepted"] / spec["proposed"], 4)
            if spec["proposed"] else 0.0,
            "tokens_per_step": round(spec["emitted"] / steps, 4)
            if steps else 0.0,
            "draft_overhead_ms_per_step": round(
                spec.get("draft_ns", 0) / steps / 1e6, 3) if steps else 0.0,
            "steps": steps,
            "proposed": spec["proposed"],
            "accepted": spec["accepted"],
        }

    off = await arm(False)
    ng = await arm(True)
    md = await arm(True, draft_name, last=True)
    arms = {"off": derived(off), "ngram": derived(ng),
            "model": derived(md)}
    return {
        # headline keys = the draft-model tree arm (the ISSUE-18 path);
        # the per-arm breakdown lives under "arms". ITL is reported per
        # arm but deliberately NOT exposed under the gated top-level
        # keys: on tiny CPU runs ITL is scheduler noise — the honest
        # regression gates for speculation are acceptance rate and
        # tokens per verify step.
        "tok_s": md["tok_s"],
        "tok_s_spec_off": off["tok_s"],
        "p50_ttft_ms": md["p50_ttft_ms"],
        "spec_acceptance_rate": arms["model"]["acceptance_rate"],
        "spec_tokens_per_step": arms["model"]["tokens_per_step"],
        "spec_acceptance_rate_ngram": arms["ngram"]["acceptance_rate"],
        "spec_tokens_per_step_ngram": arms["ngram"]["tokens_per_step"],
        "spec_steps": arms["model"]["steps"],
        "spec_proposed": arms["model"]["proposed"],
        "spec_accepted": arms["model"]["accepted"],
        "arms": arms,
        "tokens": off["tokens"] + ng["tokens"] + md["tokens"],
        "wall_s": off["wall_s"] + ng["wall_s"] + md["wall_s"],
        "perf": md.get("perf"),
        "weights": "real-checkpoint" if ckpt
        else "random-weights synthetic",
    }


async def run_mixed_bench(model: str, n_requests: int, n_tokens: int,
                          max_slots: int, long_prompt_len: int) -> dict:
    """Mixed-workload scenario (ISSUE 6): decode-heavy streams running
    CONCURRENTLY with long chunked prefills — the traffic shape the
    unified ragged paged-attention kernel exists for. Half the load is
    short-prompt/long-decode streams (ITL is their number), half is
    long-prompt/short-decode requests arriving while the others are
    mid-generation (TTFT is theirs). Under the ragged engine each prefill
    chunk and the running decodes share one launch, so the decode arm's
    ITL should NOT degrade while prefills churn; `--compare` gates both
    p50 ITL and p50 TTFT (plus tok/s) against a previous record."""

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.worker.main import resolve_checkpoint

    ckpt, tok = resolve_checkpoint(
        env_raw("GRIDLLM_CHECKPOINT_DIR"), model
    )
    tiny = model.startswith("tiny")
    engine = InferenceEngine(EngineConfig(
        model=model,
        checkpoint_path=ckpt,
        tokenizer=tok,
        max_slots=max_slots,
        page_size=64,
        num_pages=max(384, max_slots * 64),
        max_pages_per_slot=8 if tiny else 48,
        prefill_buckets=(64, 256, 1024),
        # long prompts MUST take the chunked path — that is the mixed
        # step under test (tiny CPU models cap context at 512 tokens)
        prefill_chunk=64 if tiny else 512,
    ))
    bus, registry, scheduler, app, worker = await _build_stack(
        engine, model, trace_capacity=n_requests * 4 + 16)
    client = None
    try:
        await worker.start()
        await asyncio.sleep(0.1)
        client = TestClient(TestServer(app))
        await client.start_server()

        filler = "the quick brown fox jumps over the lazy dog; "
        long_prompt = (filler * 200)[:long_prompt_len]
        short_prompt = "summarize: " + filler

        # warmup compiles every program both arms need: a long (chunked)
        # prefill AND a short (bucketed) one, plus decode. The warmup
        # prompts use the SAME "[X0] " tag shape as the measured ones so
        # they land in the same prefill buckets — a one-character length
        # difference can cross a bucket edge and put a first-compile
        # inside the measured window
        for p in (f"[W0] {long_prompt}", f"[W0] {short_prompt}"):
            warm = await client.post("/ollama/api/generate", json={
                "model": model, "prompt": p, "stream": False,
                "options": {"temperature": 0, "num_predict": 4},
            }, timeout=aiohttp.ClientTimeout(total=240))
            assert warm.status == 200, await warm.text()

        decode_ttfts: list[float] = []
        decode_itls: list[float] = []
        prefill_ttfts: list[float] = []
        tokens_out = [0]

        async def one(prompt: str, n_predict: int, ttfts: list,
                      itls: list | None, tag: str, i: int) -> None:
            t0 = time.perf_counter()
            t_first = t_last = None
            async with client.post("/ollama/api/generate", json={
                "model": model, "prompt": f"[{tag}{i}] {prompt}",
                "options": {"temperature": 0, "seed": i,
                            "num_predict": n_predict},
            }) as resp:
                assert resp.status == 200, await resp.text()
                async for line in resp.content:
                    if not line.strip():
                        continue
                    now = time.perf_counter()
                    if t_first is None:
                        t_first = now
                        ttfts.append(now - t0)
                    t_last = now
                    frame = json.loads(line)
                    if frame.get("done"):
                        n = frame.get("eval_count") or 0
                        tokens_out[0] += n
                        if itls is not None and n > 1 and t_first is not None:
                            itls.append((t_last - t_first) / (n - 1) * 1000)

        async def long_arm(i: int) -> None:
            # arrive mid-decode: the prefill chunks must share steps with
            # running streams, not an idle engine
            await asyncio.sleep(0.2 * (i + 1))
            await one(long_prompt, 4, prefill_ttfts, None, "L", i)

        # main() clamps --mixed to >= 2 requests, so both arms get >= 1
        # stream and the total matches the record's request count
        n_decode = max(n_requests // 2, 1)
        n_long = max(n_requests - n_decode, 1)
        t0 = time.perf_counter()
        await asyncio.gather(
            *(one(short_prompt, n_tokens, decode_ttfts, decode_itls,
                  "D", i) for i in range(n_decode)),
            *(long_arm(i) for i in range(n_long)),
        )
        wall = time.perf_counter() - t0
        return {
            "tok_s": tokens_out[0] / wall,
            "p50_ttft_ms": (statistics.median(prefill_ttfts) * 1000
                            if prefill_ttfts else None),
            "p50_itl_ms": (statistics.median(decode_itls)
                           if decode_itls else None),
            "p95_ttft_ms": (None if _p95(prefill_ttfts) is None
                            else _p95(prefill_ttfts) * 1000),
            "tokens": tokens_out[0],
            "wall_s": wall,
            "mixed": {
                "decode_streams": n_decode,
                "long_prefills": n_long,
                "long_prompt_chars": len(long_prompt),
                "p50_decode_ttft_ms": (
                    statistics.median(decode_ttfts) * 1000
                    if decode_ttfts else None),
            },
            "perf": _perf_sidecar(),
            "weights": "real-checkpoint" if ckpt
            else "random-weights synthetic",
        }
    finally:
        await _teardown_stack(bus, registry, scheduler, worker,
                              client=client)


async def run_disagg_bench(model: str, n_requests: int, n_tokens: int,
                           max_slots: int, long_prompt_len: int) -> dict:
    """Disaggregated-serving A/B (ISSUE 7): the same mixed workload
    (decode-heavy streams + long prefills arriving mid-generation) served
    by (a) ONE unified worker and (b) a prefill worker + a decode worker
    with KV-page migration between them. The headline: the split arm's
    decode-pool ITL under mixed load — long prefills run on the prefill
    worker, so they stop inflating the decode pool's inter-token latency
    — plus migration volume/latency from the transfer layer's metrics.
    Measured at the scheduler boundary (submit_streaming_job) so both
    arms pay identical harness overhead."""

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.transfer.migrate import (
        _MIG_BYTES,
        _MIG_SECONDS,
        _MIGRATIONS,
    )
    from gridllm_tpu.utils.config import SchedulerConfig, WorkerConfig
    from gridllm_tpu.utils.types import InferenceRequest
    from gridllm_tpu.worker.main import resolve_checkpoint
    from gridllm_tpu.worker.service import WorkerService

    ckpt, tok = resolve_checkpoint(
        env_raw("GRIDLLM_CHECKPOINT_DIR"), model
    )
    tiny = model.startswith("tiny")

    def make_engine() -> InferenceEngine:
        return InferenceEngine(EngineConfig(
            model=model,
            checkpoint_path=ckpt,
            tokenizer=tok,
            max_slots=max_slots,
            page_size=64,
            num_pages=max(384, max_slots * 64),
            max_pages_per_slot=8 if tiny else 48,
            prefill_buckets=(64, 256, 1024),
            prefill_chunk=64 if tiny else 512,
        ))

    filler = "the quick brown fox jumps over the lazy dog; "
    long_prompt = (filler * 200)[:long_prompt_len]
    # the short prompt must span >1 KV page (64 TOKENS) or there is no
    # full-page prefix to migrate and every decode stream falls back —
    # sized against the engines' ACTUAL tokenizer (byte-level for tiny
    # models, HF for real checkpoints), not in characters
    from gridllm_tpu.engine.tokenizer import get_tokenizer
    from gridllm_tpu.models.configs import get_config

    try:
        vocab = get_config(model).vocab_size
    except KeyError:
        vocab = 32000
    probe_tok = get_tokenizer(tok, vocab)
    short_prompt = "summarize: " + filler
    while len(probe_tok.encode(short_prompt, add_bos=True)) < 80:
        short_prompt += filler

    async def run_arm(roles: list[str]) -> dict:
        bus = InMemoryBus()
        await bus.connect()
        cfg = SchedulerConfig()
        registry = WorkerRegistry(bus, cfg)
        scheduler = JobScheduler(bus, registry, cfg)
        await registry.initialize()
        await scheduler.initialize()
        workers: list[WorkerService] = []
        for i, role in enumerate(roles):
            svc = WorkerService(
                bus, {model: make_engine()},
                WorkerConfig(worker_id=f"bench-{role}-{i}", role=role,
                             heartbeat_interval_ms=250),
                stream_flush_ms=5)
            await svc.start()
            workers.append(svc)
        await asyncio.sleep(0.4)  # first heartbeats (roles/headroom) land
        try:
            tokens_out = [0]

            async def one(prompt: str, n_predict: int, ttfts: list,
                          itls: list | None, tag: str, i: int) -> None:
                t0 = time.perf_counter()
                marks: list[float] = []

                async def on_chunk(_c) -> None:
                    marks.append(time.perf_counter())

                req = InferenceRequest(
                    id=f"bench-{tag}{i}-{uuid.uuid4().hex[:6]}",
                    model=model, prompt=f"[{tag}{i}] {prompt}", stream=True,
                    options={"temperature": 0, "seed": i,
                             "num_predict": n_predict},
                    metadata={"requestType": "inference"})
                res = await scheduler.submit_streaming_job(
                    req, on_chunk, timeout_ms=240_000)
                assert res.success, res.error
                n = int(res.response.eval_count or 0)
                tokens_out[0] += n
                if marks:
                    ttfts.append(marks[0] - t0)
                    if itls is not None and n > 1:
                        itls.append((marks[-1] - marks[0]) / (n - 1) * 1000)

            # warmup compiles every program both arms need — long
            # (chunked) and short (bucketed) prefills, decode, and on the
            # split arm the whole export→wire→import→warm-resume chain —
            # run TWICE so warm-path programs exist before measurement
            for w in range(2):
                await one(long_prompt, 4, [], None, "W", w)
                await one(short_prompt, 4, [], None, "W", w + 10)
            tokens_out[0] = 0  # warmup tokens must not inflate tok/s

            mig0 = _MIGRATIONS.value(side="send", outcome="ok")
            bytes0, secs0 = _MIG_BYTES.sum(), _MIG_SECONDS.sum()
            count0 = _MIG_BYTES.count()
            handoff0 = scheduler._disagg_total.value(event="handoff")
            fallback0 = scheduler._disagg_total.value(event="fallback")

            decode_ttfts: list[float] = []
            decode_itls: list[float] = []
            prefill_ttfts: list[float] = []
            n_decode = max(n_requests // 2, 1)
            n_long = max(n_requests - n_decode, 1)

            async def long_arm(i: int) -> None:
                # arrive mid-decode: prefill load lands while the decode
                # streams are generating — the interference under test
                await asyncio.sleep(0.2 * (i + 1))
                await one(long_prompt, 4, prefill_ttfts, None, "L", i)

            t0 = time.perf_counter()
            await asyncio.gather(
                *(one(short_prompt, n_tokens, decode_ttfts, decode_itls,
                      "D", i) for i in range(n_decode)),
                *(long_arm(i) for i in range(n_long)),
            )
            wall = time.perf_counter() - t0
            n_mig = int(_MIG_BYTES.count() - count0)
            steady = sum(
                p["steadyRecompiles"]
                for svc in workers
                for p in svc.engines[model].perf.state().values())
            return {
                "roles": roles,
                "tok_s": tokens_out[0] / wall,
                "tokens": tokens_out[0],
                "wall_s": wall,
                "p50_itl_ms": (statistics.median(decode_itls)
                               if decode_itls else None),
                "p95_itl_ms": _p95(decode_itls),
                "p50_ttft_ms": (statistics.median(prefill_ttfts) * 1000
                                if prefill_ttfts else None),
                "p95_ttft_ms": (None if _p95(prefill_ttfts) is None
                                else _p95(prefill_ttfts) * 1000),
                "p50_decode_ttft_ms": (
                    statistics.median(decode_ttfts) * 1000
                    if decode_ttfts else None),
                "recompiles_steady": steady,
                "migrations": {
                    "count": n_mig,
                    "ok": int(_MIGRATIONS.value(side="send", outcome="ok")
                              - mig0),
                    "bytes": int(_MIG_BYTES.sum() - bytes0),
                    "avg_ms": (round((_MIG_SECONDS.sum() - secs0)
                                     / n_mig * 1000, 2) if n_mig else None),
                    # deltas over the measured window, like count/bytes
                    # (warmups migrate too and must not skew the record)
                    "handoffs": int(scheduler._disagg_total.value(
                        event="handoff") - handoff0),
                    "fallbacks": int(scheduler._disagg_total.value(
                        event="fallback") - fallback0),
                },
            }
        finally:
            for svc in workers:
                try:
                    await svc.stop(announce=False)
                except Exception:  # noqa: BLE001
                    pass
            try:
                await scheduler.shutdown()
                await registry.shutdown()
                await bus.disconnect()
            except Exception:  # noqa: BLE001
                pass

    unified = await run_arm(["unified"])
    split = await run_arm(["prefill", "decode"])
    return {
        # headline = the split arm (what --compare gates release over
        # release); the unified arm rides in the payload for the A/B read
        "tok_s": split["tok_s"],
        "tokens": split["tokens"],
        "wall_s": unified["wall_s"] + split["wall_s"],
        "p50_itl_ms": split["p50_itl_ms"],
        "p50_ttft_ms": split["p50_ttft_ms"],
        "p95_ttft_ms": split["p95_ttft_ms"],
        "disagg": {"unified": unified, "split": split},
        "perf": _perf_sidecar(),
        "weights": "real-checkpoint" if ckpt
        else "random-weights synthetic",
    }


async def run_fleet_bench(model: str, n_requests: int, n_tokens: int,
                          max_slots: int, prompt_len: int) -> dict:
    """Scaled-control-plane A/B (ISSUE 15): the same mixed stream load
    served by (a) the single-box control plane — one in-process
    scheduler+gateway — and (b) a 2-gateway/2-shard control plane
    (GatewaySubmitter replicas publishing over ctrl:submit to
    SchedulerShard partition owners) on the same bus, one unified worker
    per arm. The headline: control-plane overhead under fan-out — tok/s
    and p50 TTFT through the scaled plane vs the local one — plus the
    shard dispatch split and lease transitions proving both partitions
    actually carried load. Measured at the submit boundary so both arms
    pay identical harness overhead."""

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.controlplane.client import GatewaySubmitter
    from gridllm_tpu.controlplane.partition import shard_of
    from gridllm_tpu.controlplane.shard import (
        SchedulerShard,
        wait_for_ownership,
    )
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import (
        ControlPlaneConfig,
        SchedulerConfig,
        WorkerConfig,
    )
    from gridllm_tpu.utils.types import InferenceRequest
    from gridllm_tpu.worker.main import resolve_checkpoint
    from gridllm_tpu.worker.service import WorkerService

    ckpt, tok = resolve_checkpoint(
        env_raw("GRIDLLM_CHECKPOINT_DIR"), model
    )
    tiny = model.startswith("tiny")

    def make_engine() -> InferenceEngine:
        return InferenceEngine(EngineConfig(
            model=model,
            checkpoint_path=ckpt,
            tokenizer=tok,
            max_slots=max_slots,
            page_size=64,
            num_pages=max(384, max_slots * 64),
            max_pages_per_slot=8 if tiny else 48,
            prefill_buckets=(64, 256, 1024),
        ))

    prompt = ("the quick brown fox jumps over the lazy dog; "
              * (prompt_len // 10 + 1))[:max(prompt_len, 40)]
    num_shards = 2

    def id_for_shard(tag: str, i: int, idx: int) -> str:
        # deterministic spread: both partitions must carry real load or
        # the scaled arm silently degrades to a 1-shard measurement
        while True:
            jid = f"bench-{tag}{i}-{uuid.uuid4().hex[:6]}"
            if shard_of(jid, num_shards) == idx:
                return jid

    async def run_arm(scaled: bool) -> dict:
        bus = InMemoryBus()
        await bus.connect()
        cfg = SchedulerConfig()
        shards: list[SchedulerShard] = []
        registries: list[WorkerRegistry] = []
        submitters: list = []
        local_sched: JobScheduler | None = None
        if scaled:
            for i in range(num_shards):
                reg = WorkerRegistry(bus, cfg)
                sh = SchedulerShard(
                    bus, reg, cfg,
                    ControlPlaneConfig(num_shards=num_shards, shard_id=i,
                                       lease_ttl_ms=2000,
                                       renew_interval_ms=300),
                    member_id=f"bench-shard-{i}", settle_s=0.01)
                await reg.initialize()
                await sh.start()
                registries.append(reg)
                shards.append(sh)
            assert await wait_for_ownership(shards, num_shards)
            for i in range(2):
                reg = WorkerRegistry(bus, cfg, observer=True)
                gw = GatewaySubmitter(bus, reg, cfg,
                                      member_id=f"bench-gw-{i}")
                await reg.initialize()
                await gw.initialize()
                registries.append(reg)
                submitters.append(gw)
        else:
            reg = WorkerRegistry(bus, cfg)
            local_sched = JobScheduler(bus, reg, cfg)
            await reg.initialize()
            await local_sched.initialize()
            registries.append(reg)
            submitters.append(local_sched)
        svc = WorkerService(bus, {model: make_engine()},
                            WorkerConfig(worker_id="bench-fleet-w0",
                                         heartbeat_interval_ms=250),
                            stream_flush_ms=5)
        await svc.start()
        await asyncio.sleep(0.4)  # registrations land on every registry
        try:
            tokens_out = [0]

            async def one(i: int, jid: str, ttfts: list,
                          itls: list | None) -> None:
                sub = submitters[i % len(submitters)]
                t0 = time.perf_counter()
                marks: list[float] = []

                async def on_chunk(_c) -> None:
                    marks.append(time.perf_counter())

                req = InferenceRequest(
                    id=jid, model=model, prompt=f"[{i}] {prompt}",
                    stream=True,
                    options={"temperature": 0, "seed": i,
                             "num_predict": n_tokens},
                    metadata={"requestType": "inference"})
                res = await sub.submit_streaming_job(req, on_chunk,
                                                     timeout_ms=240_000)
                assert res.success, res.error
                n = int(res.response.eval_count or 0)
                tokens_out[0] += n
                if marks:
                    ttfts.append(marks[0] - t0)
                    if itls is not None and n > 1:
                        itls.append((marks[-1] - marks[0]) / (n - 1) * 1000)

            for w in range(2):  # warmup compiles; spread over partitions
                await one(w, id_for_shard("W", w, w % num_shards), [],
                          None)
            tokens_out[0] = 0

            ttfts: list[float] = []
            itls: list[float] = []
            jids = [id_for_shard("R", i, i % num_shards)
                    for i in range(n_requests)]
            t0 = time.perf_counter()
            await asyncio.gather(*(one(i, jid, ttfts, itls)
                                   for i, jid in enumerate(jids)))
            wall = time.perf_counter() - t0
            steady = sum(
                p["steadyRecompiles"]
                for p in svc.engines[model].perf.state().values())
            arm = {
                "plane": "2x2" if scaled else "1x1",
                "tok_s": tokens_out[0] / wall,
                "tokens": tokens_out[0],
                "wall_s": wall,
                "p50_ttft_ms": (statistics.median(ttfts) * 1000
                                if ttfts else None),
                "p95_ttft_ms": (None if _p95(ttfts) is None
                                else _p95(ttfts) * 1000),
                "p50_itl_ms": (statistics.median(itls)
                               if itls else None),
                "recompiles_steady": steady,
            }
            if scaled:
                arm["shard_dispatched"] = [
                    int(sh.scheduler._jobs_total.value(event="dispatched"))
                    for sh in shards]
                arm["lease_transitions"] = {
                    ev: int(sum(sh.lease._transitions.value(event=ev)
                                for sh in shards))
                    for ev in ("acquired", "adopted", "deposed",
                               "expired")}
                arm["fenced_ops"] = int(sum(
                    sh.scheduler._shard_fenced.value(op=op)
                    for sh in shards
                    for op in ("assign", "timeout", "orphan", "failure",
                               "cancel", "drain", "preempt")))
            return arm
        finally:
            try:
                await svc.stop(announce=False)
            except Exception:  # noqa: BLE001
                pass
            for gw in (s for s in submitters if s is not local_sched):
                try:
                    await gw.shutdown()
                except Exception:  # noqa: BLE001
                    pass
            for sh in shards:
                try:
                    await sh.stop()
                except Exception:  # noqa: BLE001
                    pass
            try:
                if local_sched is not None:
                    await local_sched.shutdown()
                for reg in registries:
                    await reg.shutdown()
                await bus.disconnect()
            except Exception:  # noqa: BLE001
                pass

    local = await run_arm(scaled=False)
    scaled = await run_arm(scaled=True)
    return {
        # headline = the scaled plane (what --compare gates); the local
        # arm rides in the payload for the A/B read
        "tok_s": scaled["tok_s"],
        "tokens": scaled["tokens"],
        "wall_s": local["wall_s"] + scaled["wall_s"],
        "p50_ttft_ms": scaled["p50_ttft_ms"],
        "p95_ttft_ms": scaled["p95_ttft_ms"],
        "p50_itl_ms": scaled["p50_itl_ms"],
        "fleet": {"local": local, "scaled": scaled},
        "perf": _perf_sidecar(),
        "weights": "real-checkpoint" if ckpt
        else "random-weights synthetic",
    }


async def run_swap_bench(model: str, n_requests: int, n_tokens: int,
                         max_slots: int) -> dict:
    """Elastic-serving scenario (ISSUE 20), two parts.

    Part A — cold-start TTFT, three arms at the engine boundary (wall
    time from construction start to a first greedy token):

    - ``cold``: fresh persistent compile-cache dir, no weight snapshot —
      the full price (XLA compiles + weight materialization);
    - ``compile_warm``: same cache dir (now populated), weights still
      re-materialized from disk/init — what a NEW checkpoint pays on a
      warmed host;
    - ``snapshot_warm``: compile cache AND host-RAM weight snapshot hit
      — the swap-in hot path. The headline gate: snapshot-warm must be
      ≥ 3× faster than fully cold.

    Runs FIRST in the process so the cold arm's compiles are honest.

    Part B — bursty two-model traffic through the full stack: bursts of
    model A, then B, then A again, with idle gaps past the idle TTL. The
    elastic arm (placement controller on, one worker with an engine
    factory) must serve every request — A scales to zero while idle, B
    is swapped in on demand, queued-not-rejected. The static arm (model
    A pinned, no elasticity) cannot serve B: those submissions time out,
    the counter-factual the acceptance criterion names."""

    import os as _os
    import tempfile

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.engine import engine as engine_mod
    from gridllm_tpu.engine import loader
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import SchedulerConfig, WorkerConfig
    from gridllm_tpu.utils.types import InferenceRequest
    from gridllm_tpu.worker.main import resolve_checkpoint
    from gridllm_tpu.worker.service import WorkerService

    tiny = model.startswith("tiny")
    model_b = "tiny-qwen2" if tiny else "llama3.2:1b"

    cache_dir = tempfile.mkdtemp(prefix="gridllm-swap-xla-")
    _os.environ["GRIDLLM_COMPILE_CACHE_DIR"] = cache_dir
    _os.environ["GRIDLLM_WEIGHT_SNAPSHOT_BYTES"] = str(4 << 30)
    # fresh reads of both knobs even if something touched them earlier
    engine_mod._compile_cache_dir = None
    loader.reset_weight_snapshot_tier()

    def make_engine(name: str) -> InferenceEngine:
        ckpt, tok = resolve_checkpoint(env_raw("GRIDLLM_CHECKPOINT_DIR"),
                                       name)
        return InferenceEngine(EngineConfig(
            model=name,
            checkpoint_path=ckpt,
            tokenizer=tok,
            max_slots=max_slots,
            page_size=64,
            num_pages=max(384, max_slots * 64),
            max_pages_per_slot=8 if tiny else 48,
            prefill_buckets=(64, 256, 1024),
        ))

    # ---- Part A: cold-start TTFT arms --------------------------------
    from gridllm_tpu.engine.engine import GenerationRequest

    def cold_start_arm() -> tuple[float, str, InferenceEngine]:
        """(seconds to first greedy token from construction, text,
        engine) — the cold-start unit every arm measures identically."""
        marks: list[float] = []

        def on_chunk(_d: str, _done: bool, _r) -> None:
            if not marks:
                marks.append(time.perf_counter())

        t0 = time.perf_counter()
        eng = make_engine(model)
        res = eng.generate(GenerationRequest(
            id=f"swapbench-{uuid.uuid4().hex[:6]}",
            prompt="the quick brown fox",
            options={"temperature": 0, "seed": 0,
                     "num_predict": max(n_tokens, 4)},
            on_chunk=on_chunk,
        ))
        ttft = (marks[0] if marks else time.perf_counter()) - t0
        return ttft, res.text, eng

    cold_s, cold_text, eng1 = cold_start_arm()
    assert eng1.load_source in ("checkpoint", "init"), eng1.load_source
    eng1.params = None  # release before the next arm materializes
    warm_s, warm_text, eng2 = cold_start_arm()
    eng2.park_weights()
    snap_s, snap_text, eng3 = cold_start_arm()
    snapshot_hit = eng3.load_source == "snapshot"
    eng3.params = None
    tier_stats = loader.weight_snapshot_tier().stats()

    # ---- Part B: bursty two-model elastic vs static ------------------
    idle_ttl_ms = 500

    async def run_arm(elastic: bool) -> dict:
        _os.environ["GRIDLLM_PLACEMENT_INTERVAL_MS"] = (
            "100" if elastic else "0")
        _os.environ["GRIDLLM_MODEL_IDLE_TTL_MS"] = str(idle_ttl_ms)
        _os.environ["GRIDLLM_SWAP_COOLDOWN_MS"] = "100"
        # short demand half-life so the arrival-rate EWMA decays below
        # the idle epsilon within the bench's idle gap (default 60s
        # would hold models "busy" for minutes after a burst)
        _os.environ["GRIDLLM_CAPACITY_EWMA_HALFLIFE_S"] = "0.2"
        bus = InMemoryBus()
        await bus.connect()
        cfg = SchedulerConfig()
        reg = WorkerRegistry(bus, cfg)
        sched = JobScheduler(bus, reg, cfg)
        await reg.initialize()
        await sched.initialize()
        svc = WorkerService(
            bus, {model: make_engine(model)},
            WorkerConfig(worker_id=f"bench-swap-{'el' if elastic else 'st'}",
                         heartbeat_interval_ms=150),
            stream_flush_ms=5,
            engine_factory=(make_engine if elastic else None))
        await svc.start()
        await asyncio.sleep(0.4)
        served = [0]
        rejected = [0]
        b_ttfts: list[float] = []

        async def one(name: str, i: int, timeout_ms: int) -> None:
            t0 = time.perf_counter()
            marks: list[float] = []

            async def on_chunk(_c) -> None:
                marks.append(time.perf_counter())

            try:
                res = await sched.submit_streaming_job(InferenceRequest(
                    id=f"swap-{'el' if elastic else 'st'}-{name}-{i}-"
                       f"{uuid.uuid4().hex[:6]}",
                    model=name, prompt=f"[{i}] the quick brown fox",
                    stream=True,
                    options={"temperature": 0, "seed": i,
                             "num_predict": n_tokens},
                    metadata={"requestType": "inference"},
                ), on_chunk, timeout_ms=timeout_ms)
            except Exception:  # noqa: BLE001 — timeout = rejected (the
                rejected[0] += 1  # static arm's expected counter-factual)
                return
            if res.success:
                served[0] += 1
                if name == model_b and marks:
                    b_ttfts.append(marks[0] - t0)
            else:
                rejected[0] += 1

        arm: dict = {"mode": "elastic" if elastic else "static"}
        try:
            # burst 1: model A (resident everywhere)
            await asyncio.gather(*(one(model, i, 240_000)
                                   for i in range(n_requests)))
            # idle past the TTL; the elastic arm scales A to zero
            a_zero = False
            if elastic:
                deadline = time.perf_counter() + (idle_ttl_ms / 1000.0 + 8.0)
                while time.perf_counter() < deadline:
                    await asyncio.sleep(0.1)
                    if not reg.get_workers_with_model(model):
                        a_zero = True
                        break
            else:
                await asyncio.sleep(idle_ttl_ms / 1000.0 + 0.5)
            arm["a_scaled_to_zero"] = a_zero
            # burst 2: model B — swap-in on demand (elastic) / timeout
            # (static: nothing can ever serve it, 25s cap per request)
            await asyncio.gather(*(one(model_b, i,
                                       240_000 if elastic else 25_000)
                                   for i in range(n_requests)))
            # burst 3: model A again — reload from the weight snapshot
            await asyncio.gather(*(one(model, i,
                                       240_000 if elastic else 25_000)
                                   for i in range(n_requests)))
            arm["served"] = served[0]
            arm["rejected"] = rejected[0]
            arm["p50_b_swapin_ttft_ms"] = (
                statistics.median(b_ttfts) * 1000 if b_ttfts else None)
            if elastic:
                p = sched.placement
                arm["swaps"] = {
                    f"{op}_{oc}": int(p._swaps.value(op=op, outcome=oc))
                    for op in ("load", "unload")
                    for oc in ("ok", "declined", "error", "timeout")
                    if p._swaps.value(op=op, outcome=oc)}
            return arm
        finally:
            try:
                await svc.stop(announce=False)
            except Exception:  # noqa: BLE001
                pass
            try:
                await sched.shutdown()
                await reg.shutdown()
                await bus.disconnect()
            except Exception:  # noqa: BLE001
                pass
            _os.environ["GRIDLLM_PLACEMENT_INTERVAL_MS"] = "0"

    t0 = time.perf_counter()
    elastic = await run_arm(elastic=True)
    static = await run_arm(elastic=False)
    wall = time.perf_counter() - t0

    return {
        "cold_ttft_ms": cold_s * 1000,
        "compile_warm_ttft_ms": warm_s * 1000,
        "snapshot_warm_ttft_ms": snap_s * 1000,
        "cold_start_speedup": cold_s / snap_s if snap_s > 0 else None,
        "snapshot_hit": snapshot_hit,
        "cold_texts_identical": cold_text == warm_text == snap_text,
        "snapshot_tier": tier_stats,
        "compile_cache_dir_entries": sum(
            len(files) for _, _, files in _os.walk(cache_dir)),
        "bursty": {"elastic": elastic, "static": static,
                   "model_a": model, "model_b": model_b,
                   "requests_per_burst": n_requests},
        "wall_s": wall,
        "perf": _perf_sidecar(),
        "weights": "random-weights synthetic" if tiny
        else "checkpoint-or-init",
    }


async def run_embed_bench(model: str, n_requests: int,
                          batch: int = 64, rounds: int = 8) -> dict:
    """Embeddings QPS through the full stack (BASELINE config #5):
    n_requests concurrent /ollama/api/embed calls, each carrying `batch`
    texts, repeated `rounds` times after a warmup."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.engine import EngineConfig, InferenceEngine

    engine = InferenceEngine(EngineConfig(
        model=model, max_slots=1, prefill_buckets=(64, 256),
    ))
    bus, registry, scheduler, app, worker = await _build_stack(
        engine, model, stream_flush_ms=20)
    client = None
    try:
        await worker.start()
        await asyncio.sleep(0.1)
        client = TestClient(TestServer(app))
        await client.start_server()

        texts = [f"document {i}: the quick brown fox jumps over the lazy "
                 f"dog " * (1 + i % 4) for i in range(batch)]
        warm = await client.post("/ollama/api/embed",
                                 json={"model": model, "input": texts})
        assert warm.status == 200, await warm.text()

        done = [0]

        async def one() -> None:
            for _ in range(rounds):
                resp = await client.post(
                    "/ollama/api/embed", json={"model": model, "input": texts})
                assert resp.status == 200, await resp.text()
                body = await resp.json()
                done[0] += len(body.get("embeddings") or [])

        t0 = time.perf_counter()
        await asyncio.gather(*(one() for _ in range(n_requests)))
        wall = time.perf_counter() - t0
        return {"qps": done[0] / wall, "texts": done[0], "wall_s": wall,
                "perf": _perf_sidecar()}
    finally:
        await _teardown_stack(bus, registry, scheduler, worker,
                              client=client)


BENCH_SCHEMA = "gridllm-bench/v1"

# regression direction per metric: the compare gate flags a >threshold
# move the WRONG way; metrics absent from either record are skipped
# spec gating (ISSUE 18): tokens/step and acceptance — NOT ITL, which is
# scheduler noise at tiny-CPU scale (itl_speedup left the gate set when
# the spec bench went three-arm)
HIGHER_BETTER = ("tok_s", "qps", "goodput_tok_s", "slo_attainment",
                 "ttft_speedup", "prefix_cache_hit_rate",
                 "spec_acceptance_rate", "spec_tokens_per_step",
                 "spec_acceptance_rate_ngram",
                 "spec_tokens_per_step_ngram", "ttft_recovery",
                 "cold_start_speedup")
LOWER_BETTER = ("p50_ttft_ms", "p95_ttft_ms", "p50_itl_ms",
                "peak_hbm_bytes", "cold_ttft_ms", "compile_warm_ttft_ms",
                "snapshot_warm_ttft_ms")


def build_record(scenario: str, args, payload: dict, r: dict) -> dict:
    """The standardized machine-readable bench result (--emit): one stable
    schema so BENCH_rNN.json files form a comparable perf trajectory."""
    metrics: dict = {}
    for key in HIGHER_BETTER + LOWER_BETTER:
        val = payload.get(key, r.get(key))
        if isinstance(val, (int, float)):
            metrics[key] = round(float(val), 4)
    perf = r.get("perf") or {}
    metrics["recompiles_steady"] = int(perf.get("recompiles_steady", 0))
    if perf.get("peak_hbm_bytes"):
        metrics["peak_hbm_bytes"] = int(perf["peak_hbm_bytes"])
    return {
        "peak_hbm_source": perf.get("peak_hbm_source", "none"),
        "schema": BENCH_SCHEMA,
        "createdAt": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "scenario": scenario,
        "model": args.model,
        "platform": payload.get("platform"),
        "degraded": payload.get("degraded", False),
        "config": {"requests": args.requests, "tokens": args.tokens,
                   "slots": args.slots, "prompt_len": args.prompt_len},
        "metrics": metrics,
        "recompiles_by_fn": perf.get("recompiles_by_fn") or {},
        "payload": payload,
    }


def compare_records(old: dict, new: dict,
                    threshold: float = 0.10) -> tuple[list[str], list[str]]:
    """(regressions, notes) between two bench records. Apples-to-apples
    only: scenario/model/platform mismatches skip the comparison with a
    note instead of flagging nonsense regressions (a degraded CPU
    substitute run must not 'regress' a real TPU baseline)."""
    notes: list[str] = []
    for field in ("scenario", "model", "platform"):
        if old.get(field) != new.get(field):
            notes.append(
                f"baseline {field} mismatch ({old.get(field)!r} vs "
                f"{new.get(field)!r}) — comparison skipped")
            return [], notes
    if old.get("schema") != new.get("schema"):
        notes.append(f"schema drift: {old.get('schema')} vs "
                     f"{new.get('schema')} — comparing shared metrics only")
    regressions: list[str] = []
    om, nm = old.get("metrics") or {}, new.get("metrics") or {}
    for key in HIGHER_BETTER:
        if key in om and key in nm and om[key] > 0:
            if nm[key] < om[key] * (1 - threshold):
                regressions.append(
                    f"{key}: {om[key]:g} -> {nm[key]:g} "
                    f"({(nm[key] / om[key] - 1) * 100:+.1f}%)")
    for key in LOWER_BETTER:
        if key in om and key in nm and om[key] > 0:
            if nm[key] > om[key] * (1 + threshold):
                regressions.append(
                    f"{key}: {om[key]:g} -> {nm[key]:g} "
                    f"({(nm[key] / om[key] - 1) * 100:+.1f}%)")
    old_rc = om.get("recompiles_steady")
    new_rc = nm.get("recompiles_steady")
    if old_rc is not None and new_rc is not None and new_rc > old_rc:
        # any NEW steady-state recompile is a regression — there is no
        # 10% grace for a signal whose healthy value is zero
        regressions.append(f"recompiles_steady: {old_rc} -> {new_rc}")
    return regressions, notes


def probe_backend(tries: int = 1, timeout_s: float = 60.0) -> tuple[str, list[str]]:
    """Check that jax can initialize its default backend WITHOUT importing jax
    in this process (an in-process TPU init that hangs would take the whole
    bench down with it — exactly what burned round 1, BENCH_r01.json rc=1).

    Probes in a subprocess with a hard timeout. Fail-fast (ISSUE 5
    satellite): BENCH_r05 burned 2 × 240 s of every run on "backend init
    timed out" before falling back to CPU, so the probe is now ONE cheap
    device-count check with a short timeout — a healthy TPU (or TPU relay)
    enumerates its devices well inside 60 s, and a hung runtime goes
    straight to the fallback, with the skip recorded in the structured
    health fields (the returned diags land in the payload's `attempts`).
    Returns (platform, diagnostics). On failure returns ("cpu", diags)
    after pinning JAX_PLATFORMS=cpu in this process's env so the subsequent
    in-process import is guaranteed not to touch the broken accelerator."""
    import os
    import subprocess

    diags: list[str] = []
    code = ("import jax; print('PLATFORM=' + jax.devices()[0].platform + "
            "' devices=%d' % jax.device_count())")
    for attempt in range(1, tries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=timeout_s,
            )
            for line in out.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    plat = line.split("=", 1)[1].split()[0]
                    diags.append(f"attempt {attempt}: backend ok ({line[9:]})")
                    return plat, diags
            tail = (out.stderr or out.stdout).strip().splitlines()[-3:]
            diags.append(f"attempt {attempt}: rc={out.returncode} {' | '.join(tail)}")
        except subprocess.TimeoutExpired:
            diags.append(f"attempt {attempt}: backend init timed out after "
                         f"{timeout_s}s")
        if attempt < tries:
            time.sleep(5.0)
    diags.append("accelerator probe failed — skipping straight to "
                 "JAX_PLATFORMS=cpu fallback")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu", diags


def emit(payload: dict) -> None:
    """The driver contract: exactly ONE JSON line on stdout, always."""
    print(json.dumps(payload), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama3.2:3b")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=128)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=120)
    ap.add_argument("--embed", action="store_true",
                    help="embeddings QPS bench (BASELINE config #5)")
    ap.add_argument("--shared-prefix", action="store_true",
                    help="prefix-cache scenario: N streams share one long "
                         "system prompt; reports cold vs warm p50 TTFT and "
                         "the prefix-cache hit rate (ISSUE 3)")
    ap.add_argument("--prefix-len", type=int, default=1200,
                    help="shared system-prompt length in characters "
                         "(--shared-prefix only)")
    ap.add_argument("--long-context", action="store_true",
                    help="tiered-KV scenario: shared-prefix streams, then "
                         "long prompts overflow the HBM reuse LRU; A/B "
                         "host tier off vs on (post-eviction warm TTFT "
                         "recovery, per-tier hit rates, restores)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding A/B/C: the same repetitive-"
                         "completion workload spec-off, n-gram, and "
                         "draft-model + token-tree; reports per-arm "
                         "tok/s, ITL, acceptance rate, tokens per verify "
                         "step, and drafter overhead (ISSUE 5 + 18)")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="speculation depth K for the --spec scenario")
    ap.add_argument("--mixed", action="store_true",
                    help="mixed-workload scenario: decode-heavy streams "
                         "concurrent with long chunked prefills; reports "
                         "the decode arm's p50 ITL and the prefill arm's "
                         "p50 TTFT — the ragged paged-attention gate "
                         "(ISSUE 6)")
    ap.add_argument("--long-prompt-len", type=int, default=2400,
                    help="long-prefill prompt length in characters "
                         "(--mixed/--disagg only)")
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated-serving A/B: the mixed workload "
                         "served by one unified worker vs a prefill+decode "
                         "split fleet with KV-page migration; reports both "
                         "arms' decode ITL and prefill TTFT plus migration "
                         "bytes/latency (ISSUE 7)")
    ap.add_argument("--fleet", action="store_true",
                    help="scaled-control-plane A/B: the same stream load "
                         "through the single-box scheduler vs a "
                         "2-gateway/2-shard control plane on one bus; "
                         "reports both arms' tok/s and p50 TTFT plus the "
                         "shard dispatch split (ISSUE 15)")
    ap.add_argument("--swap", action="store_true",
                    help="elastic-serving scenario: cold-start TTFT arms "
                         "(fully cold vs compile-cache-warm vs weight-"
                         "snapshot-warm) plus a bursty two-model A/B — "
                         "demand-driven swapping vs a static single-model "
                         "pin (ISSUE 20)")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny-llama CPU smoke test")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the measured "
                         "window into DIR (SURVEY §5.1)")
    ap.add_argument("--emit", metavar="PATH", default=None,
                    help="write the standardized bench record "
                         "(gridllm-bench/v1) to PATH, e.g. BENCH_r06.json "
                         "— the machine-readable perf trajectory (ISSUE 4)")
    ap.add_argument("--compare", metavar="PATH", default=None,
                    help="compare this run against a previous --emit "
                         "record; exit nonzero on a >10%% regression")
    ap.add_argument("--regression-threshold", type=float, default=0.10,
                    help="fractional regression tolerance for --compare")
    args = ap.parse_args()
    if args.embed and args.model == ap.get_default("model"):
        args.model = "all-minilm"
    if args.profile and args.embed:
        # only the generate path threads profile_dir through; failing fast
        # beats silently never writing the trace
        ap.error("--profile is only supported on the generate bench")
    if args.embed and args.shared_prefix:
        ap.error("--shared-prefix is a generate scenario; drop --embed")
    if args.spec and (args.embed or args.shared_prefix):
        ap.error("--spec is its own generate scenario; drop "
                 "--embed/--shared-prefix")
    if args.mixed and (args.embed or args.shared_prefix or args.spec):
        ap.error("--mixed is its own generate scenario; drop "
                 "--embed/--shared-prefix/--spec")
    if args.long_context and (args.embed or args.shared_prefix or args.spec
                              or args.mixed or args.disagg):
        ap.error("--long-context is its own generate scenario; drop "
                 "--embed/--shared-prefix/--spec/--mixed/--disagg")
    if args.disagg and (args.embed or args.shared_prefix or args.spec
                        or args.mixed):
        ap.error("--disagg is its own generate scenario; drop "
                 "--embed/--shared-prefix/--spec/--mixed")
    if args.fleet and (args.embed or args.shared_prefix or args.spec
                       or args.mixed or args.disagg or args.long_context):
        ap.error("--fleet is its own generate scenario; drop "
                 "--embed/--shared-prefix/--spec/--mixed/--disagg/"
                 "--long-context")
    if args.swap and (args.embed or args.shared_prefix or args.spec
                      or args.mixed or args.disagg or args.long_context
                      or args.fleet):
        ap.error("--swap is its own generate scenario; drop "
                 "--embed/--shared-prefix/--spec/--mixed/--disagg/"
                 "--long-context/--fleet")
    if args.swap:
        # every burst needs at least one stream; keep the CPU arms short
        args.requests = max(args.requests, 1)
    if args.fleet:
        # both partitions must carry at least one measured stream each
        args.requests = max(args.requests, 2)
    if args.disagg:
        # at least one stream per class, same clamp rationale as --mixed
        args.requests = max(args.requests, 2)
    if args.mixed:
        # the scenario needs at least one stream per arm — clamp HERE so
        # the emitted record's request count matches the load actually run
        args.requests = max(args.requests, 2)

    # structured run health (ISSUE 2 satellite — replaces the ||-joined
    # error string): `attempts` logs every stage that failed along the way,
    # `fallback` names a degraded execution path actually taken,
    # `degraded` flags a number that must not be read as the requested
    # config's. The driver still gets exactly one JSON line.
    attempts: list[dict] = []
    degraded = False
    fallback = None
    if args.tiny:
        platform = "cpu"
    else:
        platform, diags = probe_backend()
        attempts.extend(
            {"stage": "backend_probe", "detail": d}
            for d in diags if "ok" not in d
        )
    if platform == "cpu":
        # degraded mode: still produce a number, flagged via "error".
        # The env may force-register an accelerator plugin at the jax
        # CONFIG layer (sitecustomize), so the env var alone does not
        # stick — pin the config too, before any backend init.
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        requested = args.model
        args.model = "tiny-bert" if args.embed else "tiny-llama"
        # the spec scenario needs enough decode steps for the output to
        # enter its repetitive regime before acceptance can show
        args.tokens = min(args.tokens,
                          48 if (args.spec or args.mixed or args.disagg)
                          else 16)
        if args.fleet:
            args.tokens = min(args.tokens, 16)
        args.prompt_len = 20
        # the shared prefix must still span several KV pages (64-token
        # pages, byte tokenizer) or there is nothing to cache
        args.prefix_len = min(args.prefix_len, 800)
        # tiny models cap context at 512 tokens (byte tokenizer): the
        # long arm must still span several 64-token chunks
        args.long_prompt_len = min(args.long_prompt_len, 320)
        args.requests = min(args.requests, 4)
        if args.long_context:
            # tiny slot cap is 8×64 = 512 tokens: the shared prefix must
            # leave room for the query + generation, and the long burst
            # must still exceed the post-warm free pool
            args.prefix_len = min(args.prefix_len, 320)
            args.long_prompt_len = min(args.long_prompt_len, 448)
            args.tokens = min(args.tokens, 16)
            args.requests = max(min(args.requests, 3), 2)
        if not args.tiny:
            # flag the substitution even when the CPU probe itself was
            # healthy — a tiny-model number must never read as `requested`
            degraded = True
            attempts.append({
                "stage": "degrade",
                "detail": f"cpu fallback, {requested} replaced "
                          f"with {args.model}",
            })

    metric_name = (  # provisional — refined with weights provenance below
        f"embeddings/sec via /ollama/api/embed ({args.model})" if args.embed
        else f"output tokens/sec via /ollama/api/generate ({args.model}, "
             f"{args.requests} concurrent streams)"
    )
    try:
        if args.embed:
            r = asyncio.run(run_embed_bench(args.model, args.requests))
            baseline = EMBED_BASELINE_QPS.get(args.model, 0.0)
            value, unit = r["qps"], "embeddings/s"
        elif args.shared_prefix:
            r = asyncio.run(run_shared_prefix_bench(
                args.model, args.requests, args.tokens, args.slots,
                args.prefix_len,
            ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            metric_name = (
                f"warm-cache output tokens/sec via /ollama/api/generate "
                f"({args.model}, shared-prefix scenario, {args.requests} "
                f"streams × {args.prefix_len}-char system prompt, "
                f"{r['weights']})"
            )
        elif args.long_context:
            r = asyncio.run(run_long_context_bench(
                args.model, args.requests, args.tokens, args.slots,
                args.prefix_len, args.long_prompt_len,
            ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            metric_name = (
                f"post-eviction warm output tokens/sec via /ollama/api/"
                f"generate ({args.model}, tiered-KV long-context A/B, "
                f"{args.requests} streams × {r['prefix_len']}-char shared "
                f"prefix under LRU-overflow pressure, {r['weights']})"
            )
        elif args.spec:
            r = asyncio.run(run_spec_bench(
                args.model, args.requests, args.tokens, args.slots,
                args.spec_k,
            ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            metric_name = (
                f"spec-on output tokens/sec via /ollama/api/generate "
                f"({args.model}, speculative-decoding off/n-gram/"
                f"draft-model-tree A/B/C, K={args.spec_k}, "
                f"{args.requests} streams, repetitive workload, "
                f"{r['weights']})"
            )
        elif args.disagg:
            r = asyncio.run(run_disagg_bench(
                args.model, args.requests, args.tokens, args.slots,
                args.long_prompt_len,
            ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            metric_name = (
                f"split-fleet output tokens/sec via scheduler submit "
                f"({args.model}, disaggregated prefill/decode A/B with "
                f"KV-page migration, {args.requests} streams, "
                f"{r['weights']})"
            )
        elif args.fleet:
            r = asyncio.run(run_fleet_bench(
                args.model, args.requests, args.tokens, args.slots,
                args.prompt_len,
            ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            metric_name = (
                f"scaled-control-plane output tokens/sec via gateway-"
                f"replica submit ({args.model}, 2 gateways / 2 scheduler "
                f"shards vs single-box, {args.requests} streams, "
                f"{r['weights']})"
            )
        elif args.swap:
            r = asyncio.run(run_swap_bench(
                args.model, args.requests, args.tokens, args.slots,
            ))
            baseline = 0.0
            value = r.get("cold_start_speedup") or 0.0
            unit = "x"
            metric_name = (
                f"snapshot-warm vs fully-cold cold-start TTFT speedup "
                f"({args.model}, elastic-serving scenario: compile-cache "
                f"+ weight-snapshot swap-in, plus bursty two-model "
                f"elastic-vs-static A/B, {r['weights']})"
            )
        elif args.mixed:
            r = asyncio.run(run_mixed_bench(
                args.model, args.requests, args.tokens, args.slots,
                args.long_prompt_len,
            ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            metric_name = (
                f"mixed-workload output tokens/sec via /ollama/api/"
                f"generate ({args.model}, decode streams concurrent with "
                f"long chunked prefills, {args.requests} streams, "
                f"{r['weights']})"
            )
        else:
            import os as _os

            kernel_note = ""
            try:
                r = asyncio.run(run_bench(
                    args.model, args.requests, args.tokens, args.slots,
                    args.prompt_len, profile_dir=args.profile,
                ))
            except Exception as first_err:  # noqa: BLE001
                msg = f"{type(first_err).__name__}: {first_err}"
                device_like = any(k in msg for k in (
                    "INTERNAL", "Mosaic", "XLA", "RESOURCE_EXHAUSTED",
                    "jaxlib", "TPU", "runner died", "device",
                )) or type(first_err).__module__.startswith("jax")
                # same kernels-disabled spellings _env_mode accepts: a run
                # under GRIDLLM_PALLAS=off already has no kernel path, so
                # retrying with =0 would just repeat the identical failure
                if (platform == "cpu" or not device_like
                        or (env_raw("GRIDLLM_PALLAS") or "").lower()
                        in ("0", "off", "false")):
                    raise  # not a kernel-path problem — don't mislabel it
                # kernel-path safety net: a Pallas kernel failing on REAL
                # hardware (interpret-mode tests can't catch every Mosaic
                # behavior) must degrade to the jnp path and still produce
                # an honest TPU number, not a 0.0 — flagged in the metric
                fallback = "pallas-disabled"
                attempts.append({"stage": "kernel_path", "error": msg})
                # drop the traceback BEFORE the retry: it pins the failed
                # run's engine (weights + KV pool in HBM) via its frames
                first_err = None
                del first_err
                _os.environ["GRIDLLM_PALLAS"] = "0"
                # the env decision is @functools.cache'd at first use —
                # without clearing it the retry would re-run the exact
                # same kernel path
                from gridllm_tpu.ops.kvcache import _env_mode

                _env_mode.cache_clear()
                kernel_note = ", pallas-disabled fallback"
                r = asyncio.run(run_bench(
                    args.model, args.requests, args.tokens, args.slots,
                    args.prompt_len, profile_dir=args.profile,
                ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            # the weights provenance lives IN the metric string so a
            # synthetic number can never be misread as a real-model one
            # (VERDICT r03 weak #4)
            metric_name = (
                f"output tokens/sec via /ollama/api/generate ({args.model}, "
                f"{args.requests} concurrent streams, {r['weights']}"
                f"{kernel_note})"
            )
    except BaseException as e:  # noqa: BLE001 — the JSON line must survive anything
        import traceback

        tb = traceback.format_exc().strip().splitlines()
        attempts.append({"stage": "run",
                         "error": f"{type(e).__name__}: {e}",
                         "traceback": tb[-3:]})
        err_payload = {
            "metric": metric_name, "value": 0.0,
            "unit": "embeddings/s" if args.embed else "tok/s",
            "vs_baseline": 0.0, "error": f"{type(e).__name__}: {e}",
            "attempts": attempts, "degraded": degraded,
            "fallback": fallback,
        }
        if args.emit:
            # the perf gate reads the record file — a crashed run must
            # leave one (with the error and no metrics) rather than
            # silently skipping the emit
            try:
                with open(args.emit, "w") as f:
                    json.dump({
                        "schema": BENCH_SCHEMA, "scenario": "error",
                        "model": args.model, "error": err_payload["error"],
                        "metrics": {}, "payload": err_payload,
                    }, f, indent=2, sort_keys=True)
                    f.write("\n")
            except OSError:
                pass
        emit(err_payload)
        # the one-JSON-line driver contract wants rc 0; a --emit/--compare
        # PERF GATE run must instead fail loudly — a gate that goes green
        # on a crashed benchmark is worse than no gate
        return 1 if (args.emit or args.compare) else 0
    payload = {
        "metric": metric_name,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / baseline, 3) if baseline else None,
        "platform": platform,
        "wall_s": round(r["wall_s"], 2),
        "degraded": degraded,
    }
    if args.spec:
        # the speculation headline (ISSUE 18, three arms): acceptance
        # rate and tokens per verify step for BOTH drafting backends —
        # the numbers --compare gates on (a collapse to acceptance ≈ 0
        # means drafting is pure verify overhead). ITL stays per-arm
        # inside "arms" (informational; tiny-CPU ITL is noise).
        payload["tok_s_spec_off"] = round(r["tok_s_spec_off"], 2)
        payload["spec_acceptance_rate"] = r["spec_acceptance_rate"]
        payload["spec_tokens_per_step"] = r["spec_tokens_per_step"]
        payload["spec_acceptance_rate_ngram"] = (
            r["spec_acceptance_rate_ngram"])
        payload["spec_tokens_per_step_ngram"] = (
            r["spec_tokens_per_step_ngram"])
        payload["spec_steps"] = r["spec_steps"]
        payload["spec_proposed"] = r["spec_proposed"]
        payload["spec_accepted"] = r["spec_accepted"]
        payload["arms"] = r["arms"]
        payload["tokens"] = r["tokens"]
    elif args.long_context:
        # the tiered-KV headline: the post-eviction round's warm TTFT
        # with the host tier on vs off (the recovery ratio), plus the
        # per-tier hit rates and restore counts that prove the tier —
        # not luck — did the work
        payload["p50_ttft_ms_cold"] = round(r["p50_ttft_ms_cold"], 1)
        payload["p50_ttft_ms_warm"] = round(r["p50_ttft_ms_warm"], 1)
        payload["p50_ttft_ms_post_on"] = round(r["p50_ttft_ms_post_on"], 1)
        payload["p50_ttft_ms_post_off"] = round(r["p50_ttft_ms_post_off"], 1)
        if r.get("ttft_recovery") is not None:
            payload["ttft_recovery"] = round(r["ttft_recovery"], 3)
        payload["restores"] = r["restores"]
        payload["kv_tier"] = r["kv_tier"]
        payload["tokens"] = r["tokens"]
    elif args.shared_prefix:
        # the prefix-cache headline: warm TTFT must beat cold, and the
        # warm round's prompt-page hit rate proves the cache did the work
        payload["p50_ttft_ms_cold"] = round(r["p50_ttft_ms_cold"], 1)
        payload["p50_ttft_ms_warm"] = round(r["p50_ttft_ms_warm"], 1)
        if r.get("ttft_speedup") is not None:
            payload["ttft_speedup"] = round(r["ttft_speedup"], 2)
        payload["prefix_cache_hit_rate"] = r["prefix_cache_hit_rate"]
        payload["prefix_cache_hit_rate_cold"] = r["prefix_cache_hit_rate_cold"]
        payload["prefix_cache"] = r["prefix_cache"]
        payload["tokens"] = r["tokens"]
    elif args.disagg:
        # the disaggregation headline: the split arm's decode-pool ITL
        # under mixed load (long prefills no longer inflate it) against
        # the unified arm's, plus migration volume/latency — both arms
        # ride the record so --compare gates the split numbers
        if r.get("p50_itl_ms") is not None:
            payload["p50_itl_ms"] = round(r["p50_itl_ms"], 2)
        if r.get("p50_ttft_ms") is not None:
            payload["p50_ttft_ms"] = round(r["p50_ttft_ms"], 1)
        payload["disagg"] = r["disagg"]
        payload["tokens"] = r["tokens"]
    elif args.fleet:
        # the control-plane headline: the scaled plane's TTFT/tok_s vs
        # the single-box arm (control-plane overhead under fan-out), and
        # the shard dispatch split proving both partitions carried load
        if r.get("p50_ttft_ms") is not None:
            payload["p50_ttft_ms"] = round(r["p50_ttft_ms"], 1)
        if r.get("p50_itl_ms") is not None:
            payload["p50_itl_ms"] = round(r["p50_itl_ms"], 2)
        payload["fleet"] = r["fleet"]
        payload["tokens"] = r["tokens"]
    elif args.swap:
        # the elastic-serving headline: the three cold-start arms (the
        # ≥3× snapshot-vs-cold gate), proof the snapshot tier — not luck
        # — did the work, and the bursty A/B where only the elastic arm
        # serves both models
        payload["cold_ttft_ms"] = round(r["cold_ttft_ms"], 1)
        payload["compile_warm_ttft_ms"] = round(r["compile_warm_ttft_ms"], 1)
        payload["snapshot_warm_ttft_ms"] = round(
            r["snapshot_warm_ttft_ms"], 1)
        if r.get("cold_start_speedup") is not None:
            payload["cold_start_speedup"] = round(r["cold_start_speedup"], 2)
        payload["snapshot_hit"] = r["snapshot_hit"]
        payload["cold_texts_identical"] = r["cold_texts_identical"]
        payload["snapshot_tier"] = r["snapshot_tier"]
        payload["compile_cache_dir_entries"] = r["compile_cache_dir_entries"]
        payload["bursty"] = r["bursty"]
    elif args.mixed:
        # the mixed-workload headline: the decode arm's ITL must survive
        # concurrent long prefills (single-launch mixed steps), and the
        # prefill arm's TTFT shows the chunked path's pace under load
        if r.get("p50_ttft_ms") is not None:
            payload["p50_ttft_ms"] = round(r["p50_ttft_ms"], 1)
        if r.get("p50_itl_ms") is not None:
            payload["p50_itl_ms"] = round(r["p50_itl_ms"], 2)
        payload["mixed"] = r["mixed"]
        payload["tokens"] = r["tokens"]
    elif not args.embed:
        payload["p50_ttft_ms"] = round(r["p50_ttft_ms"], 1)
        if r.get("p50_itl_ms") is not None:
            payload["p50_itl_ms"] = round(r["p50_itl_ms"], 1)
        payload["tokens"] = r["tokens"]
        if r.get("stages"):
            # per-stage breakdown from the obs tracer (queue-wait/prefill/
            # decode p50s) — explains the end-to-end numbers above
            payload["stages"] = r["stages"]
        if r.get("critical_path"):
            # additive per-segment p50 decomposition (ISSUE 17): unlike
            # the raw stage durations these sum to the traced e2e
            payload["critical_path"] = r["critical_path"]
        if r.get("slo_attainment") is not None:
            payload["slo_attainment"] = round(r["slo_attainment"], 4)
        if r.get("goodput_tok_s") is not None:
            payload["goodput_tok_s"] = round(r["goodput_tok_s"], 2)
        if r.get("capacity") is not None:
            # per-model demand/headroom snapshot + per-tenant token ledger
            # (ISSUE 16) — the capacity-smoke CI gate asserts the bench
            # traffic was attributed and the demand tracker saw it
            payload["capacity"] = r["capacity"]
        if r.get("fleet_health") is not None:
            # canary probe summary + worker health-state counts (ISSUE
            # 19) — a healthy bench run records zero quarantines
            payload["fleet_health"] = r["fleet_health"]
    else:
        payload["texts"] = r["texts"]
    if fallback:
        payload["fallback"] = fallback
    if attempts:
        payload["attempts"] = attempts
    # perf introspection always rides the driver line when measured —
    # steady-state recompiles and peak HBM are headline health signals
    perf_side = r.get("perf")
    if perf_side:
        payload["recompiles_steady"] = perf_side["recompiles_steady"]
        if perf_side.get("peak_hbm_bytes"):
            payload["peak_hbm_bytes"] = perf_side["peak_hbm_bytes"]
    scenario = ("embed" if args.embed
                else "shared-prefix" if args.shared_prefix
                else "long-context" if args.long_context
                else "spec" if args.spec
                else "mixed" if args.mixed
                else "disagg" if args.disagg
                else "fleet" if args.fleet
                else "swap" if args.swap else "generate")
    record = build_record(scenario, args, payload, r)
    regressions: list = []
    if args.compare:
        # a missing/corrupt baseline (first run of a CI gate, truncated
        # artifact) is a note, never a crash — the one-JSON-line driver
        # contract holds and the gate passes until a real baseline exists
        try:
            with open(args.compare) as f:
                baseline = json.load(f)
        except (OSError, ValueError) as e:
            baseline = None
            notes = [f"baseline unreadable ({type(e).__name__}: {e}) — "
                     "comparison skipped"]
        if baseline is not None:
            regressions, notes = compare_records(
                baseline, record, threshold=args.regression_threshold)
        payload["compare"] = {"baseline": args.compare,
                              "regressions": regressions, "notes": notes}
        record["compare"] = payload["compare"]
    if args.emit:
        with open(args.emit, "w") as f:
            json.dump(record, f, indent=2, sort_keys=True)
            f.write("\n")
    emit(payload)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
