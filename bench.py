#!/usr/bin/env python
"""Headline benchmark (driver contract: ONE JSON line on stdout).

Metric (BASELINE.md): output tokens/sec via /ollama/api/generate. The run
drives the FULL stack in one process — gateway HTTP → scheduler → in-memory
bus → WorkerService → InferenceEngine on whatever accelerator jax sees —
with N concurrent streaming requests (continuous batching), and reports
aggregate decode throughput + p50 TTFT.

vs_baseline anchors to BASELINE.json's comparison point ("Ollama-on-A100
output tokens/sec"); the reference publishes no numbers (BASELINE.md), so
the anchor values below are approximate public single-stream Ollama-on-A100
figures for each model. vs_baseline = measured_aggregate / anchor.

Usage: python bench.py [--model llama3.2:3b] [--requests 8] [--tokens 128]
       [--tiny] (tiny-llama on CPU, smoke test)
"""

from __future__ import annotations

import argparse
import asyncio
import json
import statistics
import sys
import time

# Approximate public Ollama single-stream numbers on A100 (the BASELINE.json
# comparison anchor; nothing is published by the reference itself).
A100_OLLAMA_TOK_S = {
    "llama3:8b": 110.0,
    "llama3.1:8b": 110.0,
    "llama3.2:3b": 220.0,
    "llama3.2:1b": 350.0,
    "tiny-llama": 1.0,  # smoke-test placeholder
}

# Approximate public Ollama batch-embedding throughput on A100 for the
# BASELINE config #5 anchor (nothing published by the reference itself).
EMBED_BASELINE_QPS = {
    "all-minilm": 2500.0,
    "tiny-bert": 1.0,  # smoke-test placeholder
    "tiny-llama": 1.0,
}


async def run_bench(model: str, n_requests: int, n_tokens: int,
                    max_slots: int, prompt_len: int,
                    profile_dir: str | None = None) -> dict:
    import os

    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config, WorkerConfig
    from gridllm_tpu.worker.main import resolve_checkpoint
    from gridllm_tpu.worker.service import WorkerService

    # bench honesty (VERDICT r03 weak #4): with no checkpoint the run uses
    # random weights + the byte tokenizer (representative compute,
    # unrepresentative tokenization) and the metric string says so. Same
    # resolution logic as the worker entrypoint — one source of truth.
    ckpt, tok = resolve_checkpoint(
        os.environ.get("GRIDLLM_CHECKPOINT_DIR"), model
    )
    engine = InferenceEngine(EngineConfig(
        model=model,
        checkpoint_path=ckpt,
        tokenizer=tok,
        max_slots=max_slots,
        page_size=64,
        num_pages=max(256, max_slots * 48),
        max_pages_per_slot=48,
        prefill_buckets=(256, 1024),
    ))
    bus = InMemoryBus()
    await bus.connect()
    config = Config()
    registry = WorkerRegistry(bus, config.scheduler)
    scheduler = JobScheduler(bus, registry, config.scheduler)
    # stage stats read every measured timeline — outgrow the default trace
    # LRU so large --requests runs aren't silently truncated to its tail
    scheduler.tracer.max_traces = max(scheduler.tracer.max_traces,
                                      n_requests * 2 + 16)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(bus, {model: engine}, WorkerConfig(),
                           stream_flush_ms=5)
    try:
        return await _run_bench_inner(
            client_ctx=(app, worker), engine=engine, model=model,
            n_requests=n_requests, n_tokens=n_tokens,
            prompt_len=prompt_len, profile_dir=profile_dir, ckpt=ckpt,
            scheduler=scheduler,
        )
    finally:
        # teardown ALSO on failure: the kernel-fallback retry in main()
        # rebuilds everything, and a half-alive first stack (engine runner
        # thread + HBM weights/KV pool) would make the retry OOM for
        # exactly the big models that need the fallback
        try:
            await worker.stop()
        except Exception:  # noqa: BLE001
            pass
        try:
            await scheduler.shutdown()
            await registry.shutdown()
            await bus.disconnect()
        except Exception:  # noqa: BLE001
            pass


def _stage_stats(tracer, request_ids) -> dict:
    """p50 per-stage durations (ms) from the obs tracer's stitched
    timelines — the per-stage breakdown that explains the end-to-end
    numbers, read from the SAME spans /admin/trace serves instead of being
    re-timed here (ISSUE 1 satellite)."""
    keymap = {"queue.wait": "p50_queue_wait_ms",
              "engine.prefill": "p50_prefill_ms",
              "engine.decode": "p50_decode_ms"}
    stages: dict[str, list[float]] = {k: [] for k in keymap}
    ttfts: list[float] = []
    for rid in request_ids:
        for s in tracer.export(rid) or []:
            if s["name"] in stages and s.get("durationMs") is not None:
                stages[s["name"]].append(s["durationMs"])
            elif s["name"] == "gateway.first_token":
                t = (s.get("meta") or {}).get("ttftMs")
                if t is not None:
                    ttfts.append(float(t))
    out = {keymap[name]: round(statistics.median(vals), 2)
           for name, vals in stages.items() if vals}
    if ttfts:
        # gateway-side TTFT (submit → first stream frame) — the top-level
        # p50_ttft_ms stays the client-observed HTTP number; the delta
        # between them is gateway/HTTP overhead
        out["p50_ttft_gateway_ms"] = round(statistics.median(ttfts), 2)
    return out


async def _run_bench_inner(client_ctx, engine, model, n_requests, n_tokens,
                           prompt_len, profile_dir, ckpt,
                           scheduler=None) -> dict:
    import aiohttp
    from aiohttp.test_utils import TestClient, TestServer

    app, worker = client_ctx
    await worker.start()
    await asyncio.sleep(0.1)
    client = TestClient(TestServer(app))
    await client.start_server()

    prompt = "The quick brown fox jumps over the lazy dog. " * (prompt_len // 10)

    # warmup: trigger prefill+decode compiles before timing — MUST use the
    # same prompt length as the measured run, or the real bucket's prefill
    # compile (tens of seconds on first use) lands inside the timed window.
    # Bounded wait: a device-level failure must surface as a fast, retryable
    # error (main() falls back to GRIDLLM_PALLAS=0), not a 300 s job timeout
    # that eats the whole bench window.
    warm = await client.post("/ollama/api/generate", json={
        "model": model, "prompt": prompt, "stream": False,
        "options": {"temperature": 0, "num_predict": 4},
    }, timeout=aiohttp.ClientTimeout(total=240))
    assert warm.status == 200, await warm.text()
    if not engine.running and not engine.embedding_only:
        raise RuntimeError("engine runner died during warmup "
                           "(device-level failure)")
    # stage stats must cover the MEASURED requests only, not the warmup
    warm_ids = set(scheduler.tracer.ids()) if scheduler is not None else set()

    ttfts: list[float] = []
    itls: list[float] = []  # per-stream mean inter-token latency
    tokens_out = [0]

    if profile_dir:
        # SURVEY §5.1 / VERDICT r03 #1: capture a device trace of the
        # measured window for op-level attribution (view with
        # tensorboard --logdir or xprof)
        import jax

        jax.profiler.start_trace(profile_dir)

    async def one(i: int) -> None:
        t0 = time.perf_counter()
        t_first = t_last = None
        async with client.post("/ollama/api/generate", json={
            "model": model, "prompt": f"[{i}] {prompt}",
            "options": {"temperature": 0.7, "seed": i, "num_predict": n_tokens},
        }) as resp:
            assert resp.status == 200, await resp.text()
            async for line in resp.content:
                if not line.strip():
                    continue
                now = time.perf_counter()
                if t_first is None:
                    t_first = now
                    ttfts.append(now - t0)
                t_last = now
                frame = json.loads(line)
                if frame.get("done"):
                    n = frame.get("eval_count") or 0
                    tokens_out[0] += n
                    if n > 1 and t_first is not None:
                        # streaming smoothness: a healthy pipeline spreads
                        # tokens across the window; a burst-at-the-end
                        # pathology (r03's 13 s TTFT) shows up as itl ≈ 0
                        # with huge ttft
                        itls.append((t_last - t_first) / (n - 1) * 1000)

    t_start = time.perf_counter()
    try:
        await asyncio.gather(*(one(i) for i in range(n_requests)))
    finally:
        if profile_dir:  # finalize the trace even when a request fails
            import jax

            jax.profiler.stop_trace()
    wall = time.perf_counter() - t_start

    await client.close()  # remaining teardown is run_bench's finally

    stages = {}
    slo_attainment = None
    goodput_tok_s = None
    if scheduler is not None:
        # worker-side spans publish on trace:{id} AFTER job:result resolves
        # the HTTP stream — drain the bus so the tail requests' prefill/
        # decode spans are ingested before we read the timelines
        flush = getattr(scheduler.bus, "flush", None)
        if flush is not None:
            await flush()
        measured = [r for r in scheduler.tracer.ids() if r not in warm_ids]
        stages = _stage_stats(scheduler.tracer, measured)
        # SLO/goodput from the obs SLO engine (ISSUE 2): the measured
        # streams are the "interactive" class (the warmup is non-streaming
        # → "batch", so it does not pollute these numbers)
        inter = scheduler.slo.snapshot()["classes"].get("interactive") or {}
        slo_attainment = inter.get("attainment")
        if inter.get("goodputTokens") is not None:
            goodput_tok_s = inter["goodputTokens"] / wall
    return {
        "tok_s": tokens_out[0] / wall,
        "p50_ttft_ms": statistics.median(ttfts) * 1000,
        "p50_itl_ms": statistics.median(itls) if itls else None,
        "tokens": tokens_out[0],
        "wall_s": wall,
        "stages": stages,
        "slo_attainment": slo_attainment,
        "goodput_tok_s": goodput_tok_s,
        "weights": "real-checkpoint" if ckpt else "random-weights synthetic",
    }


async def run_embed_bench(model: str, n_requests: int,
                          batch: int = 64, rounds: int = 8) -> dict:
    """Embeddings QPS through the full stack (BASELINE config #5):
    n_requests concurrent /ollama/api/embed calls, each carrying `batch`
    texts, repeated `rounds` times after a warmup."""
    from aiohttp.test_utils import TestClient, TestServer

    from gridllm_tpu.bus.memory import InMemoryBus
    from gridllm_tpu.engine import EngineConfig, InferenceEngine
    from gridllm_tpu.gateway.app import create_app
    from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
    from gridllm_tpu.utils.config import Config, WorkerConfig
    from gridllm_tpu.worker.service import WorkerService

    engine = InferenceEngine(EngineConfig(
        model=model, max_slots=1, prefill_buckets=(64, 256),
    ))
    bus = InMemoryBus()
    await bus.connect()
    config = Config()
    registry = WorkerRegistry(bus, config.scheduler)
    scheduler = JobScheduler(bus, registry, config.scheduler)
    await registry.initialize()
    await scheduler.initialize()
    app = create_app(bus, registry, scheduler, config)
    worker = WorkerService(bus, {model: engine}, WorkerConfig())
    await worker.start()
    await asyncio.sleep(0.1)
    client = TestClient(TestServer(app))
    await client.start_server()

    texts = [f"document {i}: the quick brown fox jumps over the lazy dog "
             * (1 + i % 4) for i in range(batch)]
    warm = await client.post("/ollama/api/embed",
                             json={"model": model, "input": texts})
    assert warm.status == 200, await warm.text()

    done = [0]

    async def one() -> None:
        for _ in range(rounds):
            resp = await client.post("/ollama/api/embed",
                                     json={"model": model, "input": texts})
            assert resp.status == 200, await resp.text()
            body = await resp.json()
            done[0] += len(body.get("embeddings") or [])

    t0 = time.perf_counter()
    await asyncio.gather(*(one() for _ in range(n_requests)))
    wall = time.perf_counter() - t0

    await client.close()
    await worker.stop()
    await scheduler.shutdown()
    await registry.shutdown()
    await bus.disconnect()
    return {"qps": done[0] / wall, "texts": done[0], "wall_s": wall}


def probe_backend(tries: int = 2, timeout_s: float = 240.0) -> tuple[str, list[str]]:
    """Check that jax can initialize its default backend WITHOUT importing jax
    in this process (an in-process TPU init that hangs would take the whole
    bench down with it — exactly what burned round 1, BENCH_r01.json rc=1).

    Probes in a subprocess with a hard timeout, bounded retries. Returns
    (platform, diagnostics). On persistent failure returns ("cpu", diags)
    after pinning JAX_PLATFORMS=cpu in this process's env so the subsequent
    in-process import is guaranteed not to touch the broken accelerator."""
    import os
    import subprocess

    diags: list[str] = []
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    for attempt in range(1, tries + 1):
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], capture_output=True, text=True,
                timeout=timeout_s,
            )
            for line in out.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    plat = line.split("=", 1)[1]
                    diags.append(f"attempt {attempt}: backend ok ({plat})")
                    return plat, diags
            tail = (out.stderr or out.stdout).strip().splitlines()[-3:]
            diags.append(f"attempt {attempt}: rc={out.returncode} {' | '.join(tail)}")
        except subprocess.TimeoutExpired:
            diags.append(f"attempt {attempt}: backend init timed out after {timeout_s}s")
        time.sleep(5.0)
    diags.append("falling back to JAX_PLATFORMS=cpu")
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu", diags


def emit(payload: dict) -> None:
    """The driver contract: exactly ONE JSON line on stdout, always."""
    print(json.dumps(payload), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama3.2:3b")
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--tokens", type=int, default=128)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=120)
    ap.add_argument("--embed", action="store_true",
                    help="embeddings QPS bench (BASELINE config #5)")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny-llama CPU smoke test")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the measured "
                         "window into DIR (SURVEY §5.1)")
    args = ap.parse_args()
    if args.embed and args.model == ap.get_default("model"):
        args.model = "all-minilm"
    if args.profile and args.embed:
        # only the generate path threads profile_dir through; failing fast
        # beats silently never writing the trace
        ap.error("--profile is only supported on the generate bench")

    # structured run health (ISSUE 2 satellite — replaces the ||-joined
    # error string): `attempts` logs every stage that failed along the way,
    # `fallback` names a degraded execution path actually taken,
    # `degraded` flags a number that must not be read as the requested
    # config's. The driver still gets exactly one JSON line.
    attempts: list[dict] = []
    degraded = False
    fallback = None
    if args.tiny:
        platform = "cpu"
    else:
        platform, diags = probe_backend()
        attempts.extend(
            {"stage": "backend_probe", "detail": d}
            for d in diags if "ok" not in d
        )
    if platform == "cpu":
        # degraded mode: still produce a number, flagged via "error".
        # The env may force-register an accelerator plugin at the jax
        # CONFIG layer (sitecustomize), so the env var alone does not
        # stick — pin the config too, before any backend init.
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        requested = args.model
        args.model = "tiny-bert" if args.embed else "tiny-llama"
        args.tokens = min(args.tokens, 16)
        args.prompt_len = 20
        args.requests = min(args.requests, 4)
        if not args.tiny:
            # flag the substitution even when the CPU probe itself was
            # healthy — a tiny-model number must never read as `requested`
            degraded = True
            attempts.append({
                "stage": "degrade",
                "detail": f"cpu fallback, {requested} replaced "
                          f"with {args.model}",
            })

    metric_name = (  # provisional — refined with weights provenance below
        f"embeddings/sec via /ollama/api/embed ({args.model})" if args.embed
        else f"output tokens/sec via /ollama/api/generate ({args.model}, "
             f"{args.requests} concurrent streams)"
    )
    try:
        if args.embed:
            r = asyncio.run(run_embed_bench(args.model, args.requests))
            baseline = EMBED_BASELINE_QPS.get(args.model, 0.0)
            value, unit = r["qps"], "embeddings/s"
        else:
            import os as _os

            kernel_note = ""
            try:
                r = asyncio.run(run_bench(
                    args.model, args.requests, args.tokens, args.slots,
                    args.prompt_len, profile_dir=args.profile,
                ))
            except Exception as first_err:  # noqa: BLE001
                msg = f"{type(first_err).__name__}: {first_err}"
                device_like = any(k in msg for k in (
                    "INTERNAL", "Mosaic", "XLA", "RESOURCE_EXHAUSTED",
                    "jaxlib", "TPU", "runner died", "device",
                )) or type(first_err).__module__.startswith("jax")
                if (platform == "cpu" or not device_like
                        or _os.environ.get("GRIDLLM_PALLAS") == "0"):
                    raise  # not a kernel-path problem — don't mislabel it
                # kernel-path safety net: a Pallas kernel failing on REAL
                # hardware (interpret-mode tests can't catch every Mosaic
                # behavior) must degrade to the jnp path and still produce
                # an honest TPU number, not a 0.0 — flagged in the metric
                fallback = "pallas-disabled"
                attempts.append({"stage": "kernel_path", "error": msg})
                # drop the traceback BEFORE the retry: it pins the failed
                # run's engine (weights + KV pool in HBM) via its frames
                first_err = None
                del first_err
                _os.environ["GRIDLLM_PALLAS"] = "0"
                # the env decision is @functools.cache'd at first use —
                # without clearing it the retry would re-run the exact
                # same kernel path
                from gridllm_tpu.ops.kvcache import _env_mode

                _env_mode.cache_clear()
                kernel_note = ", pallas-disabled fallback"
                r = asyncio.run(run_bench(
                    args.model, args.requests, args.tokens, args.slots,
                    args.prompt_len, profile_dir=args.profile,
                ))
            baseline = A100_OLLAMA_TOK_S.get(args.model, 0.0)
            value, unit = r["tok_s"], "tok/s"
            # the weights provenance lives IN the metric string so a
            # synthetic number can never be misread as a real-model one
            # (VERDICT r03 weak #4)
            metric_name = (
                f"output tokens/sec via /ollama/api/generate ({args.model}, "
                f"{args.requests} concurrent streams, {r['weights']}"
                f"{kernel_note})"
            )
    except BaseException as e:  # noqa: BLE001 — the JSON line must survive anything
        import traceback

        tb = traceback.format_exc().strip().splitlines()
        attempts.append({"stage": "run",
                         "error": f"{type(e).__name__}: {e}",
                         "traceback": tb[-3:]})
        emit({
            "metric": metric_name, "value": 0.0,
            "unit": "embeddings/s" if args.embed else "tok/s",
            "vs_baseline": 0.0, "error": f"{type(e).__name__}: {e}",
            "attempts": attempts, "degraded": degraded,
            "fallback": fallback,
        })
        return 0  # JSON line emitted — that is the contract
    payload = {
        "metric": metric_name,
        "value": round(value, 2),
        "unit": unit,
        "vs_baseline": round(value / baseline, 3) if baseline else None,
        "platform": platform,
        "wall_s": round(r["wall_s"], 2),
        "degraded": degraded,
    }
    if not args.embed:
        payload["p50_ttft_ms"] = round(r["p50_ttft_ms"], 1)
        if r.get("p50_itl_ms") is not None:
            payload["p50_itl_ms"] = round(r["p50_itl_ms"], 1)
        payload["tokens"] = r["tokens"]
        if r.get("stages"):
            # per-stage breakdown from the obs tracer (queue-wait/prefill/
            # decode p50s) — explains the end-to-end numbers above
            payload["stages"] = r["stages"]
        if r.get("slo_attainment") is not None:
            payload["slo_attainment"] = round(r["slo_attainment"], 4)
        if r.get("goodput_tok_s") is not None:
            payload["goodput_tok_s"] = round(r["goodput_tok_s"], 2)
    else:
        payload["texts"] = r["texts"]
    if fallback:
        payload["fallback"] = fallback
    if attempts:
        payload["attempts"] = attempts
    emit(payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
