"""Black-box flight recorder: bounded rings of lifecycle events (ISSUE 2).

Every subsystem (gateway, scheduler, registry, bus, worker, engine) appends
structured events to its own fixed-capacity ring on the process-global
recorder. Appends are a deque push under a lock — cheap enough for the
engine's sampled step loop. Nothing is persisted; the recorder exists so
that the moment something dies there is a recent-history record to dump,
not so every event survives forever.

Dumps: :func:`build_dump` assembles ONE JSON-able artifact — ring contents,
active + recent traces, SLO snapshot, registry state, engine batch state —
and is invoked both on demand (``GET /admin/dump``) and automatically by the
hang watchdog on hang/worker-crash detection (auto dumps are retained on the
recorder, bounded, and included in subsequent on-demand dumps).

Engine access is indirect: workers register a *probe* callable per engine
(:func:`register_engine_probe`) returning a point-in-time batch-state dict,
so the dump path never has to import or lock engine internals itself.
Pure stdlib.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

SUBSYSTEMS = ("gateway", "scheduler", "registry", "bus", "worker", "engine")


class FlightRecorder:
    """Per-subsystem bounded event rings + a small retained-auto-dump list."""

    def __init__(self, capacity: int = 256, max_auto_dumps: int = 4):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._rings: dict[str, deque[dict[str, Any]]] = {}
        self._auto_dumps: deque[dict[str, Any]] = deque(maxlen=max_auto_dumps)
        self._dropped: dict[str, int] = {}  # subsystem → events evicted
        # fleet timeline tap (ISSUE 17): obs/timeline.py's publisher
        # mirrors every record() onto the causal event bus without the
        # ~60 existing call sites changing
        self._tap: Callable[[str, str, dict[str, Any]], None] | None = None

    def set_capacity(self, capacity: int) -> None:
        """Resize the rings (GRIDLLM_FLIGHTREC_CAPACITY at process start —
        the process-global recorder is built before config loads)."""
        with self._lock:
            self.capacity = capacity
            for name, ring in self._rings.items():
                self._rings[name] = deque(ring, maxlen=capacity)

    def set_tap(self,
                fn: Callable[[str, str, dict[str, Any]], None] | None) -> None:
        """Install (or clear) the timeline tap called after every
        ``record()`` append."""
        self._tap = fn

    def record(self, subsystem: str, event: str, **fields: Any) -> None:
        """Append one event. Fields must be JSON-able plain data; callers
        keep them small (ids, counts, reasons — not payloads)."""
        entry = {"ts": time.time(), "event": event, **fields}
        with self._lock:
            ring = self._rings.get(subsystem)
            if ring is None:
                ring = self._rings[subsystem] = deque(maxlen=self.capacity)
            if len(ring) == self.capacity:
                self._dropped[subsystem] = self._dropped.get(subsystem, 0) + 1
            ring.append(entry)
        tap = self._tap
        if tap is not None:
            try:  # outside the lock; the ring append must never fail
                tap(subsystem, event, fields)
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass

    def snapshot(self) -> dict[str, Any]:
        """Ring contents, oldest-first, plus eviction counts so a reader
        knows when the window is truncated (no silent caps)."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "evicted": dict(self._dropped),
                "rings": {name: list(ring)
                          for name, ring in self._rings.items()},
            }

    def add_auto_dump(self, artifact: dict[str, Any]) -> None:
        with self._lock:
            self._auto_dumps.append(artifact)

    def auto_dumps(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._auto_dumps)

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._auto_dumps.clear()
            self._dropped.clear()


_DEFAULT = FlightRecorder()


def default_flight_recorder() -> FlightRecorder:
    """The process-global recorder (all subsystems of this process)."""
    return _DEFAULT


# -- engine probes -----------------------------------------------------------
# worker/service.py registers one probe per engine at start (and removes it
# at stop); dumps and watchdog diagnoses read them without touching engine
# internals. Keyed so repeated starts replace rather than accumulate.

_probes: dict[str, Callable[[], dict[str, Any]]] = {}
_probes_lock = threading.Lock()


def register_engine_probe(name: str, fn: Callable[[], dict[str, Any]]) -> None:
    with _probes_lock:
        _probes[name] = fn


def unregister_engine_probe(name: str) -> None:
    with _probes_lock:
        _probes.pop(name, None)


def engine_states() -> dict[str, Any]:
    """Point-in-time batch state from every registered engine probe. A
    probe that raises (engine mid-teardown) reports the error instead of
    breaking the dump."""
    with _probes_lock:
        probes = dict(_probes)
    out: dict[str, Any] = {}
    for name, fn in probes.items():
        try:
            out[name] = fn()
        except Exception as e:  # noqa: BLE001 — dumps must always assemble
            out[name] = {"error": str(e)}
    return out


# -- dump assembly -----------------------------------------------------------


def build_dump(scheduler: Any = None, reason: str = "on_demand",
               recorder: FlightRecorder | None = None,
               include_auto_dumps: bool = True,
               **extra: Any) -> dict[str, Any]:
    """Assemble the post-mortem artifact: rings + active/recent traces +
    SLO snapshot + registry/scheduler state + engine batch state. Every
    section is best-effort — a dead subsystem must never block the dump
    that is supposed to explain its death."""
    rec = recorder or default_flight_recorder()
    artifact: dict[str, Any] = {
        "generatedAt": time.time(),
        "reason": reason,
        "flightRecorder": rec.snapshot(),
        "engines": engine_states(),
    }
    artifact.update(extra)
    if scheduler is not None:
        try:
            tracer = scheduler.tracer
            active = tracer.active_ids()
            artifact["activeTraces"] = {
                rid: tracer.export(rid) for rid in active
            }
            artifact["recentTraceIds"] = tracer.ids()[-16:]
        except Exception as e:  # noqa: BLE001
            artifact["activeTraces"] = {"error": str(e)}
        try:
            artifact["slo"] = scheduler.slo.snapshot()
        except Exception as e:  # noqa: BLE001
            artifact["slo"] = {"error": str(e)}
        try:
            artifact["scheduler"] = {
                "stats": scheduler.get_stats(),
                "queued": [qj.request.id for qj in scheduler.job_queue],
                "active": {
                    job_id: {"worker": a.workerId,
                             "assignedAt": a.assignedAt,
                             "model": a.request.model}
                    for job_id, a in scheduler.active_jobs.items()
                },
            }
        except Exception as e:  # noqa: BLE001
            artifact["scheduler"] = {"error": str(e)}
        try:
            artifact["registry"] = {
                "counts": scheduler.registry.get_worker_count(),
                "workers": [
                    {"workerId": w.workerId, "status": w.status,
                     "currentJobs": w.currentJobs,
                     "lastHeartbeat": w.lastHeartbeat,
                     "models": w.model_names()}
                    for w in scheduler.registry.get_all_workers()
                ],
            }
        except Exception as e:  # noqa: BLE001
            artifact["registry"] = {"error": str(e)}
    if include_auto_dumps:
        artifact["autoDumps"] = rec.auto_dumps()
    return artifact
