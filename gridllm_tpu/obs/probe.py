"""Canary prober: active end-to-end correctness + latency probes
(ISSUE 19).

Each scheduler shard runs one :class:`CanaryProber`.  At a low, bounded
rate (``GRIDLLM_PROBE_INTERVAL_MS``; 0 disables) it issues synthetic
greedy fixed-seed generations pinned to one (worker, model) pair at a
time — round-robin over every live worker — through the normal submit
path (``metadata.pinWorkerId`` placement).  The repo's byte-determinism
guarantees make the full response text a correctness checksum: the
first canary per (model, engine-config-hash) **seals a golden output
hash**, and every later canary against the same pair must match
byte-identically.  A mismatch means end-to-end drift — corrupted
weights, a silent kernel fallback, dtype rot — which numcheck's sampled
kernel shadowing cannot see end to end; it quarantines the worker
immediately and opens a forensics incident (``probe.golden_drift``).

Canary traffic rides the reserved ``canary`` tenant
(obs/usage.py CANARY_TENANT): invisible in the usage ledger (both
conservation halves) and in SLO attainment, while its e2e latency still
trains the worker's health baselines (obs/health.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import time
import uuid
from typing import Any

from gridllm_tpu.utils.config import env_int
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import InferenceRequest, Priority

from .flightrec import default_flight_recorder
from .health import HealthMonitor
from .metrics import LATENCY_BUCKETS, MetricsRegistry
from .usage import CANARY_TENANT

log = get_logger("obs.probe")

# fixed probe shape: greedy (temperature 0) + pinned seed + fixed prompt
# — the determinism surface the golden hash seals. Changing ANY of these
# (or the engine config, via the hash in the golden key) re-seals.
CANARY_PROMPT = "The canary sings a fixed song:"
CANARY_SEED = 0xCA9A


class CanaryProber:
    """Low-rate synthetic prober for one scheduler shard."""

    def __init__(self, scheduler: Any, registry: Any,
                 health: HealthMonitor, metrics: MetricsRegistry) -> None:
        self.scheduler = scheduler
        self.registry = registry
        self.health = health
        self.interval_ms = env_int("GRIDLLM_PROBE_INTERVAL_MS")
        self.concurrency = max(env_int("GRIDLLM_PROBE_CONCURRENCY"), 1)
        self.timeout_ms = env_int("GRIDLLM_PROBE_TIMEOUT_MS")
        self.tokens = max(env_int("GRIDLLM_PROBE_TOKENS"), 1)
        self.enabled = self.interval_ms > 0
        # golden output hash per (model, engine-config-hash): sealed by
        # the first canary, byte-law for every later one
        self.goldens: dict[tuple[str, str], str] = {}
        self._rr = 0
        self._inflight = 0
        self._task: asyncio.Task | None = None
        self.flightrec = default_flight_recorder()
        self._probes = metrics.counter(
            "gridllm_canary_probes_total",
            "Canary probe rounds, by result: pass (golden match or "
            "seal), drift (golden mismatch — correctness regression), "
            "fail (error/timeout), error (prober-side failure before "
            "submit).",
            ("result",))
        self._latency = metrics.histogram(
            "gridllm_canary_latency_seconds",
            "Canary end-to-end latency per probed worker — the health "
            "monitor's regression baseline input.",
            ("worker",), buckets=LATENCY_BUCKETS)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self.enabled and self._task is None:
            self._task = asyncio.create_task(self._loop())
            log.info("canary prober started",
                     interval_ms=self.interval_ms, tokens=self.tokens)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_ms / 1000)
            try:
                target = self._next_target()
                if target is None:
                    continue
                if self._inflight >= self.concurrency:
                    continue  # bounded: never accumulate probe backlog
                asyncio.ensure_future(self._probe_guarded(*target))
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — probing is best-effort
                log.warning("canary round failed", error=str(e))

    # -- target selection ----------------------------------------------------
    def _targets(self) -> list[tuple[Any, str]]:
        out: list[tuple[Any, str]] = []
        for w in self.registry.get_all_workers():
            # quarantined workers get no canaries — re-registration is
            # their only way back (health.note_registered); voluntarily
            # draining workers are mid-restart and skipped too
            if w.status not in ("online", "busy"):
                continue
            if getattr(w, "healthState", "online") == "quarantined":
                continue
            mc = getattr(w, "modelCapacity", None) or {}
            for model in w.model_names():
                # scale-to-zero (ISSUE 20): a model mid-unload (or already
                # unloaded, pending re-registration) has no capacity block
                # in the worker's freshest heartbeat — probing it now
                # would time out and trip CanaryDrift on a healthy worker.
                # Embedding-only models never report capacity and are
                # exempt from the check.
                if mc and model not in mc and not self._embedding_model(w, model):
                    continue
                out.append((w, model))
        return out

    @staticmethod
    def _embedding_model(worker: Any, model: str) -> bool:
        for m in worker.capabilities.availableModels:
            if m.name == model:
                return (m.details or {}).get("family") == "bert_embed"
        return False

    def _next_target(self) -> tuple[Any, str] | None:
        targets = self._targets()
        if not targets:
            return None
        self._rr = (self._rr + 1) % len(targets)
        return targets[self._rr]

    # -- probing -------------------------------------------------------------
    def golden_key(self, worker: Any, model: str) -> tuple[str, str]:
        """(model, engine-config-hash) — the worker advertises the hash
        in its ModelInfo.details (worker/capabilities.py); workers that
        don't (older registrations, test fakes) share the empty-hash
        golden for the model."""
        for m in worker.capabilities.availableModels:
            if m.name == model:
                cfg = (m.details or {}).get("engineConfigHash")
                if cfg:
                    return (model, str(cfg))
        return (model, "")

    async def _probe_guarded(self, worker: Any, model: str) -> None:
        self._inflight += 1
        try:
            await self.probe_once(worker, model)
        except Exception as e:  # noqa: BLE001 — never kill the loop
            log.warning("canary probe errored", error=str(e),
                        worker_id=worker.workerId)
            self._probes.inc(result="error")
        finally:
            self._inflight -= 1

    async def probe_once(self, worker: Any, model: str) -> str:
        """Issue one canary at (worker, model); returns the result label
        (pass/drift/fail/error). Public so tests and bench drive rounds
        directly without the timer loop."""
        from gridllm_tpu import faults  # lazy: faults imports obs

        worker_id = worker.workerId
        try:
            faults.inject("probe.issue")
        except faults.InjectedFault:
            # prober-side failure before submit: counted, but never a
            # golden verdict and never a strike against the worker
            self._probes.inc(result="error")
            return "error"
        request = InferenceRequest(
            id=f"canary-{uuid.uuid4().hex[:12]}",
            model=model,
            prompt=CANARY_PROMPT,
            options={"temperature": 0.0, "seed": CANARY_SEED,
                     "num_predict": self.tokens},
            priority=Priority.low,
            timeout=self.timeout_ms,
            metadata={"tenant": CANARY_TENANT, "canary": True,
                      "pinWorkerId": worker_id},
        )
        t0 = time.time()
        try:
            result = await self.scheduler.submit_and_wait(
                request, timeout_ms=self.timeout_ms)
        except Exception:  # noqa: BLE001 — timeout/cancel/bus loss
            result = None
        e2e_s = time.time() - t0
        self._latency.observe(e2e_s, worker=worker_id)
        if result is None or not result.success or result.response is None:
            self._probes.inc(result="fail")
            self.health.note_canary(worker_id, ok=False, e2e_s=e2e_s)
            return "fail"
        text = result.response.response or ""
        digest = hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()
        key = self.golden_key(worker, model)
        golden = self.goldens.get(key)
        if golden is None:
            self.goldens[key] = digest
            self.flightrec.record("probe", "golden_sealed",
                                  worker=worker_id, model=model,
                                  hash=digest[:16])
            verdict = "pass"
        elif digest == golden:
            verdict = "pass"
        else:
            self.flightrec.record("probe", "golden_drift",
                                  worker=worker_id, model=model,
                                  expected=golden[:16], got=digest[:16])
            log.error("canary golden drift", worker_id=worker_id,
                      model=model, expected=golden[:16], got=digest[:16])
            verdict = "drift"
        self._probes.inc(result=verdict)
        self.health.note_canary(worker_id, ok=True, e2e_s=e2e_s,
                                drift=(verdict == "drift"))
        return verdict

    def summary(self) -> dict[str, Any]:
        """Canary pass-rate block for bench records and the fleet-health
        admin view."""
        by_result = {str(dict(labels).get("result", "")): int(v)
                     for labels, v in self._probes.items()}
        total = sum(by_result.values())
        judged = by_result.get("pass", 0) + by_result.get("drift", 0) \
            + by_result.get("fail", 0)
        return {
            "enabled": self.enabled,
            "probes": total,
            "byResult": by_result,
            "passRate": (round(by_result.get("pass", 0) / judged, 4)
                         if judged else None),
            "goldens": len(self.goldens),
        }
