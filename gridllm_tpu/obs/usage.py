"""Per-tenant / per-model usage attribution (ISSUE 16).

Two halves of one exactly-once ledger:

* **Engine/worker half** — process-global ``gridllm_usage_engine_*``
  counters, incremented by the worker at the moment a ``job:result``
  with a usage payload has been published.  These are the conservation
  anchor: whatever the engine actually spent, keyed by model only.
* **Shard half** — per-scheduler ``gridllm_usage_*`` counters with a
  ``tenant`` label, incremented by the *owning* shard when it folds a
  result's usage payload into its ledger.  Every published usage
  payload is accounted exactly once (normal completion, the orphan-race
  branch, and duplicate executions under an explicit ``duplicate``
  outcome), so per-tenant sums equal the engine counters.

Tenant ids come from the configured header (``GRIDLLM_TENANT_HEADER``)
or a truncated hash of the Authorization bearer; cardinality is bounded
at label time by :class:`TenantLRU` (``GRIDLLM_TENANT_LRU``) with an
``other`` overflow bucket.  The metric-hygiene analyzer rule bans
``tenant``-labeled registrations outside this module for that reason.
"""

from __future__ import annotations

import hashlib
import re
import threading
from collections import OrderedDict
from typing import Any, Mapping

from gridllm_tpu.utils.config import env_int, env_str

from .metrics import MetricsRegistry, default_registry

ANONYMOUS_TENANT = "anonymous"
OVERFLOW_TENANT = "other"
# Reserved tenant for canary probes (ISSUE 19): synthetic health traffic
# is excluded from BOTH halves of the conservation ledger (worker skips
# account_engine_usage, the shard's account() early-returns) and from SLO
# attainment — billing and burn rates only ever describe real demand.
CANARY_TENANT = "canary"

_TENANT_RE = re.compile(r"[^a-zA-Z0-9_.:-]+")

# usage-payload token kinds and resource kinds (wire keys -> label values)
TOKEN_KINDS = {
    "promptTokens": "prompt",
    "outputTokens": "output",
    "prefixSavedTokens": "prefix_saved",
    "specWastedTokens": "spec_wasted",
}
RESOURCE_KINDS = {
    "decodeDeviceSeconds": "decode_device",
    "kvPageSeconds": "kv_page",
}


def resolve_tenant(headers: Mapping[str, str]) -> str:
    """Resolve a tenant id from request headers: the configured tenant
    header verbatim (sanitized), else a truncated digest of the
    Authorization value, else ``anonymous``."""
    name = env_str("GRIDLLM_TENANT_HEADER")
    raw = headers.get(name) or headers.get(name.lower()) or ""
    raw = raw.strip()
    if raw:
        return _TENANT_RE.sub("_", raw)[:64]
    auth = (headers.get("Authorization") or headers.get("authorization") or "").strip()
    if auth:
        digest = hashlib.sha256(auth.encode("utf-8", "replace")).hexdigest()[:12]
        return f"key-{digest}"
    return ANONYMOUS_TENANT


def build_usage(
    *,
    tenant: str,
    model: str,
    prompt_tokens: int,
    output_tokens: int,
    prefix_saved_tokens: int = 0,
    spec_wasted_tokens: int = 0,
    decode_device_s: float = 0.0,
    kv_page_s: float = 0.0,
    migrated_bytes: int = 0,
) -> dict[str, Any]:
    """Assemble the wire-format usage payload a worker folds into its
    ``JobResult`` (camelCase keys, like the rest of the job envelope)."""
    return {
        "tenant": tenant or ANONYMOUS_TENANT,
        "model": model,
        "promptTokens": int(prompt_tokens),
        "outputTokens": int(output_tokens),
        "prefixSavedTokens": int(prefix_saved_tokens),
        "specWastedTokens": int(spec_wasted_tokens),
        "decodeDeviceSeconds": round(float(decode_device_s), 6),
        "kvPageSeconds": round(float(kv_page_s), 6),
        "migratedBytes": int(migrated_bytes),
    }


class TenantLRU:
    """Bounded tenant-label vocabulary: the most recently seen ``cap``
    tenants keep their own label; everything else folds into ``other``.
    The registry cannot see cardinality at runtime — this is the one
    place it is enforced."""

    def __init__(self, cap: int | None = None) -> None:
        self.cap = int(cap if cap is not None else env_int("GRIDLLM_TENANT_LRU"))
        self._seen: OrderedDict[str, None] = OrderedDict()
        self._lock = threading.Lock()

    def label(self, tenant: str) -> str:
        t = tenant or ANONYMOUS_TENANT
        with self._lock:
            if t in self._seen:
                self._seen.move_to_end(t)
                return t
            if len(self._seen) < self.cap:
                self._seen[t] = None
                return t
        return OVERFLOW_TENANT


# ---------------------------------------------------------------- engine half

_glob = default_registry()
_ENGINE_TOKENS = _glob.counter(
    "gridllm_usage_engine_tokens_total",
    "Engine-side usage ledger: tokens attributed at request finish.",
    ("model", "kind"),
)
_ENGINE_SECONDS = _glob.counter(
    "gridllm_usage_engine_seconds_total",
    "Engine-side usage ledger: decode device-seconds and KV "
    "page-occupancy-seconds attributed at request finish.",
    ("model", "resource"),
)
_ENGINE_MIGRATED = _glob.counter(
    "gridllm_usage_engine_migrated_bytes_total",
    "Engine-side usage ledger: KV bytes imported for disagg handoffs.",
    ("model",),
)


def account_engine_usage(usage: Mapping[str, Any]) -> None:
    """Fold one published usage payload into the process-global engine
    ledger.  Call ONLY after the result publishes succeeded — an
    unpublished execution (killed worker) must stay invisible on both
    sides of the conservation invariant."""
    if str(usage.get("tenant") or "") == CANARY_TENANT:
        return  # canary probes stay invisible on BOTH ledger halves
    model = str(usage.get("model") or "unknown")
    for key, kind in TOKEN_KINDS.items():
        n = int(usage.get(key) or 0)
        if n:
            _ENGINE_TOKENS.inc(n, model=model, kind=kind)
    for key, resource in RESOURCE_KINDS.items():
        s = float(usage.get(key) or 0.0)
        if s > 0:
            _ENGINE_SECONDS.inc(s, model=model, resource=resource)
    b = int(usage.get("migratedBytes") or 0)
    if b:
        _ENGINE_MIGRATED.inc(b, model=model)


def engine_usage_totals() -> dict[str, float]:
    """Per-kind token totals of the engine-side ledger (tests diff this
    against the shard-side per-tenant sums)."""
    out: dict[str, float] = {}
    for labels, value in _ENGINE_TOKENS.items():
        kind = dict(labels).get("kind", "")
        out[kind] = out.get(kind, 0.0) + value
    return out


# ----------------------------------------------------------------- shard half


class UsageAccountant:
    """Owning-shard usage ledger: per-tenant/per-model counters on the
    scheduler's instance registry, tenant cardinality bounded by
    :class:`TenantLRU`."""

    def __init__(self, metrics: MetricsRegistry, lru_cap: int | None = None) -> None:
        self.lru = TenantLRU(lru_cap)
        self.tokens = metrics.counter(
            "gridllm_usage_tokens_total",
            "Shard usage ledger: tokens accounted exactly once by the "
            "owning shard, attributed to tenant and model.",
            ("tenant", "model", "kind"),
        )
        self.requests = metrics.counter(
            "gridllm_usage_requests_total",
            "Shard usage ledger: terminal request outcomes per tenant "
            "and model.",
            ("tenant", "model", "outcome"),
        )
        self.seconds = metrics.counter(
            "gridllm_usage_seconds_total",
            "Shard usage ledger: decode device-seconds and KV "
            "page-occupancy-seconds per tenant and model.",
            ("tenant", "model", "resource"),
        )
        self.migrated = metrics.counter(
            "gridllm_usage_migrated_bytes_total",
            "Shard usage ledger: disagg KV bytes migrated per tenant "
            "and model.",
            ("tenant", "model"),
        )

    def account(self, usage: Mapping[str, Any] | None, outcome: str) -> None:
        """Fold one result's usage payload into the ledger.  ``outcome``
        is ``completed`` for the job that resolved the request and
        ``duplicate`` for a redundant at-least-once execution — the
        engine really spent those tokens, so conservation demands they
        land somewhere."""
        if not usage:
            return
        if str(usage.get("tenant") or "") == CANARY_TENANT:
            return  # mirrors the engine half's exclusion exactly
        tenant = self.lru.label(str(usage.get("tenant") or ANONYMOUS_TENANT))
        model = str(usage.get("model") or "unknown")
        self.requests.inc(1, tenant=tenant, model=model, outcome=outcome)
        for key, kind in TOKEN_KINDS.items():
            n = int(usage.get(key) or 0)
            if n:
                self.tokens.inc(n, tenant=tenant, model=model, kind=kind)
        for key, resource in RESOURCE_KINDS.items():
            s = float(usage.get(key) or 0.0)
            if s > 0:
                self.seconds.inc(s, tenant=tenant, model=model, resource=resource)
        b = int(usage.get("migratedBytes") or 0)
        if b:
            self.migrated.inc(b, tenant=tenant, model=model)

    def note_outcome(self, tenant: str, model: str, outcome: str) -> None:
        """Record a terminal outcome that carries no usage payload
        (failures, sheds) so demand by tenant stays visible."""
        if (tenant or "") == CANARY_TENANT:
            return
        t = self.lru.label(tenant or ANONYMOUS_TENANT)
        self.requests.inc(1, tenant=t, model=model or "unknown", outcome=outcome)

    def token_totals(self) -> dict[str, float]:
        """Per-kind token totals summed over tenants and models (the
        shard side of the conservation invariant)."""
        out: dict[str, float] = {}
        for labels, value in self.tokens.items():
            kind = dict(labels).get("kind", "")
            out[kind] = out.get(kind, 0.0) + value
        return out

    def snapshot(self) -> dict[str, Any]:
        """JSON view of the ledger, grouped tenant -> model."""
        tenants: dict[str, dict[str, dict[str, Any]]] = {}

        def cell(tenant: str, model: str) -> dict[str, Any]:
            return tenants.setdefault(tenant, {}).setdefault(
                model, {"tokens": {}, "seconds": {}, "outcomes": {}, "migratedBytes": 0}
            )

        for labels, value in self.tokens.items():
            d = dict(labels)
            cell(d["tenant"], d["model"])["tokens"][d["kind"]] = value
        for labels, value in self.seconds.items():
            d = dict(labels)
            cell(d["tenant"], d["model"])["seconds"][d["resource"]] = round(value, 6)
        for labels, value in self.requests.items():
            d = dict(labels)
            cell(d["tenant"], d["model"])["outcomes"][d["outcome"]] = int(value)
        for labels, value in self.migrated.items():
            d = dict(labels)
            cell(d["tenant"], d["model"])["migratedBytes"] = int(value)
        return {"tenants": tenants}
