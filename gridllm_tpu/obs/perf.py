"""Performance introspection layer (ISSUE 4).

The obs stack through ISSUE 2 says *whether* requests meet their SLOs;
this module instruments the three dominant TPU-side reasons they don't:

1. **Recompile tripwire** (:class:`RecompileTripwire` / :class:`JitProbe`)
   — wraps the engine's jitted entry points and fingerprints every call's
   abstract signature (array shapes/dtypes — the shape-bucket and
   donated-arg-layout proxy jit keys on — plus static args). A signature
   never seen before means XLA compiled a new program. Compiles while the
   probe is *unarmed* are expected warmup (bucket compiles, first block);
   once armed (the engine arms itself after its first completed request),
   every new signature is a **steady-state recompile**: counted in
   ``gridllm_recompiles_total{fn,reason}``, logged to the flight recorder
   with the offending shapes, and — past a per-window budget — escalated
   to a watchdog-style *recompile storm* diagnosis.
2. **Device-memory accounting** (:func:`memory_snapshot`) — splits each
   device's live HBM into weights / KV pool / workspace from
   ``jax.live_arrays()`` classified against engine-registered memory
   probes, plus allocator-derived KV math (cold vs cached pages,
   lane-padding overhead, reserved-capacity fragmentation). Served at
   ``GET /admin/memory`` and exported as
   ``gridllm_device_memory_bytes{device,kind}`` gauges via a registry
   collector, with headroom/limit gauges where the backend reports
   allocator stats (TPU; CPU reports live bytes only).
3. **On-demand profiler capture** (:class:`ProfilerCapture`) —
   ``POST /admin/profile?seconds=N`` starts a ``jax.profiler`` trace into
   a bounded artifact directory (``GRIDLLM_PROFILE_DIR``, oldest captures
   pruned past ``GRIDLLM_PROFILE_KEEP``) and returns the path; the hang
   watchdog auto-triggers a short capture on decode-step hangs so the
   trace covers the wedge, not its aftermath.

The step-time decomposition histograms (host scheduling vs dispatch vs
on-device step) are registered here and driven by the engine's runner
loop — see engine/engine.py.

jax is imported lazily (function-level): importing this module — and
therefore ``gridllm_tpu.obs`` — must stay cheap for control-plane-only
processes. Pure stdlib otherwise.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from collections import deque
from typing import Any, Callable

from gridllm_tpu.obs.flightrec import default_flight_recorder
from gridllm_tpu.obs.metrics import default_registry
from gridllm_tpu.utils.config import ENV_VARS, env_float, env_int, env_raw
from gridllm_tpu.utils.logging import get_logger

log = get_logger("obs.perf")

_OBS = default_registry()

# -- recompile tripwire instruments -----------------------------------------

RECOMPILES_TOTAL = _OBS.counter(
    "gridllm_recompiles_total",
    "XLA compiles observed by the jit tripwire, by wrapped fn and reason "
    "(warmup = before the engine's first completed request; new_shape / "
    "new_static / new_signature = steady-state recompiles — each one is "
    "also a flight-recorder event carrying the offending shapes).",
    ("fn", "reason"),
)
RECOMPILE_STORMS_TOTAL = _OBS.counter(
    "gridllm_recompile_storms_total",
    "Recompile-storm diagnoses: steady-state recompiles exceeded the "
    "per-window budget (GRIDLLM_RECOMPILE_BUDGET per "
    "GRIDLLM_RECOMPILE_WINDOW seconds).",
)

# -- step-time decomposition (engine runner drives these) -------------------
# Sub-ms-focused buckets: decode steps on a healthy TPU are 1-50 ms; the
# long tail is exactly what these histograms exist to catch.
STEP_PHASE_BUCKETS = (
    0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0,
)
HOST_SCHED_SECONDS = _OBS.histogram(
    "gridllm_engine_host_sched_seconds",
    "Host-side gap between finishing one decode block's ingest and "
    "dispatching the next (admission, tokenize, stream callbacks, control "
    "drain), AMORTIZED PER FUSED STEP so it compares 1:1 with "
    "gridllm_engine_device_step_seconds, by model. Growth here is a host "
    "stall, not a device problem.",
    ("model",), buckets=STEP_PHASE_BUCKETS,
)
DISPATCH_SECONDS = _OBS.histogram(
    "gridllm_engine_dispatch_seconds",
    "Wall time for a fused decode block's jitted call to RETURN (trace + "
    "lower + enqueue; the device keeps computing after). A spike here "
    "usually means a recompile — pair with gridllm_recompiles_total.",
    ("model",), buckets=STEP_PHASE_BUCKETS,
)
DEVICE_STEP_SECONDS = _OBS.histogram(
    "gridllm_engine_device_step_seconds",
    "Estimated on-device time per fused decode step, by model. With the "
    "dispatch pipeline saturated this is the delta between consecutive "
    "block fetch completions (device-bound pace); otherwise dispatch-to-"
    "fetch wall time (upper bound including queue wait).",
    ("model",), buckets=STEP_PHASE_BUCKETS,
)

# -- device-memory gauges ----------------------------------------------------

DEVICE_MEMORY_BYTES = _OBS.gauge(
    "gridllm_device_memory_bytes",
    "Live device memory by kind: weights (model params), kv_pool (paged "
    "KV cache + tables), workspace (all other live arrays — activations, "
    "sampler state, staging buffers). Classified per jax.live_arrays() "
    "against engine memory probes at scrape time.",
    ("device", "kind"),
)
DEVICE_MEMORY_HEADROOM = _OBS.gauge(
    "gridllm_device_memory_headroom_bytes",
    "Allocator-reported free device memory (bytes_limit - bytes_in_use); "
    "only present on backends exposing memory_stats (TPU/GPU).",
    ("device",),
)
DEVICE_MEMORY_LIMIT = _OBS.gauge(
    "gridllm_device_memory_limit_bytes",
    "Allocator-reported device memory limit; only present on backends "
    "exposing memory_stats (TPU/GPU).",
    ("device",),
)


# Deliberately laxer than utils/config._env: these are read lazily on
# telemetry paths (per steady-state recompile, per capture), where a
# malformed env var must degrade to the default, never raise — config
# load's fail-fast SystemExit semantics would turn a typo'd budget into
# an outage of the thing doing the diagnosing.
def jax_loaded() -> bool:
    """Whether this process already imported jax. Every perf path that
    would otherwise import jax checks this first: in an engine-less
    control-plane process (split-deployment gateway) a surprise backend
    init is seconds of stall at best and, on a TPU host whose worker
    holds the exclusive libtpu claim, a hang — scrapes, snapshots, and
    captures must refuse or no-op instead."""
    import sys

    return "jax" in sys.modules


# ---------------------------------------------------------------------------
# recompile tripwire
# ---------------------------------------------------------------------------


def _leaf_signature(leaves: list[Any]) -> tuple[tuple[Any, ...], tuple[str, ...]]:
    """(array avals, static reprs) for one call's flattened args. Arrays
    contribute (shape, dtype) — the jit cache key's shape-bucket /
    donated-layout proxy; everything else (python ints, bools, static
    kwargs) contributes its repr."""
    avals: list[Any] = []
    statics: list[str] = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            avals.append((tuple(shape), str(dtype)))
        else:
            statics.append(repr(leaf))
    return tuple(avals), tuple(statics)


class JitProbe:
    """One wrapped jitted callable. Transparent pass-through plus
    signature bookkeeping; the owning :class:`RecompileTripwire` gets told
    about every first-seen signature."""

    def __init__(self, name: str, fn: Callable, tripwire: "RecompileTripwire",
                 armable: bool = True):
        self.name = name
        self._fn = fn
        self._tripwire = tripwire
        # armable=False: probes whose whole compile surface is explicitly
        # bucket-bounded and demand-driven (embed batch/len buckets,
        # vision image counts) — their first-use compiles can land long
        # after the generation path warms, so flagging them would page on
        # healthy behavior. They still count under reason="warmup".
        self.armable = armable
        self.armed = False
        # signature bookkeeping is guarded: the embed probe is called
        # from concurrent asyncio.to_thread workers while the runner
        # thread drives decode — an unguarded check-then-add would
        # double-count the same first-seen signature
        self._sig_lock = threading.Lock()
        # full signature → first-seen; plus the two projections used to
        # classify WHAT changed when a new signature appears
        self._seen: set[tuple] = set()
        self._seen_avals: set[tuple] = set()
        self._seen_statics: set[tuple] = set()
        # identity-memo for the first positional arg: every engine entry
        # point passes the (large, shape-stable) params tree first, and
        # re-flattening its hundreds of leaves per decode-block dispatch
        # would tax the hot path and inflate DISPATCH_SECONDS. One
        # (obj, sig) tuple so cross-thread reads are never torn; the
        # strong ref makes the `is` check immune to id reuse.
        self._memo: tuple[Any, tuple] | None = None
        self.compiles = 0
        self.steady_recompiles = 0

    def arm(self) -> None:
        """Enter steady state: every new signature from here on is a
        flagged recompile, not expected warmup."""
        self.armed = True

    def __getattr__(self, name):
        # transparent wrapper: jit-object introspection (_cache_size,
        # lower, ...) must keep working through the probe
        fn = self.__dict__.get("_fn")
        if fn is None:  # mid-__init__ / copy protocols
            raise AttributeError(name)
        return getattr(fn, name)

    def _signature(self, args, kwargs) -> tuple[tuple, tuple]:
        """(avals, statics) for this call. Always computed as arg0's
        leaves followed by the rest's, so memo hits and misses produce
        identical keys for identical calls."""
        import jax

        flatten = jax.tree_util.tree_flatten
        if not args:
            return _leaf_signature(flatten(kwargs)[0])
        memo = self._memo
        if memo is not None and memo[0] is args[0]:
            avals0, statics0 = memo[1]
        else:
            avals0, statics0 = _leaf_signature(flatten(args[0])[0])
            self._memo = (args[0], (avals0, statics0))
        avals_r, statics_r = _leaf_signature(flatten((args[1:], kwargs))[0])
        return avals0 + avals_r, statics0 + statics_r

    def __call__(self, *args, **kwargs):
        avals, statics = self._signature(args, kwargs)
        key = (avals, statics)
        with self._sig_lock:
            new = key not in self._seen
            if new:
                reason = self._note_compile(avals, statics, key)
        if new and reason != "warmup":
            self._tripwire._on_steady_recompile(self, reason, avals, statics)
        return self._fn(*args, **kwargs)

    def _note_compile(self, avals, statics, key) -> str:
        """Record a first-seen signature (caller holds _sig_lock).

        A probe's very FIRST signature is always ``warmup`` even when
        armed: a program must compile once to exist, and some entry
        points legitimately run for the first time only after the engine
        warms (window_seed needs a prefix-cache hit, which requires a
        COMPLETED request — the very event that arms the tripwire;
        chunked prefill needs the first long prompt). Only a SECOND
        signature on an armed probe is evidence of shape leakage."""
        self.compiles += 1
        if not self.armed or not self._seen:
            reason = "warmup"
        elif statics in self._seen_statics and avals not in self._seen_avals:
            reason = "new_shape"
        elif avals in self._seen_avals and statics not in self._seen_statics:
            reason = "new_static"
        else:
            reason = "new_signature"
        self._seen.add(key)
        self._seen_avals.add(avals)
        self._seen_statics.add(statics)
        RECOMPILES_TOTAL.inc(fn=self.name, reason=reason)
        if reason != "warmup":
            self.steady_recompiles += 1
        return reason


class RecompileTripwire:
    """Per-engine probe set + process-wide storm detection. Engines build
    one (``InferenceEngine._build_fns``), wrap each jitted entry point,
    and arm it after their first completed request; storms are judged
    across ALL tripwires in the process (a per-engine budget would let N
    co-hosted engines each storm just under it)."""

    # shared across instances: storms are a process-level pathology
    _storm_lock = threading.Lock()
    _storm_events: deque[float] = deque(maxlen=256)
    _last_storm_ts = 0.0

    def __init__(self, context: str = ""):
        self.context = context  # e.g. the model name, for events/logs
        self._probes: dict[str, JitProbe] = {}

    def wrap(self, name: str, fn: Callable, armable: bool = True) -> JitProbe:
        probe = JitProbe(name, fn, self, armable=armable)
        self._probes[name] = probe
        return probe

    def arm(self) -> None:
        for probe in self._probes.values():
            if probe.armable:
                probe.arm()

    @property
    def armed(self) -> bool:
        return any(p.armed for p in self._probes.values())

    def state(self) -> dict[str, Any]:
        return {
            name: {"compiles": p.compiles,
                   "steadyRecompiles": p.steady_recompiles,
                   "armed": p.armed,
                   "signatures": len(p._seen)}
            for name, p in self._probes.items()
        }

    def _on_steady_recompile(self, probe: JitProbe, reason: str,
                             avals, statics) -> None:
        # compact shape string: enough to identify the offending program
        # without dumping a 300-leaf params tree into the ring
        shapes = ",".join(f"{s}/{d}" for s, d in avals[:12])
        if len(avals) > 12:
            shapes += f",…+{len(avals) - 12}"
        default_flight_recorder().record(
            "engine", "recompile", fn=probe.name, reason=reason,
            context=self.context, nArrays=len(avals), shapes=shapes,
            statics=";".join(statics[:8]),
        )
        log.warning("steady-state recompile", fn=probe.name, reason=reason,
                    context=self.context, shapes=shapes)
        try:
            budget = env_int("GRIDLLM_RECOMPILE_BUDGET")
            window = env_float("GRIDLLM_RECOMPILE_WINDOW")
        except ValueError:
            # this runs on the engine step path mid-incident: a malformed
            # telemetry knob must degrade to the registry default, not crash
            budget = int(ENV_VARS["GRIDLLM_RECOMPILE_BUDGET"].default)
            window = float(ENV_VARS["GRIDLLM_RECOMPILE_WINDOW"].default)
        now = time.monotonic()
        with RecompileTripwire._storm_lock:
            ev = RecompileTripwire._storm_events
            ev.append(now)
            while ev and now - ev[0] > window:
                ev.popleft()
            storm = (len(ev) > budget
                     and now - RecompileTripwire._last_storm_ts > window / 2)
            if storm:
                RecompileTripwire._last_storm_ts = now
        if storm:
            RECOMPILE_STORMS_TOTAL.inc()
            diagnosis = {"windowS": window, "budget": budget,
                         "recompilesInWindow": len(ev),
                         "lastFn": probe.name, "lastReason": reason,
                         "lastShapes": shapes}
            default_flight_recorder().record(
                "engine", "recompile_storm", **diagnosis)
            log.error("recompile storm: steady-state recompiles exceed "
                      "budget — shape bucketing is broken or inputs are "
                      "unbucketed", **diagnosis)


def recompile_totals() -> dict[str, Any]:
    """Process-wide compile counts from the tripwire counter, split into
    warmup vs steady-state (bench --emit reads this; the CI perf-smoke
    gate asserts steady == 0)."""
    out = {"total": 0, "warmup": 0, "steady": 0, "byFn": {}}
    for labels, count in RECOMPILES_TOTAL.items():
        fn, reason = labels["fn"], labels["reason"]
        count = int(count)
        out["total"] += count
        if reason == "warmup":
            out["warmup"] += count
        else:
            out["steady"] += count
        per = out["byFn"].setdefault(fn, {"warmup": 0, "steady": 0})
        per["warmup" if reason == "warmup" else "steady"] += count
    return out


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------
# Engines register a *memory probe* (worker/service.py, one per service)
# returning, per model, the live weight/KV arrays plus allocator math —
# mirroring the flight recorder's engine probes so the snapshot path never
# imports or locks engine internals.

_memory_probes: dict[str, Callable[[], dict[str, Any]]] = {}
_memory_probes_lock = threading.Lock()


def register_memory_probe(name: str, fn: Callable[[], dict[str, Any]]) -> None:
    with _memory_probes_lock:
        _memory_probes[name] = fn


def unregister_memory_probe(name: str) -> None:
    with _memory_probes_lock:
        _memory_probes.pop(name, None)


def _device_label(device: Any) -> str:
    return f"{device.platform}:{device.id}"


def memory_snapshot() -> dict[str, Any]:
    """Point-in-time device-memory breakdown (``GET /admin/memory``).

    Walks ``jax.live_arrays()`` once, attributing each array's per-shard
    bytes to its device as weights / kv_pool / workspace by identity
    against the registered memory probes; workspace is everything not
    claimed, so the three kinds sum to the measured live total exactly.
    Adds allocator-reported in-use/limit/headroom where the backend
    exposes memory_stats (TPU/GPU; CPU has none) and per-model KV math
    from the page allocator (cold vs cached pages, lane-padding overhead,
    reserved-capacity fragmentation).

    In a process that never imported jax this returns an empty snapshot
    with a note instead of initializing a backend (see jax_loaded)."""
    if not jax_loaded():
        return {"generatedAt": time.time(), "devices": {}, "models": {},
                "note": "jax not initialized in this process — query the "
                        "worker health port for the engine-side view"}
    import jax

    with _memory_probes_lock:
        probes = dict(_memory_probes)
    models: dict[str, Any] = {}
    weight_ids: set[int] = set()
    kv_ids: set[int] = set()
    # shape+dtype fallback for KV attribution: the decode block DONATES
    # and rebinds engine.cache, so under load the live pool arrays can be
    # successors of the ones the probe captured (same shapes, new ids) —
    # id-only matching would misread the whole pool as workspace exactly
    # when the server is busy. Weights are never donated; ids suffice.
    kv_shapes: set[tuple] = set()
    for probe_name, fn in probes.items():
        try:
            for model, info in fn().items():
                weights = info.get("weights") or []
                kv = info.get("kv") or []
                weight_ids.update(id(a) for a in weights)
                kv_ids.update(id(a) for a in kv)
                # only the rank≥4 pool arrays (k/v: [L,P,ps,KVH,D]) —
                # they carry ~all the bytes and their shape is
                # unambiguous; low-rank tables/lengths share shapes with
                # sampler state and stay id-matched
                kv_shapes.update(
                    (tuple(a.shape), str(a.dtype)) for a in kv
                    if hasattr(a, "shape") and len(a.shape) >= 4)
                entry = dict(info.get("alloc") or {})
                entry["weightsBytes"] = sum(
                    getattr(a, "nbytes", 0) for a in weights)
                entry["kvPoolBytes"] = sum(
                    getattr(a, "nbytes", 0) for a in kv)
                entry["probe"] = probe_name
                models[model] = entry
        except Exception as e:  # noqa: BLE001 — snapshots must assemble
            models[f"{probe_name}:error"] = {"error": str(e)}

    devices: dict[str, dict[str, Any]] = {}

    def dev_entry(label: str) -> dict[str, Any]:
        return devices.setdefault(label, {
            "weightsBytes": 0, "kvPoolBytes": 0, "workspaceBytes": 0,
            "totalLiveBytes": 0,
        })

    for arr in jax.live_arrays():
        try:
            if id(arr) in weight_ids:
                kind = "weightsBytes"
            elif id(arr) in kv_ids or (
                    (tuple(arr.shape), str(arr.dtype)) in kv_shapes):
                kind = "kvPoolBytes"
            else:
                kind = "workspaceBytes"
            for shard in arr.addressable_shards:
                entry = dev_entry(_device_label(shard.device))
                nbytes = getattr(shard.data, "nbytes", 0)
                entry[kind] += nbytes
                entry["totalLiveBytes"] += nbytes
        except Exception:  # noqa: BLE001 — deleted mid-walk (donation race)
            continue

    for device in jax.local_devices():
        entry = dev_entry(_device_label(device))
        stats = None
        try:
            stats = device.memory_stats()
        except Exception:  # noqa: BLE001 — backend without allocator stats
            stats = None
        if stats:
            in_use = stats.get("bytes_in_use")
            limit = (stats.get("bytes_limit")
                     or stats.get("bytes_reservable_limit"))
            entry["bytesInUse"] = in_use
            entry["bytesLimit"] = limit
            entry["peakBytesInUse"] = stats.get("peak_bytes_in_use")
            if in_use is not None and limit:
                entry["headroomBytes"] = max(limit - in_use, 0)
                largest = stats.get("largest_free_block_bytes")
                free = limit - in_use
                if largest is not None and free > 0:
                    # external fragmentation: how much of the free HBM is
                    # NOT reachable as one contiguous block
                    entry["fragmentation"] = round(1 - largest / free, 4)
        else:
            entry["bytesInUse"] = None
            entry["bytesLimit"] = None
            entry["headroomBytes"] = None
    return {
        "generatedAt": time.time(),
        "devices": devices,
        "models": models,
    }


def _memory_collector() -> None:
    """Registry collector: refresh the device-memory gauges from a fresh
    snapshot at scrape time (point-in-time-correct, like the scheduler's
    queue-depth collectors). Skips entirely in processes that never
    imported jax — a scrape must not initialize a backend."""
    if not jax_loaded():
        return
    snap = memory_snapshot()
    for label, entry in snap["devices"].items():
        DEVICE_MEMORY_BYTES.set(entry["weightsBytes"],
                                device=label, kind="weights")
        DEVICE_MEMORY_BYTES.set(entry["kvPoolBytes"],
                                device=label, kind="kv_pool")
        DEVICE_MEMORY_BYTES.set(entry["workspaceBytes"],
                                device=label, kind="workspace")
        if entry.get("headroomBytes") is not None:
            DEVICE_MEMORY_HEADROOM.set(entry["headroomBytes"], device=label)
        if entry.get("bytesLimit"):
            DEVICE_MEMORY_LIMIT.set(entry["bytesLimit"], device=label)


# Registered once at import: scrapes of any process importing the engine
# get the gauges; processes with no live arrays pay one cheap walk.
_OBS.add_collector("perf.device_memory", _memory_collector)


# ---------------------------------------------------------------------------
# on-demand profiler capture
# ---------------------------------------------------------------------------


class CaptureBusy(RuntimeError):
    """A profiler capture is already running (jax allows one trace at a
    time per process)."""


class ProfilerCapture:
    """Bounded on-demand ``jax.profiler`` captures.

    ``capture(seconds)`` starts a trace into a fresh subdirectory of the
    artifact root (``GRIDLLM_PROFILE_DIR``, default
    ``/tmp/gridllm-profiles``), spawns a daemon timer that stops it after
    ``seconds``, prunes the oldest captures past ``GRIDLLM_PROFILE_KEEP``
    (default 4), and returns the path immediately — the caller (an HTTP
    handler or the hang watchdog) never blocks for the capture window.
    Open the result with TensorBoard (``tensorboard --logdir <path>``,
    profile plugin) or Perfetto (``xprof``/trace viewer); see README
    "Profiling & performance introspection"."""

    MAX_SECONDS = 120.0

    def __init__(self, base_dir: str | None = None, keep: int | None = None):
        self._base_dir = base_dir
        self._keep = keep
        self._lock = threading.Lock()
        self._active: dict[str, Any] | None = None
        self.captures: list[dict[str, Any]] = []  # bounded history

    @property
    def base_dir(self) -> str:
        return (self._base_dir
                or env_raw("GRIDLLM_PROFILE_DIR")
                or "/tmp/gridllm-profiles")

    @property
    def keep(self) -> int:
        if self._keep is not None:
            return self._keep
        try:
            return env_int("GRIDLLM_PROFILE_KEEP")
        except ValueError:
            # read during artifact rotation (watchdog auto-capture thread
            # included) — degrade to the registry default, not an exception
            return int(ENV_VARS["GRIDLLM_PROFILE_KEEP"].default)

    @property
    def active(self) -> dict[str, Any] | None:
        with self._lock:
            return dict(self._active) if self._active else None

    def _prune(self) -> None:
        base = self.base_dir
        try:
            # only the module's own trace-* capture dirs are prunable —
            # GRIDLLM_PROFILE_DIR may point at a shared directory, and
            # deleting unrelated entries there would be catastrophic
            entries = sorted(
                e for e in os.listdir(base)
                if e.startswith("trace-")
                and os.path.isdir(os.path.join(base, e))
            )
        except OSError:
            return
        for stale in entries[:max(0, len(entries) - self.keep)]:
            shutil.rmtree(os.path.join(base, stale), ignore_errors=True)

    def capture(self, seconds: float, reason: str = "on_demand") -> dict[str, Any]:
        """Start a capture; returns {path, seconds, reason, startedAt}.
        Raises :class:`CaptureBusy` when one is already running."""
        seconds = min(max(float(seconds), 0.05), self.MAX_SECONDS)
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason)[:48]
        path = os.path.join(
            self.base_dir, f"trace-{int(time.time() * 1000)}-{safe_reason}")
        with self._lock:
            if self._active is not None:
                raise CaptureBusy(
                    f"capture already running: {self._active['path']}")
            import jax

            os.makedirs(path, exist_ok=True)
            self._prune()
            jax.profiler.start_trace(path)
            info = {"path": path, "seconds": seconds, "reason": reason,
                    "startedAt": time.time()}
            self._active = info
        default_flight_recorder().record("engine", "profile_capture",
                                         path=path, seconds=seconds,
                                         reason=reason)
        threading.Thread(target=self._finish_after, args=(seconds,),
                         name="profiler-capture", daemon=True).start()
        return dict(info)

    def _finish_after(self, seconds: float) -> None:
        time.sleep(seconds)
        self.stop()

    def stop(self) -> dict[str, Any] | None:
        """Stop the active capture (idempotent; also the timer's path).
        The trace flush runs OUTSIDE the lock: writing a large trace can
        take seconds, and a concurrent capture() on the event loop must
        get an immediate CaptureBusy/answer, not block on the flush.
        Claiming ``_active`` under the lock first keeps stop idempotent
        and leaves exactly one thread responsible for the flush; a
        capture() arriving mid-flush correctly sees "busy" until the
        post-flush bookkeeping clears it."""
        with self._lock:
            info = self._active
            if info is None or info.get("stopping"):
                return None  # no capture, or another thread owns the flush
            info["stopping"] = True
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — a failed stop must not
            info["error"] = str(e)  # wedge the endpoint forever
        with self._lock:
            self._active = None
            info.pop("stopping", None)
            info["endedAt"] = time.time()
            self.captures.append(dict(info))
            del self.captures[:-16]
        return dict(info)


_PROFILER = ProfilerCapture()


def default_profiler() -> ProfilerCapture:
    """The process-global capture manager (HTTP endpoints + watchdog)."""
    return _PROFILER


def handle_profile_request(seconds_raw: str | None) -> tuple[int, dict[str, Any]]:
    """Transport-agnostic body of ``POST /admin/profile?seconds=N``:
    (http_status, json_payload). Shared by the gateway admin surface and
    the worker health port so neither re-implements validation, the
    busy conflict, or the no-jax guard (which refuses rather than
    synchronously initializing a backend in a control-plane process).
    Does blocking work (dir pruning, start_trace) — async HTTP handlers
    must call it via ``asyncio.to_thread``."""
    if not jax_loaded():
        return 501, {"error": "no jax runtime in this process — POST the "
                              "worker health port's /admin/profile for an "
                              "engine-side capture",
                     "code": "NO_JAX_RUNTIME"}
    raw = seconds_raw if seconds_raw is not None else "5"
    try:
        seconds = float(raw)
    except ValueError:
        return 400, {"error": f"seconds must be a number, got {raw!r}",
                     "code": "BAD_REQUEST"}
    if not 0 < seconds <= ProfilerCapture.MAX_SECONDS:
        return 400, {"error": f"seconds must be in "
                              f"(0, {ProfilerCapture.MAX_SECONDS:g}]",
                     "code": "BAD_REQUEST"}
    try:
        return 200, default_profiler().capture(seconds, reason="on_demand")
    except CaptureBusy as e:
        return 409, {"error": str(e), "code": "CAPTURE_BUSY"}
