"""Per-worker health baselines + quarantine state machine (ISSUE 19).

Each scheduler shard owns a :class:`HealthMonitor`.  Rolling per-worker
baselines — canary end-to-end latency (obs/probe.py), decode ITL from
the span-derived timing the SLO judge already computes, and heartbeat
inter-arrival gap measured receiver-side — feed an EWMA+z-score
regression detector (same decay idiom as obs/capacity.py).  Verdicts
drive a four-state machine per worker::

    online ──strikes──▶ degraded ──strikes──▶ quarantined
      ▲                    │                      │ (re-register)
      └───clean canaries───┘        probation ◀───┘
      ▲                                │
      └────────clean canaries─────────┘
    (any state) ──golden drift──▶ quarantined

Degraded workers stay in placement with a load-score penalty
(``GRIDLLM_HEALTH_DEGRADED_PENALTY``, mirroring the ISSUE 3
prefix-affinity weight); quarantined workers are excluded and drained
through the ISSUE 9 graceful-drain path ({"type": "drain"} on their job
channel), so in-flight work resumes exactly-once on peers.  A
quarantined worker that re-registers (operator restart) enters
probation: canaries keep flowing, user traffic is routed elsewhere
while alternatives exist, and ``GRIDLLM_HEALTH_PROBATION_PASSES`` clean
rounds readmit it.

Transitions replicate on the durable ``health:state`` channel so every
registry — scheduler shards and observer-mode gateway replicas — holds
the same ``WorkerInfo.healthState``; forensics (ISSUE 17) opens an
incident on ``health.quarantined`` and ``probe.golden_drift``.

Import-cycle note: bus/base.py imports ``gridllm_tpu.obs`` at module
load, and faults.py imports ``gridllm_tpu.obs`` too — so channel
helpers AND the fault layer are imported lazily inside methods here
(same pattern as obs/timeline.py).  Pure stdlib.
"""

from __future__ import annotations

import asyncio
import json
import math
import time
from typing import Any, Callable

from gridllm_tpu.utils.config import env_float, env_int
from gridllm_tpu.utils.logging import get_logger

from .flightrec import default_flight_recorder
from .metrics import MetricsRegistry

log = get_logger("obs.health")

HEALTH_STATES = ("online", "degraded", "quarantined", "probation")
# numeric codes for the gridllm_worker_health_state gauge (alert exprs
# compare against these: 3 == quarantined)
STATE_CODES = {"online": 0, "probation": 1, "degraded": 2, "quarantined": 3}

# baseline signal names (snapshot keys; one _Baseline each per worker)
SIG_CANARY_E2E = "canary_e2e"
SIG_ITL = "itl"
SIG_HEARTBEAT_GAP = "heartbeat_gap"


class _Baseline:
    """Exponentially decayed mean/variance with a shared half-life:
    ``zscore(x)`` judges a fresh observation against the baseline BEFORE
    folding it in, so a regression cannot mask itself by dragging the
    mean toward it in the same call."""

    __slots__ = ("halflife", "count", "vsum", "v2sum", "t_last")

    def __init__(self, halflife_s: float) -> None:
        self.halflife = max(float(halflife_s), 1e-3)
        self.count = 0.0
        self.vsum = 0.0
        self.v2sum = 0.0
        self.t_last = time.time()

    def _decay_to(self, now: float) -> None:
        dt = max(now - self.t_last, 0.0)
        if dt > 0:
            f = 0.5 ** (dt / self.halflife)
            self.count *= f
            self.vsum *= f
            self.v2sum *= f
            self.t_last = now

    def mean(self) -> float:
        return self.vsum / self.count if self.count > 1e-9 else 0.0

    def std(self) -> float:
        if self.count <= 1e-9:
            return 0.0
        m = self.mean()
        return math.sqrt(max(self.v2sum / self.count - m * m, 0.0))

    def zscore(self, value: float) -> float:
        """Deviation of ``value`` from the current baseline, in baseline
        standard deviations (floored at 10% of the mean so a perfectly
        steady baseline cannot manufacture infinite z from jitter)."""
        std = max(self.std(), abs(self.mean()) * 0.1, 1e-9)
        return (value - self.mean()) / std

    def observe(self, value: float, now: float | None = None) -> None:
        now = time.time() if now is None else now
        if self.count <= 1e-9:
            # epoch starts at the first sample — decaying an empty
            # baseline across the construction->first-observe gap would
            # be a no-op on real clocks but wrong under injected time
            self.t_last = now
        self._decay_to(now)
        self.count += 1.0
        self.vsum += float(value)
        self.v2sum += float(value) * float(value)


class _WorkerHealth:
    __slots__ = ("state", "strikes", "passes", "baselines",
                 "pending_anomaly", "last_heartbeat", "last_reason")

    def __init__(self) -> None:
        self.state = "online"
        self.strikes = 0          # consecutive anomalous canary rounds
        self.passes = 0           # consecutive clean canary rounds
        self.baselines: dict[str, _Baseline] = {}
        # regression flagged by an out-of-band signal (ITL, heartbeat
        # gap) since the last canary round — folded into that round's
        # verdict so all transitions happen at one cadence
        self.pending_anomaly = ""
        self.last_heartbeat = 0.0
        self.last_reason = ""


class HealthMonitor:
    """Per-worker regression detection + health state machine for one
    scheduler shard.  Pure bookkeeping is synchronous (unit-testable
    without a loop); bus publishes ride best-effort tasks."""

    def __init__(self, bus: Any, registry: Any, metrics: MetricsRegistry,
                 member: Callable[[], str] | str = "") -> None:
        self.bus = bus
        self.registry = registry
        self._member = member
        self.halflife_s = env_float("GRIDLLM_HEALTH_EWMA_HALFLIFE_S")
        self.z_threshold = env_float("GRIDLLM_HEALTH_Z_THRESHOLD")
        self.min_samples = env_int("GRIDLLM_HEALTH_MIN_SAMPLES")
        self.degrade_strikes = max(env_int("GRIDLLM_HEALTH_DEGRADE_STRIKES"), 1)
        self.quarantine_strikes = max(
            env_int("GRIDLLM_HEALTH_QUARANTINE_STRIKES"), 1)
        self.probation_passes = max(
            env_int("GRIDLLM_HEALTH_PROBATION_PASSES"), 1)
        self._workers: dict[str, _WorkerHealth] = {}
        self.flightrec = default_flight_recorder()
        self._state_gauge = metrics.gauge(
            "gridllm_worker_health_state",
            "Health-monitor verdict per worker: 0 online, 1 probation, "
            "2 degraded, 3 quarantined (ISSUE 19).",
            ("worker",))
        self._transitions = metrics.counter(
            "gridllm_health_transitions_total",
            "Worker health-state transitions, by target state "
            "(online/degraded/quarantined/probation).",
            ("state",))

    # -- helpers -------------------------------------------------------------
    def member(self) -> str:
        return self._member() if callable(self._member) else str(self._member)

    def _get(self, worker_id: str) -> _WorkerHealth:
        wh = self._workers.get(worker_id)
        if wh is None:
            wh = self._workers[worker_id] = _WorkerHealth()
            self._state_gauge.set(0, worker=worker_id)
        return wh

    def state_of(self, worker_id: str) -> str:
        wh = self._workers.get(worker_id)
        return wh.state if wh is not None else "online"

    def _observe(self, wh: _WorkerHealth, signal: str,
                 value: float) -> float | None:
        """Fold one observation into a baseline; returns the z-score it
        was judged at, or None while the baseline is still warming up
        (or when the health.baseline fault site drops the observation)."""
        from gridllm_tpu import faults  # lazy: faults imports obs

        if faults.check("health.baseline"):
            return None
        bl = wh.baselines.get(signal)
        if bl is None:
            bl = wh.baselines[signal] = _Baseline(self.halflife_s)
        z = bl.zscore(value) if bl.count >= self.min_samples else None
        bl.observe(value)
        return z

    # -- out-of-band signals -------------------------------------------------
    def note_itl(self, worker_id: str, itl_s: float) -> None:
        """Decode inter-token latency from the SLO judge's span-derived
        timing — real traffic trains the baseline between canaries."""
        wh = self._get(worker_id)
        z = self._observe(wh, SIG_ITL, float(itl_s))
        if z is not None and z > self.z_threshold:
            wh.pending_anomaly = f"itl z={z:.1f}"

    def note_heartbeat(self, worker_id: str, now: float | None = None) -> None:
        """Heartbeat inter-arrival gap, measured receiver-side (the
        payload is untouched): a worker whose event loop is seizing
        shows up here before any request does."""
        now = time.time() if now is None else now
        wh = self._get(worker_id)
        if wh.last_heartbeat > 0:
            z = self._observe(wh, SIG_HEARTBEAT_GAP, now - wh.last_heartbeat)
            if z is not None and z > self.z_threshold:
                wh.pending_anomaly = f"heartbeat_gap z={z:.1f}"
        wh.last_heartbeat = now

    def note_registered(self, worker_id: str, status: str = "online") -> None:
        """An ONLINE (re-)registration readmits a quarantined worker to
        probation — the only exit from quarantine: the worker restarted,
        so its canaries get a fresh chance to prove it.  Non-online
        registrations (the quarantine drain itself re-registers with
        status "draining") must not launder the verdict."""
        if status != "online":
            return
        wh = self._workers.get(worker_id)
        if wh is not None and wh.state == "quarantined":
            self._transition(worker_id, "probation", "reregistered")

    # -- the canary cadence --------------------------------------------------
    def note_canary(self, worker_id: str, *, ok: bool, e2e_s: float,
                    drift: bool = False) -> None:
        """One canary round's verdict for a worker.  All state-machine
        transitions happen here (one cadence); out-of-band anomalies
        flagged since the last round fold into this verdict."""
        wh = self._get(worker_id)
        if drift:
            # byte-level correctness drift outranks every latency signal:
            # quarantine immediately from any state
            self._transition(worker_id, "quarantined", "golden_drift")
            return
        reason = "" if ok else "canary_failed"
        if ok:
            z = self._observe(wh, SIG_CANARY_E2E, e2e_s)
            if z is not None and z > self.z_threshold:
                reason = f"canary_e2e z={z:.1f}"
        if not reason and wh.pending_anomaly:
            reason = wh.pending_anomaly
        wh.pending_anomaly = ""
        if reason:
            wh.passes = 0
            wh.strikes += 1
            wh.last_reason = reason
            if wh.state == "online" and wh.strikes >= self.degrade_strikes:
                self._transition(worker_id, "degraded", reason)
            elif (wh.state == "degraded"
                  and wh.strikes >= self.quarantine_strikes):
                self._transition(worker_id, "quarantined", reason)
            elif wh.state == "probation":
                # a probation worker is on its last chance — any strike
                # sends it straight back to quarantine
                self._transition(worker_id, "quarantined", reason)
        else:
            wh.strikes = 0
            wh.passes += 1
            if (wh.state in ("degraded", "probation")
                    and wh.passes >= self.probation_passes):
                self._transition(worker_id, "online", "recovered")

    # -- transitions ---------------------------------------------------------
    def _transition(self, worker_id: str, new: str, reason: str) -> None:
        wh = self._get(worker_id)
        old = wh.state
        if old == new:
            return
        wh.state = new
        wh.strikes = 0
        wh.passes = 0
        wh.last_reason = reason
        self._state_gauge.set(STATE_CODES[new], worker=worker_id)
        self._transitions.inc(state=new)
        # literal event names per branch: the event-discipline analyzer
        # resolves record() sites statically against the EVENTS registry
        if new == "online":
            self.flightrec.record("health", "recovered",
                                  worker=worker_id, reason=reason)
        elif new == "degraded":
            self.flightrec.record("health", "degraded",
                                  worker=worker_id, reason=reason)
        elif new == "probation":
            self.flightrec.record("health", "probation",
                                  worker=worker_id, reason=reason)
        else:
            self.flightrec.record("health", "quarantined",
                                  worker=worker_id, reason=reason)
        log.warning("worker health transition", worker_id=worker_id,
                    old=old, new=new, reason=reason)
        # apply locally first: the next dispatch pass must see the
        # verdict even if the bus echo is slow (or the bus is dead)
        apply_state = getattr(self.registry, "apply_health_state", None)
        if apply_state is not None:
            apply_state(worker_id, new)
        self._spawn(self._announce(worker_id, new, reason))
        if new == "quarantined":
            self._spawn(self._request_drain(worker_id))

    def _spawn(self, coro) -> None:
        # get_running_loop, not ensure_future: outside a loop the latter
        # silently CREATES one on the main thread and parks the task there
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:  # no running loop (sync unit tests)
            coro.close()
            return
        loop.create_task(coro)

    async def _announce(self, worker_id: str, state: str,
                        reason: str) -> None:
        from gridllm_tpu.bus.base import CH_HEALTH_STATE  # lazy: cycle

        try:
            await self.bus.publish(CH_HEALTH_STATE, json.dumps({
                "worker": worker_id, "state": state, "reason": reason,
                "member": self.member(), "ts": time.time()}))
        except Exception as e:  # noqa: BLE001 — the local apply already
            log.warning("health:state publish failed",  # routed around it
                        worker_id=worker_id, error=str(e))

    async def _request_drain(self, worker_id: str) -> None:
        """Quarantine drains through the ISSUE 9 graceful path: the
        worker live-migrates or requeues its in-flight jobs (resumed
        exactly-once on peers) and refuses new work."""
        from gridllm_tpu.bus.base import worker_job_channel  # lazy: cycle

        try:
            await self.bus.publish(
                worker_job_channel(worker_id),
                json.dumps({"type": "drain", "reason": "quarantine"}))
        except Exception as e:  # noqa: BLE001 — placement exclusion
            log.warning("quarantine drain publish failed",  # still holds
                        worker_id=worker_id, error=str(e))

    # -- views ---------------------------------------------------------------
    def counts(self) -> dict[str, int]:
        out = {s: 0 for s in HEALTH_STATES}
        for wh in self._workers.values():
            out[wh.state] = out.get(wh.state, 0) + 1
        return out

    def snapshot(self) -> dict[str, Any]:
        """JSON view for ctrl:status / GET /admin/health/fleet."""
        workers: dict[str, Any] = {}
        for worker_id, wh in self._workers.items():
            workers[worker_id] = {
                "state": wh.state,
                "strikes": wh.strikes,
                "passes": wh.passes,
                "reason": wh.last_reason,
                "baselines": {
                    sig: {"mean": round(bl.mean(), 6),
                          "std": round(bl.std(), 6),
                          "n": round(bl.count, 2)}
                    for sig, bl in wh.baselines.items()},
            }
        return {"workers": workers, "counts": self.counts()}
