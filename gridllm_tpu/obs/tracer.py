"""Per-request span timelines, stitched gateway↔worker over the bus.

A ``Span`` is a named wall-clock interval (or point event) tied to a
``request_id``. The gateway's :class:`Tracer` records the control-plane
stages (receive, queue-wait, dispatch, first-token, complete); each worker
records its execution stages (execute, prefill, decode) on its OWN tracer
and publishes the finished timeline on ``trace:{request_id}`` when the job
resolves. The gateway psubscribes ``trace:*`` (scheduler.initialize) and
merges what arrives, so ``GET /admin/trace/{request_id}`` returns ONE
timeline spanning both sides.

Timestamps are epoch seconds (``time.time()``) — stitching relies on the
hosts' clocks, which is exactly what a distributed trace can honestly
offer without a clock-sync protocol; same-host deployments (and the whole
test suite) are exact.

Storage is bounded: finished timelines are an LRU of ``max_traces``; spans
still open when a request is finished/aborted are closed with an
``aborted`` marker rather than leaked (the chaos tests assert
``active_count() == 0`` after timeout storms). Pure stdlib.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from typing import Any, Iterator

# The trace channel family is registered in the bus channel registry
# (bus/base.py, family "trace") but its helpers live HERE: bus/base
# imports obs.metrics, so importing back from obs would be circular.
# The channel-discipline rule resolves this constant inside the helper
# and verifies it against the registered pattern, so the spellings
# cannot drift.
TRACE_CHANNEL_PREFIX = "trace:"


def trace_channel(request_id: str) -> str:
    return f"{TRACE_CHANNEL_PREFIX}{request_id}"


def trace_pattern() -> str:
    """Glob pattern covering every trace channel (psubscribe)."""
    return f"{TRACE_CHANNEL_PREFIX}*"


class Span:
    __slots__ = ("request_id", "name", "source", "start", "end", "meta")

    def __init__(self, request_id: str, name: str, source: str,
                 start: float | None = None, end: float | None = None,
                 meta: dict[str, Any] | None = None):
        self.request_id = request_id
        self.name = name
        self.source = source
        self.start = time.time() if start is None else start
        self.end = end
        self.meta = meta or {}

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "source": self.source,
            "start": self.start,
            "end": self.end,
        }
        if self.end is not None:
            d["durationMs"] = round((self.end - self.start) * 1000, 3)
        if self.meta:
            d["meta"] = self.meta
        return d

    @classmethod
    def from_dict(cls, request_id: str, d: dict[str, Any]) -> "Span":
        return cls(
            request_id,
            str(d.get("name", "?")),
            str(d.get("source", "?")),
            start=float(d.get("start") or 0.0),
            end=None if d.get("end") is None else float(d["end"]),
            meta=dict(d.get("meta") or {}),
        )


class Tracer:
    """Thread-safe span store for one process role (gateway or worker)."""

    def __init__(self, source: str = "gateway", max_traces: int = 512):
        self.source = source
        self.max_traces = max_traces
        self._lock = threading.Lock()
        self._open: dict[str, list[Span]] = {}      # request → open spans
        self._closed: dict[str, list[Span]] = {}    # request → closed spans
        self._done: OrderedDict[str, list[Span]] = OrderedDict()  # LRU

    # -- recording ----------------------------------------------------------
    def begin(self, request_id: str, name: str, **meta: Any) -> Span:
        span = Span(request_id, name, self.source, meta=meta)
        with self._lock:
            self._open.setdefault(request_id, []).append(span)
        return span

    def end(self, span: Span, **meta: Any) -> Span:
        with self._lock:
            if span.end is None:
                span.end = time.time()
                span.meta.update(meta)
                opens = self._open.get(span.request_id, [])
                if span in opens:
                    opens.remove(span)
                    if not opens:
                        del self._open[span.request_id]
                self._closed.setdefault(span.request_id, []).append(span)
                self._absorb_locked(span.request_id)
            elif meta:
                # a seal (scheduler-side failure/timeout abort) raced ahead
                # of the span's owner and force-closed it — the owner's
                # metadata (outcome etc.) must still land, and the span DID
                # get a proper end, so drop the seal's aborted marker
                span.meta.pop("aborted", None)
                span.meta.update(meta)
        return span

    @contextmanager
    def span(self, request_id: str, name: str, **meta: Any) -> Iterator[Span]:
        s = self.begin(request_id, name, **meta)
        try:
            yield s
        finally:
            self.end(s)

    def event(self, request_id: str, name: str, **meta: Any) -> Span:
        """Point-in-time mark: a zero-duration span."""
        now = time.time()
        span = Span(request_id, name, self.source, start=now, end=now,
                    meta=meta)
        with self._lock:
            self._closed.setdefault(request_id, []).append(span)
            self._absorb_locked(request_id)
        return span

    def record(self, request_id: str, name: str, start: float, end: float,
               **meta: Any) -> Span:
        """Add an already-measured interval (e.g. derived from engine
        timings) with explicit timestamps."""
        span = Span(request_id, name, self.source, start=start, end=end,
                    meta=meta)
        with self._lock:
            self._closed.setdefault(request_id, []).append(span)
            self._absorb_locked(request_id)
        return span

    def _absorb_locked(self, request_id: str) -> None:
        """Called with the lock held after a span lands in ``_closed``.
        Spans recorded AFTER a request's timeline was sealed (e.g. a
        retry event arriving once the waiter timed out and finished the
        trace) fold straight into the bounded finished LRU rather than
        accumulating in ``_closed``; and ``_closed`` itself is hard-capped
        by force-sealing its oldest request, so a request that never
        reaches a terminal seal cannot grow gateway memory without bound."""
        if request_id in self._done and request_id not in self._open:
            self._merge_done_locked(request_id, self._closed.pop(request_id))
        if len(self._closed) > self.max_traces:
            for rid in list(self._closed):  # oldest-first insertion order
                if len(self._closed) <= self.max_traces:
                    break
                if rid in self._open:  # still live — skip, not worth sealing
                    continue
                self._merge_done_locked(rid, self._closed.pop(rid))

    def _merge_done_locked(self, request_id: str, extra: list[Span]) -> None:
        spans = self._done.pop(request_id, []) + extra
        spans.sort(key=lambda s: (s.start, s.end or s.start))
        self._done[request_id] = spans
        self._trim_done_locked()

    def _trim_done_locked(self) -> None:
        """LRU-evict finished timelines — but never a request that still
        has OPEN spans here: evicting it would silently drop its already-
        ingested worker half, and the later finish() would re-insert only
        the gateway half (a half-merged timeline for a live request).
        If every entry is open (pathological), evict oldest anyway —
        bounded memory beats a perfect timeline."""
        while len(self._done) > self.max_traces:
            victim = next(
                (rid for rid in self._done if rid not in self._open), None)
            if victim is None:
                self._done.popitem(last=False)
                continue
            del self._done[victim]

    # -- lifecycle ----------------------------------------------------------
    def finish(self, request_id: str) -> list[dict[str, Any]]:
        """Move a request's spans to the finished LRU (closing any still
        open with an aborted marker) and return the serialized timeline."""
        return self._seal(request_id, reason="")

    def abort(self, request_id: str, reason: str = "aborted") -> None:
        """Close every open span for the request (timeout/cancel paths must
        never leak an active span) and seal the timeline. Idempotent."""
        self._seal(request_id, reason=reason)

    def _seal(self, request_id: str, reason: str) -> list[dict[str, Any]]:
        now = time.time()
        with self._lock:
            opens = self._open.pop(request_id, [])
            for s in opens:
                # a span still open at seal time is abnormal whichever path
                # sealed it (clean finish should have ended everything)
                s.end = now
                s.meta.setdefault("aborted", True)
                if reason:
                    s.meta.setdefault("reason", reason)
            spans = self._done.pop(request_id, [])
            spans += self._closed.pop(request_id, [])
            spans += opens
            if not spans:
                return []
            spans.sort(key=lambda s: (s.start, s.end or s.start))
            self._done[request_id] = spans
            self._trim_done_locked()
            return [s.to_dict() for s in spans]

    def ingest(self, request_id: str, span_dicts: list[dict[str, Any]]) -> None:
        """Merge remote spans (a worker's published timeline) into the
        finished store, preserving chronological order. Each publication
        carries the publishing side's FULL timeline (finish() re-seals), so
        a re-publication — e.g. a worker that NACKed earlier and later ran
        the job — REPLACES that source's spans rather than duplicating them.

        Incoming spans that are still OPEN (a flight-recorder dump of a
        dying worker's active spans — normal publications are sealed by
        finish()) are closed here with an aborted marker: the publisher is
        never coming back to end them, and /admin/trace must not serve a
        half-merged timeline with remote spans dangling open forever."""
        incoming = [Span.from_dict(request_id, d) for d in span_dicts]
        if not incoming:
            return
        for s in incoming:
            if s.end is None:
                s.end = s.start
                s.meta.setdefault("aborted", True)
                s.meta.setdefault("reason", "unsealed_at_publish")
        sources = {s.source for s in incoming}
        with self._lock:
            # requests still in flight gateway-side keep their open/closed
            # spans where they are; they join at finish()/abort()
            kept = [s for s in self._done.pop(request_id, [])
                    if s.source not in sources]
            spans = kept + incoming
            spans.sort(key=lambda s: (s.start, s.end or s.start))
            self._done[request_id] = spans
            self._trim_done_locked()

    # -- queries ------------------------------------------------------------
    def export(self, request_id: str) -> list[dict[str, Any]] | None:
        """The stitched timeline for a request (finished + still-recording
        spans), or None if the tracer has never seen it."""
        with self._lock:
            done = self._done.get(request_id)
            closed = self._closed.get(request_id)
            opens = self._open.get(request_id)
            if done is None and closed is None and opens is None:
                return None
            spans = list(done or []) + list(closed or []) + list(opens or [])
        spans.sort(key=lambda s: (s.start, s.end or s.start))
        return [s.to_dict() for s in spans]

    def active_count(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._open.values())

    def active_ids(self) -> list[str]:
        with self._lock:
            return list(self._open)

    def ids(self) -> list[str]:
        with self._lock:
            return list(self._done)
