"""Per-model demand / capacity model behind ``GET /admin/capacity``
(ISSUE 16).

Each scheduler shard owns a :class:`DemandTracker`: exponentially
decayed per-model arrival rate, service (completion) rate, queue-wait
EWMA and service-time EWMA (half-life ``GRIDLLM_CAPACITY_EWMA_HALFLIFE_S``),
joined at snapshot time with live queue depth and the slot/KV headroom
workers advertise per model in their heartbeats.  The derived *scale
hint* is the signed replica delta that would bring slot utilization to
the ``TARGET_UTILIZATION`` burn rate at current demand — the consumable
surface the future autoscaler (ROADMAP items 1/2) keys off.

``controlplane/status.py`` ships ``snapshot()`` in every ``ctrl:status``
envelope; :func:`merge_capacity` folds the per-shard snapshots into the
fleet view any gateway replica serves.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Iterable, Mapping

from gridllm_tpu.utils.config import env_float

from .metrics import MetricsRegistry

# slot-utilization the scale hint steers toward: enough headroom to
# absorb bursts without idling paid-for accelerators.
TARGET_UTILIZATION = 0.8

_LN2 = math.log(2.0)


class _Decay:
    """Exponentially decayed event counter + weighted mean with a shared
    half-life.  ``rate()`` is events/second (steady state of the decayed
    count is ``rate * halflife / ln2``); ``mean()`` is the decayed
    average of observed values (queue wait, service time)."""

    __slots__ = ("halflife", "count", "vsum", "t_last")

    def __init__(self, halflife_s: float) -> None:
        self.halflife = max(float(halflife_s), 1e-3)
        self.count = 0.0
        self.vsum = 0.0
        self.t_last = time.time()

    def _decay_to(self, now: float) -> None:
        dt = max(now - self.t_last, 0.0)
        if dt > 0:
            f = 0.5 ** (dt / self.halflife)
            self.count *= f
            self.vsum *= f
            self.t_last = now

    def observe(self, value: float = 0.0, now: float | None = None) -> None:
        now = time.time() if now is None else now
        self._decay_to(now)
        self.count += 1.0
        self.vsum += float(value)

    def rate(self, now: float | None = None) -> float:
        now = time.time() if now is None else now
        self._decay_to(now)
        return self.count * _LN2 / self.halflife

    def mean(self, now: float | None = None) -> float:
        now = time.time() if now is None else now
        self._decay_to(now)
        return self.vsum / self.count if self.count > 1e-9 else 0.0


class _ModelDemand:
    __slots__ = ("arrivals", "completions", "waits", "services")

    def __init__(self, halflife_s: float) -> None:
        self.arrivals = _Decay(halflife_s)
        self.completions = _Decay(halflife_s)
        self.waits = _Decay(halflife_s)
        self.services = _Decay(halflife_s)


def aggregate_worker_capacity(
    workers: Iterable[Any],
) -> dict[str, dict[str, int]]:
    """Sum the per-model ``modelCapacity`` heartbeat blocks across live
    workers: free/total decode slots, free KV pages, worker count."""
    agg: dict[str, dict[str, int]] = {}
    for w in workers:
        mc = getattr(w, "modelCapacity", None) or {}
        for model, caps in mc.items():
            if not isinstance(caps, Mapping):
                continue
            cell = agg.setdefault(
                model, {"slotsFree": 0, "slotsTotal": 0, "kvPagesFree": 0, "workers": 0}
            )
            cell["slotsFree"] += int(caps.get("slotsFree") or 0)
            cell["slotsTotal"] += int(caps.get("slotsTotal") or 0)
            cell["kvPagesFree"] += int(caps.get("kvPagesFree") or 0)
            cell["workers"] += 1
    return agg


def dedup_capacity_totals(workers: Iterable[Any]) -> dict[str, int]:
    """Fleet slot/KV totals counting each distinct engine pool ONCE
    (ISSUE 20 satellite). Copy-model aliases serve one engine under
    several names; per-model cells rightly attribute the shared pool to
    every name (any of them can use it), but summing those cells into a
    fleet total double-counts. Heartbeat blocks carry an ``engine``
    identity token — aliases share it, so dedup is per (worker, token).
    Blocks without a token (older workers) are counted per name."""
    totals = {"slotsFree": 0, "slotsTotal": 0, "kvPagesFree": 0, "engines": 0}
    for w in workers:
        mc = getattr(w, "modelCapacity", None) or {}
        seen: set[int] = set()
        for caps in mc.values():
            if not isinstance(caps, Mapping):
                continue
            tok = int(caps.get("engine") or 0)
            if tok:
                if tok in seen:
                    continue
                seen.add(tok)
            totals["slotsFree"] += int(caps.get("slotsFree") or 0)
            totals["slotsTotal"] += int(caps.get("slotsTotal") or 0)
            totals["kvPagesFree"] += int(caps.get("kvPagesFree") or 0)
            totals["engines"] += 1
    return totals


def _utilization(cap: Mapping[str, int]) -> float:
    total = int(cap.get("slotsTotal") or 0)
    if total <= 0:
        return 0.0
    free = max(min(int(cap.get("slotsFree") or 0), total), 0)
    return (total - free) / total


def _scale_hint(
    *, workers: int, utilization: float, arrival_rate: float, queue_depth: int
) -> int:
    """Signed replica delta to bring slot utilization to
    ``TARGET_UTILIZATION`` at current demand.  No workers + live demand
    asks for one; a standing queue always asks for at least one more;
    scale-down never drops below a single replica."""
    if workers <= 0:
        return 1 if (arrival_rate > 0 or queue_depth > 0) else 0
    needed = math.ceil(workers * utilization / TARGET_UTILIZATION)
    hint = needed - workers
    if queue_depth > 0:
        hint = max(hint, 1)
    return max(hint, -(workers - 1))


class DemandTracker:
    """Per-shard demand/capacity model.  ``queue_depths`` and
    ``worker_capacity`` are live views supplied by the scheduler; the
    tracker owns only the decayed rate state."""

    def __init__(
        self,
        metrics: MetricsRegistry,
        *,
        halflife_s: float | None = None,
        queue_depths: Callable[[], Mapping[str, int]] | None = None,
        worker_capacity: Callable[[], Mapping[str, Mapping[str, int]]] | None = None,
        pool_totals: Callable[[], Mapping[str, int]] | None = None,
    ) -> None:
        self.halflife = float(
            halflife_s
            if halflife_s is not None
            else env_float("GRIDLLM_CAPACITY_EWMA_HALFLIFE_S")
        )
        self._queue_depths = queue_depths or (lambda: {})
        self._worker_capacity = worker_capacity or (lambda: {})
        self._pool_totals = pool_totals or (lambda: {})
        self._models: dict[str, _ModelDemand] = {}
        self._lock = threading.Lock()
        self._g_arrival = metrics.gauge(
            "gridllm_capacity_arrival_rate",
            "Per-model request arrival rate (EWMA, requests/s) at this shard.",
            ("model",),
        )
        self._g_service = metrics.gauge(
            "gridllm_capacity_service_rate",
            "Per-model request completion rate (EWMA, requests/s) at this shard.",
            ("model",),
        )
        self._g_queue = metrics.gauge(
            "gridllm_capacity_queue_depth",
            "Per-model jobs queued at this shard.",
            ("model",),
        )
        self._g_wait = metrics.gauge(
            "gridllm_capacity_wait_seconds",
            "Per-model queue-wait EWMA (seconds) at this shard.",
            ("model",),
        )
        self._g_util = metrics.gauge(
            "gridllm_capacity_utilization",
            "Per-model fleet decode-slot utilization (0..1) as seen by "
            "this shard's worker registry.",
            ("model",),
        )
        self._g_headroom = metrics.gauge(
            "gridllm_capacity_headroom",
            "Per-model free capacity across live workers (decode slots "
            "or KV pages).",
            ("model", "resource"),
        )
        self._g_hint = metrics.gauge(
            "gridllm_capacity_scale_hint",
            "Signed replica delta to hold the SLO at current burn rate "
            "(positive = scale out).",
            ("model",),
        )
        self._g_fleet = metrics.gauge(
            "gridllm_capacity_fleet_slots",
            "Fleet decode slots deduped by engine identity (copy-model "
            "aliases counted once), by state (free / total).",
            ("state",),
        )
        metrics.add_collector("capacity", self._collect)

    def _demand(self, model: str) -> _ModelDemand:
        d = self._models.get(model)
        if d is None:
            d = self._models.setdefault(model, _ModelDemand(self.halflife))
        return d

    def note_arrival(self, model: str) -> None:
        with self._lock:
            self._demand(model).arrivals.observe()

    def note_dispatch(self, model: str, wait_s: float) -> None:
        with self._lock:
            self._demand(model).waits.observe(max(float(wait_s), 0.0))

    def note_completion(self, model: str, service_s: float) -> None:
        with self._lock:
            d = self._demand(model)
            d.completions.observe()
            d.services.observe(max(float(service_s), 0.0))

    def snapshot(self) -> dict[str, Any]:
        now = time.time()
        queues = dict(self._queue_depths())
        caps = {m: dict(c) for m, c in self._worker_capacity().items()}
        models: dict[str, Any] = {}
        with self._lock:
            names = set(self._models) | set(queues) | set(caps)
            for model in sorted(names):
                d = self._models.get(model)
                cap = caps.get(
                    model,
                    {"slotsFree": 0, "slotsTotal": 0, "kvPagesFree": 0, "workers": 0},
                )
                util = _utilization(cap)
                arrival = d.arrivals.rate(now) if d else 0.0
                qd = int(queues.get(model, 0))
                models[model] = {
                    "arrivalRate": round(arrival, 4),
                    "serviceRate": round(d.completions.rate(now) if d else 0.0, 4),
                    "queueDepth": qd,
                    "waitEwmaS": round(d.waits.mean(now) if d else 0.0, 4),
                    "serviceEwmaS": round(d.services.mean(now) if d else 0.0, 4),
                    "utilization": round(util, 4),
                    "headroom": {
                        "slots": int(cap.get("slotsFree") or 0),
                        "kvPages": int(cap.get("kvPagesFree") or 0),
                    },
                    "slotsTotal": int(cap.get("slotsTotal") or 0),
                    "workers": int(cap.get("workers") or 0),
                    "scaleHint": _scale_hint(
                        workers=int(cap.get("workers") or 0),
                        utilization=util,
                        arrival_rate=arrival,
                        queue_depth=qd,
                    ),
                }
        fleet = {k: int(v) for k, v in dict(self._pool_totals()).items()}
        return {"halflifeS": self.halflife, "models": models, "fleet": fleet}

    def _collect(self) -> None:
        snap = self.snapshot()
        for model, m in snap["models"].items():
            self._g_arrival.set(m["arrivalRate"], model=model)
            self._g_service.set(m["serviceRate"], model=model)
            self._g_queue.set(m["queueDepth"], model=model)
            self._g_wait.set(m["waitEwmaS"], model=model)
            self._g_util.set(m["utilization"], model=model)
            self._g_headroom.set(m["headroom"]["slots"], model=model, resource="slots")
            self._g_headroom.set(
                m["headroom"]["kvPages"], model=model, resource="kv_pages"
            )
            self._g_hint.set(m["scaleHint"], model=model)
        fleet = snap.get("fleet") or {}
        if fleet:
            self._g_fleet.set(fleet.get("slotsFree", 0), state="free")
            self._g_fleet.set(fleet.get("slotsTotal", 0), state="total")


def merge_capacity(snapshots: Iterable[Mapping[str, Any]]) -> dict[str, Any]:
    """Fold per-shard capacity snapshots into the fleet view.  Demand
    (arrival/service rates, queue depth) is partitioned across shards so
    it sums; worker headroom is observed identically by every shard's
    registry, so element-wise max avoids double counting.  The scale
    hint is recomputed from the merged numbers."""
    models: dict[str, dict[str, Any]] = {}
    fleet: dict[str, int] = {}
    shards = 0
    halflife = 0.0
    for snap in snapshots:
        if not snap:
            continue
        shards += 1
        halflife = max(halflife, float(snap.get("halflifeS") or 0.0))
        # every shard's registry observes the same workers — element-wise
        # max (like headroom), never a sum
        for k, v in (snap.get("fleet") or {}).items():
            fleet[k] = max(int(fleet.get(k, 0)), int(v or 0))
        for model, m in (snap.get("models") or {}).items():
            cell = models.setdefault(
                model,
                {
                    "arrivalRate": 0.0,
                    "serviceRate": 0.0,
                    "queueDepth": 0,
                    "waitEwmaS": 0.0,
                    "_wait_w": 0.0,
                    "headroom": {"slots": 0, "kvPages": 0},
                    "slotsTotal": 0,
                    "workers": 0,
                },
            )
            arr = float(m.get("arrivalRate") or 0.0)
            cell["arrivalRate"] += arr
            cell["serviceRate"] += float(m.get("serviceRate") or 0.0)
            cell["queueDepth"] += int(m.get("queueDepth") or 0)
            w = max(arr, 1e-9)
            cell["waitEwmaS"] += float(m.get("waitEwmaS") or 0.0) * w
            cell["_wait_w"] += w
            hr = m.get("headroom") or {}
            cell["headroom"]["slots"] = max(
                cell["headroom"]["slots"], int(hr.get("slots") or 0)
            )
            cell["headroom"]["kvPages"] = max(
                cell["headroom"]["kvPages"], int(hr.get("kvPages") or 0)
            )
            cell["slotsTotal"] = max(cell["slotsTotal"], int(m.get("slotsTotal") or 0))
            cell["workers"] = max(cell["workers"], int(m.get("workers") or 0))
    for model, cell in models.items():
        wsum = cell.pop("_wait_w")
        cell["waitEwmaS"] = round(cell["waitEwmaS"] / wsum, 4) if wsum > 1e-9 else 0.0
        cell["arrivalRate"] = round(cell["arrivalRate"], 4)
        cell["serviceRate"] = round(cell["serviceRate"], 4)
        total = cell["slotsTotal"]
        util = (total - min(cell["headroom"]["slots"], total)) / total if total else 0.0
        cell["utilization"] = round(util, 4)
        cell["scaleHint"] = _scale_hint(
            workers=cell["workers"],
            utilization=util,
            arrival_rate=cell["arrivalRate"],
            queue_depth=cell["queueDepth"],
        )
    return {"shards": shards, "halflifeS": halflife, "models": models,
            "fleet": fleet}
