"""Observability subsystem.

Raw telemetry (ISSUE 1): metrics.py (instruments + Prometheus text
encoding) and tracer.py (stitched per-request span timelines).
Interpretation layer (ISSUE 2): slo.py (per-class objectives, attainment,
burn rates, goodput), watchdog.py (per-phase hang detection), flightrec.py
(black-box event rings + post-mortem dump artifacts).
Performance introspection (ISSUE 4): perf.py (recompile tripwire,
device-memory accounting, step-time decomposition instruments, on-demand
jax.profiler capture).
Fleet economics (ISSUE 16): usage.py (per-tenant/per-model cost
attribution with an exactly-once engine/shard conservation ledger),
capacity.py (per-model demand rates, headroom, and autoscaling hints
behind /admin/capacity).
Active fleet health (ISSUE 19): probe.py (golden-hash canary prober),
health.py (per-worker EWMA+z-score regression baselines driving the
degraded/quarantined/probation state machine behind
/admin/health/fleet).

Pure stdlib — no prometheus_client, no OpenTelemetry; perf.py imports
jax lazily so control-plane processes stay light.
"""

from gridllm_tpu.obs.capacity import (
    DemandTracker,
    aggregate_worker_capacity,
    dedup_capacity_totals,
    merge_capacity,
)
from gridllm_tpu.obs.flightrec import (
    FlightRecorder,
    build_dump,
    default_flight_recorder,
    register_engine_probe,
    unregister_engine_probe,
)
from gridllm_tpu.obs.forensics import TRIGGERS, IncidentCollector
from gridllm_tpu.obs.health import HEALTH_STATES, STATE_CODES, HealthMonitor
from gridllm_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    PROMETHEUS_CONTENT_TYPE,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    render_registries,
)
from gridllm_tpu.obs.perf import (
    CaptureBusy,
    ProfilerCapture,
    RecompileTripwire,
    default_profiler,
    memory_snapshot,
    recompile_totals,
    register_memory_probe,
    unregister_memory_probe,
)
from gridllm_tpu.obs.probe import CanaryProber
from gridllm_tpu.obs.slo import SLOEngine, classify_request
from gridllm_tpu.obs.timeline import (
    CRITICAL_PATH_SEGMENTS,
    EDGE_FAMILIES,
    EVENTS,
    HLC,
    EventSpec,
    HLCStamp,
    TimelinePublisher,
    TimelineStore,
    critical_path,
    default_clock,
    emit_event,
    encode_hlc,
    register_event,
    set_emitter,
    split_hlc,
    stamp_key,
    timeline_armed,
    timeline_emitter,
)
from gridllm_tpu.obs.tracer import (
    TRACE_CHANNEL_PREFIX,
    Span,
    Tracer,
    trace_channel,
    trace_pattern,
)
from gridllm_tpu.obs.usage import (
    CANARY_TENANT,
    TenantLRU,
    UsageAccountant,
    account_engine_usage,
    build_usage,
    resolve_tenant,
)
from gridllm_tpu.obs.watchdog import HangWatchdog

__all__ = [
    "CANARY_TENANT",
    "CRITICAL_PATH_SEGMENTS",
    "EDGE_FAMILIES",
    "EVENTS",
    "HEALTH_STATES",
    "HLC",
    "LATENCY_BUCKETS",
    "PROMETHEUS_CONTENT_TYPE",
    "SIZE_BUCKETS",
    "STATE_CODES",
    "TRIGGERS",
    "CanaryProber",
    "CaptureBusy",
    "Counter",
    "DemandTracker",
    "EventSpec",
    "FlightRecorder",
    "Gauge",
    "HLCStamp",
    "HangWatchdog",
    "HealthMonitor",
    "Histogram",
    "IncidentCollector",
    "MetricsRegistry",
    "ProfilerCapture",
    "RecompileTripwire",
    "SLOEngine",
    "Span",
    "TRACE_CHANNEL_PREFIX",
    "TenantLRU",
    "TimelinePublisher",
    "TimelineStore",
    "Tracer",
    "UsageAccountant",
    "account_engine_usage",
    "aggregate_worker_capacity",
    "dedup_capacity_totals",
    "build_dump",
    "build_usage",
    "classify_request",
    "critical_path",
    "default_clock",
    "default_flight_recorder",
    "default_profiler",
    "default_registry",
    "emit_event",
    "encode_hlc",
    "memory_snapshot",
    "merge_capacity",
    "recompile_totals",
    "register_engine_probe",
    "register_event",
    "register_memory_probe",
    "render_registries",
    "resolve_tenant",
    "set_emitter",
    "split_hlc",
    "stamp_key",
    "timeline_armed",
    "timeline_emitter",
    "trace_channel",
    "trace_pattern",
    "unregister_engine_probe",
    "unregister_memory_probe",
]
