"""Observability subsystem: metrics registry + request tracer (ISSUE 1).

Pure stdlib — no prometheus_client, no OpenTelemetry. See metrics.py for
the instrument/encoding layer and tracer.py for span timelines.
"""

from gridllm_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    PROMETHEUS_CONTENT_TYPE,
    SIZE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    render_registries,
)
from gridllm_tpu.obs.tracer import (
    TRACE_CHANNEL_PREFIX,
    Span,
    Tracer,
    trace_channel,
)

__all__ = [
    "LATENCY_BUCKETS",
    "PROMETHEUS_CONTENT_TYPE",
    "SIZE_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "Span",
    "TRACE_CHANNEL_PREFIX",
    "Tracer",
    "default_registry",
    "render_registries",
    "trace_channel",
]
