"""Dependency-free metrics: Counter/Gauge/Histogram + Prometheus text encoding.

Pure stdlib (ISSUE 1 hard constraint). Instruments are safe to update from
asyncio callbacks and worker/engine threads: every metric guards its sample
map with a ``threading.Lock`` (updates are dict writes — the lock is cheap
and uncontended on the hot paths, which are single-writer per thread).

Two registries exist in practice, mirroring the deployment split:

- the process-global default registry (``default_registry()``): engine, ops
  kernel-dispatch, bus, and worker-service instruments — everything that is
  per-process no matter how many gateway stacks tests build;
- per-``JobScheduler`` registries: gateway/scheduler instruments, so each
  test (and each server instance) gets fresh zeroed counters and
  ``get_stats()`` stays instance-scoped.

``GET /metrics`` renders both, concatenated (names are disjoint by
convention: ``gridllm_gateway_*``/``gridllm_scheduler_*``/``gridllm_workers``
live on the scheduler registry, everything else on the default one).

Exposition format: the Prometheus text format, version 0.0.4
(https://prometheus.io/docs/instrumenting/exposition_formats/). Histograms
are fixed-bucket cumulative with ``_bucket``/``_sum``/``_count`` series and
an implicit ``+Inf`` bucket.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Iterable

# Default latency buckets (seconds): sub-ms token steps up to multi-minute
# cold loads. Chosen once, fixed — encoders and tests rely on them.
LATENCY_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)
# Occupancy/size buckets (counts): batch slots, queue depths.
SIZE_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


def _labels_str(names: tuple[str, ...], values: tuple[str, ...],
                extra: tuple[tuple[str, str], ...] = ()) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: dict[str, str]) -> tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labels)}"
            )
        return tuple(str(labels[n]) for n in self.labelnames)

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        lines.extend(self._render_samples())
        return lines

    def _render_samples(self) -> list[str]:  # pragma: no cover - abstract
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = ()):
        super().__init__(name, help, labelnames)
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def items(self) -> list[tuple[dict[str, str], float]]:
        """Point-in-time samples as ({label: value}, count) pairs —
        the public iteration surface (obs/perf.py recompile_totals)."""
        with self._lock:
            snap = list(self._values.items())
        return [(dict(zip(self.labelnames, key)), v) for key, v in snap]

    def _render_samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            f"{self.name}{_labels_str(self.labelnames, key)} {_format_value(v)}"
            for key, v in items
        ]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...] = ()):
        super().__init__(name, help, labelnames)
        self._values: dict[tuple[str, ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._values.get(key, 0.0)

    def _render_samples(self) -> list[str]:
        with self._lock:
            items = sorted(self._values.items())
        return [
            f"{self.name}{_labels_str(self.labelnames, key)} {_format_value(v)}"
            for key, v in items
        ]


class Histogram(_Metric):
    """Fixed-bucket cumulative histogram (Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name: str, help: str,
                 labelnames: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = LATENCY_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = b
        # per label-set: ([per-bucket counts ..., +Inf count], sum)
        self._series: dict[tuple[str, ...], tuple[list[int], float]] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            counts, total = self._series.get(
                key, ([0] * (len(self.buckets) + 1), 0.0)
            )
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._series[key] = (counts, total + value)

    def count(self, **labels: str) -> int:
        key = self._key(labels)
        with self._lock:
            counts, _ = self._series.get(key, ([], 0.0))
            return sum(counts)

    def total_count(self) -> int:
        with self._lock:
            return sum(sum(c) for c, _ in self._series.values())

    def sum(self, **labels: str) -> float:
        key = self._key(labels)
        with self._lock:
            return self._series.get(key, ([], 0.0))[1]

    def _render_samples(self) -> list[str]:
        with self._lock:
            items = sorted(
                (k, (list(c), s)) for k, (c, s) in self._series.items()
            )
        lines: list[str] = []
        for key, (counts, total) in items:
            cum = 0
            for i, ub in enumerate(self.buckets):
                cum += counts[i]
                ls = _labels_str(self.labelnames, key,
                                 extra=(("le", _format_value(ub)),))
                lines.append(f"{self.name}_bucket{ls} {cum}")
            cum += counts[-1]
            ls = _labels_str(self.labelnames, key, extra=(("le", "+Inf"),))
            lines.append(f"{self.name}_bucket{ls} {cum}")
            base = _labels_str(self.labelnames, key)
            lines.append(f"{self.name}_sum{base} {_format_value(total)}")
            lines.append(f"{self.name}_count{base} {cum}")
        return lines


class MetricsRegistry:
    """Name-keyed metric store. ``counter()``/``gauge()``/``histogram()``
    are get-or-create (idempotent across module reloads and repeated
    subsystem construction); re-registering with a different type or label
    set raises. Collectors are named callbacks run just before ``render()``
    so gauges derived from live objects (queue depth, worker counts) are
    point-in-time-correct without instrumenting every mutation; re-adding a
    collector under the same name replaces it (latest stack wins in tests)."""

    def __init__(self) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._collectors: dict[str, Callable[[], None]] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Iterable[str], **kw) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} re-registered with a different "
                        "type or label set"
                    )
                want = kw.get("buckets")
                if want is not None and existing.buckets != tuple(
                        sorted(float(x) for x in want)):
                    raise ValueError(
                        f"histogram {name!r} re-registered with different "
                        "buckets"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str,
                labelnames: Iterable[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str,
              labelnames: Iterable[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str,
                  labelnames: Iterable[str] = (),
                  buckets: tuple[float, ...] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def add_collector(self, name: str, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors[name] = fn

    def remove_collector(self, name: str) -> None:
        with self._lock:
            self._collectors.pop(name, None)

    def get(self, name: str) -> _Metric | None:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        with self._lock:
            collectors = list(self._collectors.values())
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for fn in collectors:
            try:
                fn()
            except Exception:  # noqa: BLE001 — a dead collector (torn-down
                pass           # test stack) must not break the scrape
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-global registry (engine/ops/bus/worker instruments)."""
    return _DEFAULT


def render_registries(*registries: MetricsRegistry) -> str:
    """Concatenated exposition across registries (gateway /metrics renders
    its scheduler's registry plus the process default)."""
    seen: set[int] = set()
    parts: list[str] = []
    for reg in registries:
        if id(reg) in seen:
            continue
        seen.add(id(reg))
        text = reg.render()
        if text:
            parts.append(text)
    return "".join(parts)
