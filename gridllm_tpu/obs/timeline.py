"""Fleet-wide causal event timeline (ISSUE 17).

Every member (gateway replicas, scheduler shards, workers) stamps its
flight-recorder lifecycle events — plus bus send/receive edges — with a
**hybrid logical clock** (HLC: physical milliseconds + logical counter,
merged on every bus message receive), batches them on a bounded queue, and
publishes them on the durable ``obs:event`` channel. Any member running a
:class:`TimelineStore` subscribes that channel and can answer
``GET /admin/timeline/{request_id}`` with the causal slice for one request
stitched across members; obs/forensics.py assembles incident reports from
the same store. :func:`critical_path` decomposes a request's traced e2e
latency into additive segments for the ``gridllm_critical_path_seconds``
histogram.

The publisher NEVER blocks an emitter: events land in a lock-guarded
deque; when the flush task cannot drain it (wedged bus), the oldest events
are dropped and counted (``gridllm_timeline_dropped_events_total``). A
broken timeline costs telemetry, not decode ITL.

Import-cycle note: bus/base.py imports ``gridllm_tpu.obs`` at module load
(for bus metrics), so NOTHING in this module may import bus code at the
top level — the obs package must finish importing first. Channel
constants are imported lazily inside methods (same pattern as
obs/tracer.py's ``TRACE_CHANNEL_PREFIX``); bus/base.py in turn imports
the HLC helpers from HERE at top level, which is safe because the obs
package is fully loaded by then. Pure stdlib.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Any, Callable

from gridllm_tpu.obs.metrics import default_registry
from gridllm_tpu.utils.logging import get_logger

log = get_logger("obs.timeline")


# -- hybrid logical clock ----------------------------------------------------


@dataclass(frozen=True, order=True)
class HLCStamp:
    """One HLC reading: orders by (wall_ms, logical, member) — the member
    id is the deterministic tie-break between concurrent events, never a
    statement about real time."""

    wall_ms: int
    logical: int
    member: str = ""

    def encode(self) -> str:
        return f"{self.wall_ms},{self.logical},{self.member}"

    @classmethod
    def parse(cls, raw: str) -> "HLCStamp":
        wall, logical, member = raw.split(",", 2)
        return cls(int(wall), int(logical), member)

    def to_list(self) -> list[Any]:
        return [self.wall_ms, self.logical, self.member]

    @classmethod
    def from_list(cls, raw: Any) -> "HLCStamp | None":
        try:
            wall, logical, member = raw
            return cls(int(wall), int(logical), str(member))
        except Exception:
            return None


class HLC:
    """Hybrid logical clock (Kulkarni et al.): ``tick()`` stamps local
    events and sends, ``update()`` merges a remote stamp on receive.
    Both are monotone; ``update()`` always returns a stamp ordered after
    the remote one, so a received message provably happens-after its
    send even when the hosts' physical clocks disagree by minutes.
    ``now_fn`` is injectable so tests can skew one member's clock."""

    def __init__(self, member: str = "",
                 now_fn: Callable[[], float] = time.time):
        self.member = member
        self.now_fn = now_fn
        self._wall = 0
        self._logical = 0
        self._lock = threading.Lock()

    def _now_ms(self) -> int:
        return int(self.now_fn() * 1000)

    def set_member(self, member: str) -> None:
        self.member = member

    def tick(self) -> HLCStamp:
        """Advance for a local event or a message send."""
        with self._lock:
            now = self._now_ms()
            if now > self._wall:
                self._wall, self._logical = now, 0
            else:
                self._logical += 1
            return HLCStamp(self._wall, self._logical, self.member)

    def update(self, remote: HLCStamp) -> HLCStamp:
        """Merge a remote stamp on message receive; the returned stamp is
        strictly after both the local clock and ``remote``."""
        with self._lock:
            now = self._now_ms()
            if now > self._wall and now > remote.wall_ms:
                self._wall, self._logical = now, 0
            elif remote.wall_ms > self._wall:
                self._wall = remote.wall_ms
                self._logical = remote.logical + 1
            elif self._wall > remote.wall_ms:
                self._logical += 1
            else:
                self._logical = max(self._logical, remote.logical) + 1
            return HLCStamp(self._wall, self._logical, self.member)

    def peek(self) -> HLCStamp:
        with self._lock:
            return HLCStamp(self._wall, self._logical, self.member)


_CLOCK = HLC()


def default_clock() -> HLC:
    """The process-global HLC every bus publish/receive runs through."""
    return _CLOCK


# -- wire framing ------------------------------------------------------------
# An HLC stamp rides INSIDE every bus message as a prefix frame (inside
# the broker's seq framing, which RespBus strips first), so the single
# strip-and-merge site in bus/base.py's HandlerPump covers both bus
# implementations. Mark bytes can't appear in JSON payloads.

_HLC_MARK = "\x00h\x00"


def encode_hlc(stamp: HLCStamp, payload: str) -> str:
    return f"{_HLC_MARK}{stamp.encode()}\x00{payload}"


def split_hlc(payload: str) -> tuple[HLCStamp | None, str]:
    """Split a framed message into (stamp, body); unframed messages (an
    old member mid-rolling-upgrade, tests publishing raw strings) pass
    through with ``stamp=None``."""
    if not payload.startswith(_HLC_MARK):
        return None, payload
    head, sep, body = payload[len(_HLC_MARK):].partition("\x00")
    if not sep:
        return None, payload
    try:
        return HLCStamp.parse(head), body
    except (ValueError, TypeError):
        return None, payload


# -- typed event registry ----------------------------------------------------
# Every timeline event type is declared exactly once here: name
# ("subsystem.event" — flight-recorder sites keep their existing
# spellings), the payload keys its sites may attach, and the modules
# allowed to emit it. The event-discipline analyzer rule
# (analysis/rules/event_discipline.py) statically discovers every
# flight-recorder ``record()`` / ``emit_event()`` call site and verifies
# both directions against this registry and the README "Timeline events"
# table, so an undeclared event (or a dead declaration) is a gridcheck
# finding, not a silent drift.


@dataclass(frozen=True)
class EventSpec:
    name: str
    keys: tuple[str, ...]
    modules: tuple[str, ...]
    open_keys: bool = False


EVENTS: dict[str, EventSpec] = {}


def register_event(name: str, *, keys: tuple[str, ...] = (),
                   modules: tuple[str, ...] = (),
                   open_keys: bool = False) -> None:
    """Declare one timeline event type. ``open_keys`` marks events whose
    sites splat dynamic fields (``**loaded``) — key sets are then a
    lower bound, not exact."""
    if name in EVENTS:
        raise ValueError(f"duplicate register_event({name!r})")
    EVENTS[name] = EventSpec(name, tuple(keys), tuple(modules), open_keys)


register_event("bus.failover", keys=("conn", "endpoint", "epoch"),
               modules=("gridllm_tpu/bus/resp.py",))
register_event("bus.recv", keys=("channel",),
               modules=("gridllm_tpu/bus/base.py",))
register_event("bus.resume_gap", keys=("channel", "lost"),
               modules=("gridllm_tpu/bus/resp.py",))
register_event("bus.send", keys=("channel",),
               modules=("gridllm_tpu/bus/base.py",))
register_event("bus.seq_reset", keys=("channel",),
               modules=("gridllm_tpu/bus/resp.py",))
register_event("bus.subscriber_down", keys=("endpoint",),
               modules=("gridllm_tpu/bus/resp.py",))
register_event("bus.subscriber_reconnected", keys=("endpoint", "outageS"),
               modules=("gridllm_tpu/bus/resp.py",))
register_event("engine.admit",
               keys=("cachedTokens", "model", "promptTokens", "request",
                     "slot"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.block", keys=("gen", "k", "model", "pending", "slots"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.finish",
               keys=("model", "reason", "request", "slot", "tokens"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.kv_import",
               keys=("model", "pagesInstalled", "pagesShared", "tokens"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.kv_park", keys=("model", "pages", "tokens"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.profile_capture", keys=("path", "reason", "seconds"),
               modules=("gridllm_tpu/obs/perf.py",))
register_event("engine.recompile",
               keys=("context", "fn", "nArrays", "reason", "shapes",
                     "statics"),
               modules=("gridllm_tpu/obs/perf.py",))
register_event("engine.recompile_storm", keys=(),
               modules=("gridllm_tpu/obs/perf.py",), open_keys=True)
register_event("engine.runner_dead", keys=("error", "model"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.step_failure", keys=("error", "model", "streak"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.verify",
               keys=("drafted", "gen", "k", "model", "pending", "slots"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("engine.verify_tree",
               keys=("drafted", "gen", "model", "nodes", "pending",
                     "slots"),
               modules=("gridllm_tpu/engine/engine.py",))
register_event("gateway.server_error", keys=("method", "route", "status"),
               modules=("gridllm_tpu/gateway/obs_routes.py",))
register_event("health.degraded", keys=("reason", "worker"),
               modules=("gridllm_tpu/obs/health.py",))
register_event("health.probation", keys=("reason", "worker"),
               modules=("gridllm_tpu/obs/health.py",))
register_event("health.quarantined", keys=("reason", "worker"),
               modules=("gridllm_tpu/obs/health.py",))
register_event("health.recovered", keys=("reason", "worker"),
               modules=("gridllm_tpu/obs/health.py",))
register_event("gateway.submitted", keys=("model",),
               modules=("gridllm_tpu/controlplane/client.py",))
register_event("numcheck.nonfinite", keys=("op",),
               modules=("gridllm_tpu/analysis/numcheck.py",), open_keys=True)
register_event("numcheck.tolerance", keys=("op",),
               modules=("gridllm_tpu/analysis/numcheck.py",), open_keys=True)
register_event("probe.golden_drift",
               keys=("expected", "got", "model", "worker"),
               modules=("gridllm_tpu/obs/probe.py",))
register_event("probe.golden_sealed", keys=("hash", "model", "worker"),
               modules=("gridllm_tpu/obs/probe.py",))
register_event("registry.liveness_resumed", keys=("workers",),
               modules=("gridllm_tpu/scheduler/registry.py",))
register_event("registry.liveness_suspended", keys=("workers",),
               modules=("gridllm_tpu/scheduler/registry.py",))
register_event("registry.worker_crash", keys=("reason", "worker"),
               modules=("gridllm_tpu/obs/watchdog.py",))
register_event("registry.worker_registered", keys=("models", "worker"),
               modules=("gridllm_tpu/scheduler/registry.py",))
register_event("registry.worker_removed",
               keys=("currentJobs", "reason", "worker"),
               modules=("gridllm_tpu/scheduler/registry.py",))
register_event("scheduler.cancelled", keys=("job", "reason"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.deadline_exceeded", keys=("job", "model"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.disagg_fallback", keys=("job", "reason", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.drain_handoff",
               keys=("fromWorker", "job", "toWorker", "tokens"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.drain_requeued", keys=("fromWorker", "job"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.duplicate_completion",
               keys=("job", "tokens", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.failed",
               keys=("error", "job", "model", "tenant", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.handoff",
               keys=("fromWorker", "job", "toWorker", "tokens"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.hang", keys=("ageS", "job", "phase", "worker"),
               modules=("gridllm_tpu/obs/watchdog.py",))
register_event("scheduler.migration_lost",
               keys=("fromWorker", "job", "toWorker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.nacked", keys=("job", "nacks", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.orphaned", keys=("job", "reason", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.preempt_requested",
               keys=("job", "waiting", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.preempted",
               keys=("fromWorker", "job", "parkedTokens"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.retry", keys=("attempt", "error", "job"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.retry_budget_exhausted", keys=("error", "job"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.shard_adopted", keys=("member", "shard"),
               modules=("gridllm_tpu/scheduler/scheduler.py",),
               open_keys=True)
register_event("scheduler.shard_released",
               keys=("active", "queued", "shard"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("scheduler.timeout",
               keys=("job", "model", "reason", "tenant", "worker"),
               modules=("gridllm_tpu/scheduler/scheduler.py",))
register_event("transfer.kv_imported",
               keys=("bytes", "request", "tokens", "worker"),
               modules=("gridllm_tpu/transfer/migrate.py",))
register_event("transfer.kv_released", keys=("request", "worker"),
               modules=("gridllm_tpu/transfer/migrate.py",))
register_event("transfer.kv_send_failed",
               keys=("bytes", "job", "reason", "to", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("transfer.kv_sent",
               keys=("bytes", "job", "reason", "to", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.drain_handoff",
               keys=("job", "migrated", "to", "tokens", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.draining", keys=("budgetS", "jobs", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.duplicate_dropped", keys=("job", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.engine_dead", keys=("model", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.fatal_exit", keys=("reason", "worker"),
               modules=("gridllm_tpu/worker/main.py",))
register_event("worker.job_failed",
               keys=("error", "job", "model", "tenant", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.preempt_handoff",
               keys=("job", "parkedTokens", "tokens", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.started", keys=("models", "worker"),
               modules=("gridllm_tpu/worker/service.py",))
register_event("worker.stopped", keys=("announce", "worker"),
               modules=("gridllm_tpu/worker/service.py",))


# -- bus-edge helpers --------------------------------------------------------
# Channel families whose send/receive edges become timeline events.
# Deliberately EXCLUDES the hot volume families (stream frames, KV
# transfer chunks, heartbeats, status envelopes, trace publications):
# edges exist to order lifecycle transitions, not to mirror the data
# plane. The HLC stamp itself still rides on EVERY message.

EDGE_FAMILIES = frozenset({
    "job:completed", "job:failed", "job:handoff", "job:drain",
    "job:preempted", "job:snapshot", "ctrl:submit", "ctrl:cancel",
    "worker:job",
})


def edge_request_id(message: str) -> str | None:
    """Best-effort request id from a lifecycle payload (all the edge
    families carry JSON with one of these spellings)."""
    try:
        data = json.loads(message)
    except (ValueError, TypeError):
        return None
    if not isinstance(data, dict):
        return None
    rid = data.get("jobId") or data.get("requestId")
    if isinstance(rid, str) and rid:
        return rid
    for key in ("request", "job"):
        sub = data.get(key)
        if isinstance(sub, dict) and isinstance(sub.get("id"), str):
            return sub["id"]
    return None


# -- module-level emitter ----------------------------------------------------
# One process-global publisher (like the flight recorder): armed once at
# process start; every subsystem — and the bus-edge hooks in bus/base.py —
# emits through it. None = timeline disabled, emits are no-ops.

_EMITTER: "TimelinePublisher | None" = None


def set_emitter(pub: "TimelinePublisher | None") -> None:
    global _EMITTER
    _EMITTER = pub


def timeline_emitter() -> "TimelinePublisher | None":
    return _EMITTER


def timeline_armed() -> bool:
    return _EMITTER is not None


def emit_event(name: str, *, member: str | None = None,
               request_id: str | None = None,
               stamp: HLCStamp | None = None, **fields: Any) -> None:
    """Emit one timeline event through the global publisher (no-op when
    the timeline is disarmed). ``member``/``request_id``/``stamp`` are
    envelope attributes, not payload keys."""
    if _EMITTER is not None:
        _EMITTER.emit(name, member=member, request_id=request_id,
                      stamp=stamp, fields=fields)


def stamp_key(ev: dict[str, Any]) -> tuple[int, int, str]:
    """Sort key: the event's HLC stamp (causal order across members)."""
    stamp = HLCStamp.from_list(ev.get("stamp"))
    if stamp is None:
        return (0, 0, "")
    return (stamp.wall_ms, stamp.logical, stamp.member)


class TimelinePublisher:
    """Bounded, never-blocking event publisher for one member.

    ``emit()`` appends to a lock-guarded deque (callable from any
    thread); a flush task drains batches onto the durable ``obs:event``
    channel. Overflow drops the OLDEST events and counts them — recent
    history is what forensics wants, and a wedged bus must cost
    telemetry, never decode ITL. ``install()`` wires the process: the
    global emitter slot plus a flight-recorder tap so every existing
    ``record()`` site becomes a timeline event without changing."""

    def __init__(self, member: str, *, queue_capacity: int = 2048,
                 flush_ms: float = 200.0, batch_max: int = 256,
                 registry=None):
        self.member = member
        self.queue_capacity = queue_capacity
        self.flush_s = max(flush_ms, 1.0) / 1000.0
        self.batch_max = batch_max
        self.clock = default_clock()
        if not self.clock.member:
            # first armer names the process clock (tie-break identity)
            self.clock.set_member(member)
        self._q: deque[dict[str, Any]] = deque()
        self._lock = threading.Lock()
        self._bus = None
        self._task: asyncio.Task | None = None
        self._dropped = (registry or default_registry()).counter(
            "gridllm_timeline_dropped_events_total",
            "Timeline events dropped by the bounded publisher queue "
            "(bus backpressure) instead of blocking an emitter, by "
            "member.",
            ("member",),
        )

    # -- emit side (any thread, never blocks) -------------------------------
    def emit(self, name: str, *, member: str | None = None,
             request_id: str | None = None,
             stamp: HLCStamp | None = None,
             fields: dict[str, Any] | None = None) -> None:
        if stamp is None:
            stamp = self.clock.tick()
        ev: dict[str, Any] = {
            "name": name,
            "member": member or self.member,
            "stamp": stamp.to_list(),
        }
        if request_id:
            ev["requestId"] = request_id
        if fields:
            ev["fields"] = fields
        with self._lock:
            if len(self._q) >= self.queue_capacity:
                self._q.popleft()
                self._dropped.inc(member=self.member)
            self._q.append(ev)

    def _on_record(self, subsystem: str, event: str,
                   fields: dict[str, Any]) -> None:
        """Flight-recorder tap: every existing ``record()`` site becomes
        a ``subsystem.event`` timeline event. Member attribution prefers
        an explicit ``member`` field, then the worker id on worker-side
        subsystems, then this publisher's member."""
        member = fields.get("member")
        if not member and subsystem in ("worker", "transfer", "engine"):
            member = fields.get("worker")
        rid = (fields.get("job") or fields.get("jobId")
               or fields.get("request") or fields.get("requestId"))
        payload = {k: v for k, v in fields.items() if k != "member"}
        self.emit(f"{subsystem}.{event}",
                  member=member if isinstance(member, str) else None,
                  request_id=rid if isinstance(rid, str) else None,
                  fields=payload)

    def install(self) -> None:
        """Become the process emitter: global slot + flight-recorder tap."""
        from gridllm_tpu.obs.flightrec import default_flight_recorder

        set_emitter(self)
        default_flight_recorder().set_tap(self._on_record)

    # -- flush side (event loop) --------------------------------------------
    async def start(self, bus) -> None:
        self._bus = bus
        if self._task is None:
            self._task = asyncio.create_task(self._flush_loop())

    async def stop(self) -> None:
        from gridllm_tpu.obs.flightrec import default_flight_recorder

        if self._task is not None:
            self._task.cancel()
            self._task = None
        if timeline_emitter() is self:
            set_emitter(None)
            default_flight_recorder().set_tap(None)

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.flush_s)
            try:
                await self.flush_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — telemetry must not kill
                log.warning("timeline flush failed", error=str(e))

    async def flush_once(self) -> int:
        """Drain up to ``batch_max`` queued events onto the bus. A failed
        publish counts the batch as dropped rather than requeueing it —
        backpressure never grows the queue beyond its bound."""
        if self._bus is None:
            return 0
        with self._lock:
            if not self._q:
                return 0
            batch = [self._q.popleft()
                     for _ in range(min(len(self._q), self.batch_max))]
        # deferred import: bus/base.py imports the obs package at module
        # load, so the constant cannot be imported at OUR module level
        from gridllm_tpu.bus.base import CH_OBS_EVENT

        payload = json.dumps({"member": self.member, "events": batch},
                             default=str)
        try:
            await self._bus.publish(CH_OBS_EVENT, payload)
        except Exception as e:  # noqa: BLE001
            for _ in batch:
                self._dropped.inc(member=self.member)
            log.warning("timeline publish failed; batch dropped",
                        error=str(e), events=len(batch))
            return 0
        return len(batch)

    def pending(self) -> int:
        with self._lock:
            return len(self._q)


class TimelineStore:
    """Fleet-merged event store: subscribes ``obs:event``, keeps a global
    ring plus a bounded per-request index, and serves HLC-ordered slices.
    Ingesting also merges every event's stamp into the local clock, so
    anything this member emits afterwards (incident reports) is causally
    after everything it has seen."""

    def __init__(self, *, capacity: int = 4096, max_requests: int = 512):
        self.capacity = capacity
        self.max_requests = max_requests
        self._ring: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._by_request: OrderedDict[str, list[dict[str, Any]]] = (
            OrderedDict())
        self._listeners: list[Callable[[dict[str, Any]], None]] = []
        self._sub = None

    async def attach(self, bus) -> None:
        from gridllm_tpu.bus.base import CH_OBS_EVENT

        self._sub = await bus.subscribe(CH_OBS_EVENT, self._on_batch)

    async def detach(self) -> None:
        if self._sub is not None:
            await self._sub.unsubscribe()
            self._sub = None

    async def _on_batch(self, channel: str, raw: str) -> None:
        try:
            data = json.loads(raw)
        except (ValueError, TypeError):
            return
        events = data.get("events") if isinstance(data, dict) else None
        if not isinstance(events, list):
            return
        for ev in events:
            if isinstance(ev, dict) and isinstance(ev.get("name"), str):
                self.ingest(ev)

    def ingest(self, ev: dict[str, Any]) -> None:
        stamp = HLCStamp.from_list(ev.get("stamp"))
        if stamp is not None:
            default_clock().update(stamp)
        self._ring.append(ev)
        rid = ev.get("requestId")
        if isinstance(rid, str) and rid:
            bucket = self._by_request.get(rid)
            if bucket is None:
                bucket = self._by_request[rid] = []
                while len(self._by_request) > self.max_requests:
                    self._by_request.popitem(last=False)
            else:
                self._by_request.move_to_end(rid)
            bucket.append(ev)
            # per-request bound: a runaway stream cannot pin the index
            if len(bucket) > self.capacity:
                del bucket[0]
        for fn in list(self._listeners):
            try:
                fn(ev)
            except Exception as e:  # noqa: BLE001 — listeners are best-effort
                log.warning("timeline listener failed", error=str(e))

    def add_listener(self, fn: Callable[[dict[str, Any]], None]) -> None:
        self._listeners.append(fn)

    def slice(self, request_id: str) -> list[dict[str, Any]]:
        """All events for one request in HLC (causal) order."""
        return sorted(self._by_request.get(request_id, ()), key=stamp_key)

    def window(self, wall_lo_ms: int, wall_hi_ms: int) -> list[dict[str, Any]]:
        """Events whose physical component falls in [lo, hi], HLC-ordered
        — the incident collector's causal-window query."""
        out = [ev for ev in self._ring
               if wall_lo_ms <= stamp_key(ev)[0] <= wall_hi_ms]
        out.sort(key=stamp_key)
        return out

    def events(self) -> list[dict[str, Any]]:
        return list(self._ring)


# -- critical-path decomposition ---------------------------------------------

CRITICAL_PATH_SEGMENTS = (
    "queue_wait", "dispatch", "prefill", "decode_device",
    "decode_host_stall", "migration", "suspend_resume",
)

# span name → segment, in descending precedence when intervals overlap:
# KV migration work wins over the prefill/decode it interrupts, compute
# wins over the queue span that may straddle a requeue.
_MIGRATION_SPANS = ("kvx.send", "kvx.import", "engine.prefill_export")


def critical_path(spans: list[dict[str, Any]]) -> dict[str, float] | None:
    """Decompose a stitched trace into additive latency segments.

    Sweeps the root ``gateway.request`` interval: every elementary
    sub-interval is attributed to exactly ONE segment by precedence
    (migration > prefill > decode > queue-wait), uncovered time inside
    the worker-execution hull but between execute spans is
    ``suspend_resume`` (preemption/handoff gaps), and all other
    uncovered time is ``dispatch`` (control-plane transit). Decode time
    splits into device compute (the engine-measured ``engineNs`` share)
    vs host stall. The segments sum to the e2e latency exactly, so the
    ``gridllm_critical_path_seconds`` histogram is an additive
    decomposition, not a set of overlapping timers. Returns None until
    the root span is sealed."""
    root = next((s for s in spans
                 if s.get("name") == "gateway.request"
                 and s.get("end") is not None), None)
    if root is None:
        return None
    t0, t1 = float(root["start"]), float(root["end"])
    if t1 <= t0:
        return None

    def clipped(names: tuple[str, ...] | str) -> list[tuple[float, float]]:
        wanted = (names,) if isinstance(names, str) else names
        out = []
        for s in spans:
            if s.get("name") not in wanted or s.get("end") is None:
                continue
            a = max(t0, float(s["start"]))
            b = min(t1, float(s["end"]))
            if b > a:
                out.append((a, b))
        return out

    migration = clipped(_MIGRATION_SPANS)
    prefill = clipped("engine.prefill")
    decode = clipped("engine.decode")
    queue = clipped("queue.wait")
    execs = clipped("worker.execute")
    exec_hull = ((min(a for a, _ in execs), max(b for _, b in execs))
                 if execs else None)

    def covers(ivs: list[tuple[float, float]], x: float) -> bool:
        return any(a <= x < b for a, b in ivs)

    points = sorted({t0, t1,
                     *(p for iv in (*migration, *prefill, *decode,
                                    *queue, *execs) for p in iv)})
    seg = dict.fromkeys(CRITICAL_PATH_SEGMENTS, 0.0)
    decode_cov = 0.0
    for a, b in zip(points, points[1:]):
        if b <= t0 or a >= t1:
            continue
        mid = (a + b) / 2
        dur = b - a
        if covers(migration, mid):
            seg["migration"] += dur
        elif covers(prefill, mid):
            seg["prefill"] += dur
        elif covers(decode, mid):
            decode_cov += dur
        elif covers(queue, mid):
            seg["queue_wait"] += dur
        elif (exec_hull is not None
              and exec_hull[0] <= mid < exec_hull[1]
              and not covers(execs, mid)):
            seg["suspend_resume"] += dur
        else:
            seg["dispatch"] += dur
    # engine-measured device time bounds the device share of decode; the
    # remainder is host stall (python step loop, transfers, GIL)
    engine_s = sum(
        float((s.get("meta") or {}).get("engineNs") or 0.0) / 1e9
        for s in spans if s.get("name") == "engine.decode")
    seg["decode_device"] = min(decode_cov, engine_s)
    seg["decode_host_stall"] = decode_cov - seg["decode_device"]
    seg["e2e"] = t1 - t0
    return seg
