"""Hang watchdog: detects requests silently wedged in one phase (ISSUE 2).

The failure mode this closes (BENCH_r0x): a request sits between scheduler
and engine for minutes and nothing says so — the job timeout eventually
fires (10 minutes by default) and the evidence is one unstructured error
string. The watchdog sweeps the scheduler's live state on an interval and
flags any request stuck in a phase past that phase's deadline
(utils/config.py ``WatchdogConfig``):

- **queue**: an open ``queue.wait`` span older than the queue deadline
  (no worker serves the model, or dispatch is starved);
- **dispatch**: assigned to a worker, no sign of life past the dispatch
  deadline — the assignment publish landed nowhere;
- **prefill**: still no first token far past that (a cold compile is
  minutes; a wedged one is forever). Gateway-side the two differ only by
  age — stream progress is the only worker signal before completion;
- **decode-step**: the stream produced tokens and then stopped — the
  engine wedged mid-decode without exiting (the chaos-test scenario).

On detection the watchdog increments ``gridllm_hangs_total{phase}``,
attaches a diagnosis event to the request's trace (last span, worker id,
engine batch state from registered probes), records + auto-dumps a flight
recorder artifact (obs/flightrec.py), and — when ``requeue`` is on — aborts
the assignment (cancellation published to the worker) and requeues the job
at the front with reason ``hang`` through the scheduler's orphan machinery.
Only ``prefill`` and ``decode-step`` hangs requeue: ``queue`` has nothing
to requeue, and ``dispatch`` is gateway-indistinguishable from a slow
first compile — both are diagnosis-only.

Worker crashes (registry removals for heartbeat_timeout / aliveness_probe /
disconnected) also trigger an auto dump, so a SIGKILLed worker leaves a
readable post-mortem without anyone asking for one.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any

from gridllm_tpu.obs.flightrec import (
    FlightRecorder,
    build_dump,
    default_flight_recorder,
    engine_states,
)
from gridllm_tpu.utils.config import WatchdogConfig
from gridllm_tpu.utils.logging import get_logger

log = get_logger("obs.watchdog")

# registry-removal reasons that mean "the worker died", not "it left"
CRASH_REASONS = ("heartbeat_timeout", "aliveness_probe", "disconnected")


class HangWatchdog:
    """Sweeps one JobScheduler's tracer spans + assignments. Owned and
    lifecycled by the scheduler (initialize/shutdown) so every stack —
    gateway, bench, tests — gets hang detection without extra wiring."""

    def __init__(self, scheduler: Any, config: WatchdogConfig | None = None,
                 recorder: FlightRecorder | None = None):
        self.scheduler = scheduler
        self.config = config or WatchdogConfig()
        self.recorder = recorder or default_flight_recorder()
        self._task: asyncio.Task | None = None
        self._flagged: dict[str, str] = {}  # job_id → phase already handled
        self.hangs: list[dict[str, Any]] = []  # detection log (bounded)
        self._hangs_total = scheduler.metrics.counter(
            "gridllm_hangs_total",
            "Requests detected stuck in one phase past its deadline, by "
            "phase (queue/dispatch/prefill/decode-step).", ("phase",))
        self._sweeps_total = scheduler.metrics.counter(
            "gridllm_watchdog_sweeps_total", "Watchdog sweep passes run.")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        if not self.config.enabled or self._task is not None:
            return
        self.scheduler.registry.on("worker_removed", self._on_worker_removed)
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self.scheduler.registry.off("worker_removed", self._on_worker_removed)

    async def _loop(self) -> None:
        interval = self.config.interval_ms / 1000
        while True:
            await asyncio.sleep(interval)
            try:
                await self.sweep()
            except Exception as e:  # noqa: BLE001 — the watchdog must outlive
                log.error("watchdog sweep failed", error=str(e))

    # -- crash dumps --------------------------------------------------------
    def _on_worker_removed(self, worker_id: str, _info: Any,
                           reason: str) -> None:
        if reason not in CRASH_REASONS:
            return
        self.recorder.record("registry", "worker_crash",
                             worker=worker_id, reason=reason)
        self._auto_dump(f"worker_crash:{worker_id}",
                        crash={"worker": worker_id, "reason": reason})

    def _auto_dump(self, reason: str, **extra: Any) -> None:
        artifact = build_dump(self.scheduler, reason=reason,
                              recorder=self.recorder,
                              include_auto_dumps=False, **extra)
        self.recorder.add_auto_dump(artifact)
        log.error("flight recorder auto dump", reason=reason)

    # -- detection ----------------------------------------------------------
    @staticmethod
    def _streams_frames(request: Any) -> bool:
        """Whether this request is expected to produce job:stream frames —
        the only pre-completion progress signal. Non-streaming requests,
        and streaming ones the worker force-buffers (format/tools/think,
        worker/service.py), run silently until completion; for them silence
        is NOT evidence of a hang."""
        if not getattr(request, "stream", False):
            return False
        md = getattr(request, "metadata", None) or {}
        return not (getattr(request, "format", None)
                    or getattr(request, "tools", None)
                    or md.get("format") or md.get("think"))

    def _detect(self, now: float) -> list[dict[str, Any]]:
        cfg = self.config
        sched = self.scheduler
        hangs: list[dict[str, Any]] = []
        for job_id, span in list(sched._queue_spans.items()):
            age = now - span.start
            if age * 1000 > cfg.queue_deadline_ms:
                hangs.append({"requestId": job_id, "phase": "queue",
                              "ageS": round(age, 3), "worker": None})
        for job_id, assignment in list(sched.active_jobs.items()):
            age = now - assignment.assignedAt
            progress = sched._stream_progress.get(job_id)
            if progress is None:
                # a request that will never stream gives no progress signal
                # at all — a long healthy generation is indistinguishable
                # from a wedge, so it can only ever reach the diagnosis-only
                # "dispatch" phase, never the requeueing "prefill" one
                frames = self._streams_frames(assignment.request)
                if frames and age * 1000 > cfg.prefill_deadline_ms:
                    phase = "prefill"
                elif age * 1000 > cfg.dispatch_deadline_ms:
                    phase = "dispatch"
                else:
                    continue
                hangs.append({"requestId": job_id, "phase": phase,
                              "ageS": round(age, 3),
                              "worker": assignment.workerId})
            else:
                _first, last = progress
                stall = now - last
                if stall * 1000 > cfg.decode_stall_ms:
                    hangs.append({"requestId": job_id, "phase": "decode-step",
                                  "ageS": round(age, 3),
                                  "stallS": round(stall, 3),
                                  "worker": assignment.workerId})
        return hangs

    def _diagnose(self, hang: dict[str, Any]) -> dict[str, Any]:
        spans = self.scheduler.tracer.export(hang["requestId"]) or []
        last = spans[-1] if spans else None
        return {
            "lastSpan": ({"name": last["name"], "source": last["source"],
                          "start": last["start"], "end": last.get("end")}
                         if last else None),
            "engines": engine_states(),
        }

    async def sweep(self) -> list[dict[str, Any]]:
        """One detection pass. Returns the hangs acted on this pass."""
        self._sweeps_total.inc()
        now = time.time()
        sched = self.scheduler
        hangs = self._detect(now)
        live = {h["requestId"] for h in hangs}
        # a request that recovered (or resolved) may hang again later in a
        # DIFFERENT phase — only an identical (id, phase) repeat is skipped
        for job_id in list(self._flagged):
            if job_id not in live:
                del self._flagged[job_id]
        acted: list[dict[str, Any]] = []
        for hang in hangs:
            job_id, phase = hang["requestId"], hang["phase"]
            if self._flagged.get(job_id) == phase:
                continue
            self._flagged[job_id] = phase
            self._hangs_total.inc(phase=phase)
            diagnosis = self._diagnose(hang)
            if phase == "decode-step":
                # a stream that stalled mid-decode means the engine is
                # wedged RIGHT NOW — a short profiler capture of the next
                # few seconds shows what the device (or the host hold-up)
                # is doing, which no post-hoc dump can. to_thread: the
                # capture start does blocking work (dir prune,
                # start_trace) that must not stall the sweep loop.
                profile = await asyncio.to_thread(self._profile_hang, phase)
                if profile is not None:
                    diagnosis["profile"] = profile
            hang["diagnosis"] = diagnosis
            sched.tracer.event(
                job_id, "watchdog.hang", phase=phase,
                worker=hang.get("worker"), ageS=hang["ageS"],
                lastSpan=(diagnosis["lastSpan"] or {}).get("name"))
            self.recorder.record("scheduler", "hang", job=job_id,
                                 phase=phase, worker=hang.get("worker"),
                                 ageS=hang["ageS"])
            log.error("hang detected", job_id=job_id, phase=phase,
                      worker=hang.get("worker"), age_s=hang["ageS"])
            self._auto_dump(f"hang:{phase}:{job_id}", hang=hang)
            acted.append(hang)
            self.hangs.append(hang)
            del self.hangs[:-64]  # bounded detection log
            # requeue only on phases the gateway can be SURE about:
            # decode-step (the stream demonstrably stalled) and prefill
            # (far past even a cold compile). "dispatch" is diagnosis-only
            # — gateway-side it is indistinguishable from a slow prefill,
            # and requeueing a job mid-first-compile would waste minutes
            # of real work on a false positive.
            if self.config.requeue and phase in ("prefill", "decode-step"):
                await self._abort_and_requeue(job_id)
        return acted

    def _profile_hang(self, phase: str) -> dict[str, Any] | None:
        """Best-effort short jax.profiler capture on a decode-step hang
        (config.profile_on_hang_s; 0 disables). Busy/failed captures are
        swallowed — profiling is evidence-gathering, never a reason the
        hang handling itself fails. In split deployments this profiles
        the gateway process (diagnosis-limited); the engine-side capture
        lives on the worker health port's POST /admin/profile."""
        seconds = self.config.profile_on_hang_s
        if not seconds:
            return None
        from gridllm_tpu.obs.perf import default_profiler, jax_loaded

        if not jax_loaded():
            # engine-less control-plane process (split deployment): a
            # trace of nothing is not worth a backend init in the
            # watchdog loop. The worker health port's POST /admin/profile
            # is the engine-side capture.
            return None
        try:
            return default_profiler().capture(seconds,
                                              reason=f"hang-{phase}")
        except Exception as e:  # noqa: BLE001
            log.warning("hang profiler capture skipped", error=str(e))
            return None

    async def _abort_and_requeue(self, job_id: str) -> None:
        """Cancel the wedged assignment on its worker (best-effort — a
        truly dead worker hears nothing) and requeue the job at the front
        via the orphan machinery with reason ``hang``. The scheduler's
        at-least-once hygiene (duplicate drop + resolved-copy purge)
        absorbs the case where the worker was merely slow and answers."""
        sched = self.scheduler
        assignment = sched.active_jobs.get(job_id)
        if assignment is None:
            return  # resolved between detection and action — nothing to do
        try:
            await sched.publish_cancellation(assignment.workerId, job_id,
                                             "hang")
        except Exception as e:  # noqa: BLE001 — requeue must still happen
            log.warning("hang cancellation publish failed", job_id=job_id,
                        error=str(e))
        await sched._orphan_job(assignment, reason="hang")
        sched.request_dispatch()
