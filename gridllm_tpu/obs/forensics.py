"""Automated incident forensics over the fleet timeline (ISSUE 17).

The :class:`IncidentCollector` listens to a member's
:class:`~gridllm_tpu.obs.timeline.TimelineStore`. When a trigger event
lands — watchdog hang, shard lease loss, broker failover, lost
migration, preemption — it opens a bounded incident report whose causal
window (± ``window_ms`` around the trigger's HLC physical time) is
re-sliced from the store on READ, flight-recorder style: by the time an
operator fetches ``GET /admin/incidents``, every member's surrounding
events have usually arrived, and the report says so explicitly via
``complete`` when the window has fully elapsed. No timers, no background
tasks — assembly is lazy and bounded. Pure stdlib.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any

from gridllm_tpu.obs.metrics import default_registry
from gridllm_tpu.obs.timeline import TimelineStore, stamp_key

# trigger event name → incident kind. These are the cross-member failure
# modes ISSUE 17 names; anything else on the timeline is context, not a
# trigger.
TRIGGERS: dict[str, str] = {
    "scheduler.hang": "watchdog_hang",
    "scheduler.shard_adopted": "shard_lease_lost",
    "bus.failover": "broker_failover",
    "scheduler.migration_lost": "migration_lost",
    "scheduler.preempted": "preemption",
    # active fleet health (ISSUE 19): a quarantine verdict or a canary
    # golden-hash mismatch names the worker in its incident key
    "health.quarantined": "worker_quarantined",
    "probe.golden_drift": "canary_drift",
}


class IncidentCollector:
    """Bounded auto-assembled incident reports from the fleet timeline."""

    def __init__(self, store: TimelineStore, *, member: str = "",
                 window_ms: float = 5000.0, max_incidents: int = 32,
                 registry=None):
        self.store = store
        self.member = member
        self.window_ms = window_ms
        self._incidents: deque[dict[str, Any]] = deque(maxlen=max_incidents)
        self._seq = 0
        self._counter = (registry or default_registry()).counter(
            "gridllm_incidents_total",
            "Auto-assembled incident reports opened by the forensics "
            "collector, by kind (watchdog_hang/shard_lease_lost/"
            "broker_failover/migration_lost/preemption/"
            "worker_quarantined/canary_drift).",
            ("kind",),
        )
        store.add_listener(self._on_event)

    def _on_event(self, ev: dict[str, Any]) -> None:
        kind = TRIGGERS.get(ev.get("name") or "")
        if kind is None:
            return
        wall_ms = stamp_key(ev)[0]
        key = (ev.get("requestId")
               or (ev.get("fields") or {}).get("worker")
               or (ev.get("fields") or {}).get("shard")
               or (ev.get("fields") or {}).get("endpoint") or "")
        # debounce: one report per (kind, subject) per window — a retry
        # storm around one failure is one incident, not a report flood
        for inc in self._incidents:
            if (inc["kind"] == kind and inc["key"] == str(key)
                    and abs(inc["triggerWallMs"] - wall_ms)
                    <= self.window_ms):
                return
        self._seq += 1
        self._counter.inc(kind=kind)
        self._incidents.append({
            "id": f"{kind}-{self._seq}",
            "kind": kind,
            "key": str(key),
            "member": self.member,
            "trigger": ev,
            "triggerWallMs": wall_ms,
            "windowMs": self.window_ms,
        })

    def reports(self, now_ms: float | None = None) -> list[dict[str, Any]]:
        """Assemble every open incident against the CURRENT store
        contents (lazy finalize). ``complete`` flips once the causal
        window has fully elapsed — before that, late members may still
        be flushing their half of the story."""
        if now_ms is None:
            now_ms = time.time() * 1000
        out = []
        for inc in self._incidents:
            lo = inc["triggerWallMs"] - self.window_ms
            hi = inc["triggerWallMs"] + self.window_ms
            events = self.store.window(int(lo), int(hi))
            out.append({
                "id": inc["id"],
                "kind": inc["kind"],
                "key": inc["key"],
                "collectedBy": inc["member"],
                "trigger": inc["trigger"],
                "windowMs": inc["windowMs"],
                "complete": now_ms >= hi,
                "members": sorted({str(ev.get("member") or "?")
                                   for ev in events}),
                "events": events,
            })
        return out

    def count(self) -> int:
        return len(self._incidents)
