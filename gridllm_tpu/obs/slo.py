"""SLO engine: per-request-class objectives, attainment, burn rates,
goodput (ISSUE 2).

Raw latency histograms (PR 1) say how fast the system is; this layer says
whether it is fast ENOUGH. Each resolved request is classified
(:func:`classify_request`) and judged against its class's configured
objectives (TTFT / inter-token latency / end-to-end, utils/config.py
``SLOConfig``). The per-class outcome stream feeds:

- cumulative attainment ratios (within-SLO / total) and per-objective
  violation counters;
- multi-window **burn rates** — the pace at which the class is spending
  its error budget: ``(violation rate over window) / (1 - target)``. A
  burn rate of 1.0 sustained for the whole window exactly exhausts the
  budget; alerting pairs a fast window (paging) with a slow one
  (ticketing) — deploy/prometheus-alerts.yml encodes the pairing;
- **goodput**: tokens served by within-SLO requests vs. all tokens, plus
  wasted-token accounting for work the cluster did and then threw away
  (duplicate executions surfaced by PR 1's at-least-once counters,
  cancelled decodes).

Everything is exposed twice from the SAME state: gauges on ``/metrics``
(render-time collector) and JSON at ``GET /admin/slo`` — so scrapes and
snapshots cannot disagree. Pure stdlib; thread-safe.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

from gridllm_tpu.obs.metrics import MetricsRegistry
from gridllm_tpu.utils.config import SLOConfig

# objectives a request can violate; "error" marks failed/timed-out requests
OBJECTIVES = ("ttft", "itl", "e2e", "error")


def classify_request(request: Any) -> str:
    """Request class for SLO purposes: embeddings are their own class,
    streaming generation is interactive, the rest is batch."""
    if getattr(request, "request_type", "") == "embedding" or \
            getattr(request, "input", None) is not None:
        return "embedding"
    if getattr(request, "stream", False):
        return "interactive"
    return "batch"


class _ClassState:
    __slots__ = ("requests", "within", "tokens", "goodput_tokens",
                 "violations", "events")

    def __init__(self) -> None:
        self.requests = 0
        self.within = 0
        self.tokens = 0
        self.goodput_tokens = 0
        self.violations: dict[str, int] = {}
        # (ts, ok) outcome stream for windowed burn rates; bounded so a
        # flood cannot grow memory — at the cap the oldest events age out
        # exactly as the window prune would have dropped them anyway
        self.events: deque[tuple[float, bool]] = deque(maxlen=65536)


class SLOEngine:
    def __init__(self, config: SLOConfig | None = None,
                 metrics: MetricsRegistry | None = None):
        self.config = config or SLOConfig()
        self.metrics = metrics or MetricsRegistry()
        self._lock = threading.Lock()
        self._classes: dict[str, _ClassState] = {}
        # per-model breakdown (ISSUE 16): same outcome stream keyed by
        # model, JSON-only (/admin/slo "models") — no extra gauge series
        self._models: dict[str, _ClassState] = {}
        self._wasted: dict[str, int] = {}  # reason → tokens
        m = self.metrics
        self._requests_total = m.counter(
            "gridllm_slo_requests_total",
            "Requests judged against their class SLO.", ("slo_class",))
        self._violations_total = m.counter(
            "gridllm_slo_violations_total",
            "SLO objective violations, by class and objective "
            "(ttft/itl/e2e/error).", ("slo_class", "objective"))
        self._tokens_total = m.counter(
            "gridllm_slo_tokens_total",
            "Output tokens attributed to SLO-judged requests, by class.",
            ("slo_class",))
        self._goodput_tokens = m.counter(
            "gridllm_goodput_tokens_total",
            "Output tokens served by within-SLO requests, by class.",
            ("slo_class",))
        self._wasted_tokens = m.counter(
            "gridllm_goodput_wasted_tokens_total",
            "Output tokens the cluster generated and then discarded "
            "(duplicate executions, cancellations), by reason.",
            ("reason",))
        self._attainment = m.gauge(
            "gridllm_slo_attainment_ratio",
            "Cumulative fraction of requests meeting every objective of "
            "their class.", ("slo_class",))
        self._burn = m.gauge(
            "gridllm_slo_burn_rate",
            "Error-budget burn rate over a trailing window: violation "
            "rate / (1 - target). 1.0 sustained for the window exhausts "
            "the budget.", ("slo_class", "window"))
        self._goodput_ratio = m.gauge(
            "gridllm_goodput_ratio",
            "Within-SLO tokens / all SLO-judged tokens, cumulative.")
        m.add_collector("slo", self._collect)

    # -- recording ----------------------------------------------------------
    def record(self, slo_class: str, ok: bool = True,
               ttft_s: float | None = None, itl_s: float | None = None,
               e2e_s: float | None = None, tokens: int = 0,
               now: float | None = None, model: str | None = None) -> bool:
        """Judge one resolved request. ``ok=False`` (failure/timeout) is an
        unconditional violation ("error"); otherwise each objective the
        class configures is checked against the measurement provided (a
        missing measurement — e.g. no ITL on a one-token reply — is not a
        violation). Returns whether the request was within SLO."""
        if not self.config.enabled:
            return True
        cls_cfg = self.config.classes.get(slo_class)
        violated: list[str] = []
        if not ok:
            violated.append("error")
        elif cls_cfg is not None:
            checks = (("ttft", cls_cfg.ttft_ms, ttft_s),
                      ("itl", cls_cfg.itl_ms, itl_s),
                      ("e2e", cls_cfg.e2e_ms, e2e_s))
            violated = [name for name, limit_ms, measured_s in checks
                        if limit_ms is not None and measured_s is not None
                        and measured_s * 1000 > limit_ms]
        within = not violated
        ts = time.time() if now is None else now
        with self._lock:
            st = self._classes.setdefault(slo_class, _ClassState())
            st.requests += 1
            st.tokens += tokens
            if within:
                st.within += 1
                st.goodput_tokens += tokens
            for obj in violated:
                st.violations[obj] = st.violations.get(obj, 0) + 1
            st.events.append((ts, within))
            if model:
                ms = self._models.setdefault(model, _ClassState())
                ms.requests += 1
                ms.tokens += tokens
                if within:
                    ms.within += 1
                    ms.goodput_tokens += tokens
                for obj in violated:
                    ms.violations[obj] = ms.violations.get(obj, 0) + 1
                ms.events.append((ts, within))
        self._requests_total.inc(slo_class=slo_class)
        self._tokens_total.inc(tokens, slo_class=slo_class)
        if within:
            self._goodput_tokens.inc(tokens, slo_class=slo_class)
        for obj in violated:
            self._violations_total.inc(slo_class=slo_class, objective=obj)
        return within

    def record_waste(self, tokens: int, reason: str) -> None:
        """Account tokens that were generated and then thrown away."""
        if tokens <= 0:
            return
        with self._lock:
            self._wasted[reason] = self._wasted.get(reason, 0) + tokens
        self._wasted_tokens.inc(tokens, reason=reason)

    # -- derived views ------------------------------------------------------
    def _burn_rates_locked(self, st: _ClassState, target: float,
                           now: float) -> dict[int, float]:
        """All configured windows in ONE newest-first walk of the event
        deque (called with the lock held): windows sorted ascending share
        the pass — when the walk crosses a window's cutoff, that window's
        counts are frozen and the walk continues for the larger ones."""
        windows = sorted(self.config.windows_s)
        budget = max(1.0 - target, 1e-9)
        counts: dict[int, tuple[int, int]] = {}  # window → (total, bad)
        total = bad = 0
        wi = 0
        for ts, within in reversed(st.events):
            while wi < len(windows) and ts < now - windows[wi]:
                counts[windows[wi]] = (total, bad)
                wi += 1
            if wi >= len(windows):
                break
            total += 1
            bad += 0 if within else 1
        for w in windows[wi:]:
            counts[w] = (total, bad)
        return {w: ((b / t) / budget if t else 0.0)
                for w, (t, b) in counts.items()}

    def _target_of(self, name: str) -> float:
        cfg = self.config.classes.get(name)
        return cfg.target if cfg is not None else 0.99

    def _collect(self) -> None:
        """Render-time collector: gauges from the same state snapshot()
        reads, so /metrics and /admin/slo always agree."""
        now = time.time()
        with self._lock:
            classes = dict(self._classes)
            total_tokens = sum(st.tokens for st in classes.values())
            good_tokens = sum(st.goodput_tokens for st in classes.values())
            burns = {name: self._burn_rates_locked(st, self._target_of(name),
                                                   now)
                     for name, st in classes.items()}
        for name, st in classes.items():
            if st.requests:
                self._attainment.set(st.within / st.requests, slo_class=name)
            for w, rate in burns[name].items():
                self._burn.set(rate, slo_class=name, window=f"{w}s")
        if total_tokens:
            self._goodput_ratio.set(good_tokens / total_tokens)

    def snapshot(self) -> dict[str, Any]:
        """The /admin/slo JSON body."""
        now = time.time()
        out_classes: dict[str, Any] = {}
        with self._lock:
            classes = dict(self._classes)
            models = dict(self._models)
            wasted = dict(self._wasted)
            burns = {name: self._burn_rates_locked(st, self._target_of(name),
                                                   now)
                     for name, st in classes.items()}
        total_tokens = good_tokens = 0
        for name, st in classes.items():
            cfg = self.config.classes.get(name)
            burn = {f"{w}s": round(rate, 4)
                    for w, rate in burns[name].items()}
            total_tokens += st.tokens
            good_tokens += st.goodput_tokens
            out_classes[name] = {
                "objectives": (cfg.model_dump() if cfg is not None else None),
                "requests": st.requests,
                "withinSlo": st.within,
                "attainment": (round(st.within / st.requests, 6)
                               if st.requests else None),
                "violations": dict(st.violations),
                "burnRates": burn,
                "tokens": st.tokens,
                "goodputTokens": st.goodput_tokens,
            }
        return {
            "enabled": self.config.enabled,
            "windowsS": list(self.config.windows_s),
            "classes": out_classes,
            "models": {
                name: {
                    "requests": ms.requests,
                    "withinSlo": ms.within,
                    "attainment": (round(ms.within / ms.requests, 6)
                                   if ms.requests else None),
                    "violations": dict(ms.violations),
                    "tokens": ms.tokens,
                    "goodputTokens": ms.goodput_tokens,
                }
                for name, ms in models.items()
            },
            "goodput": {
                "tokensTotal": total_tokens,
                "tokensWithinSlo": good_tokens,
                "ratio": (round(good_tokens / total_tokens, 6)
                          if total_tokens else None),
                "wastedTokens": wasted,
            },
        }
