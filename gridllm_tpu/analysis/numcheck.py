"""Runtime numerics sanitizer (``GRIDLLM_SANITIZE=1``, gridcheck v3).

The differential tests prove each Pallas kernel against its jnp oracle
on the shapes the tests happen to exercise; this module proves the SAME
contract on whatever shapes the serving path actually dispatches. When
armed it does two things:

1. **Shadow execution.** A sampled fraction of kernel dispatches (the
   attention dispatchers in ``ops/attention.py``) also trace the
   registry's jnp reference and compare the two outputs inside the
   compiled program, at the per-op tolerance ``ops/kernels.py``
   declares. The excess error (beyond ``atol + rtol * |ref|``) reaches
   the host through ``jax.debug.callback``; any excess > 0 is a
   violation. Sampling is decided at TRACE time — one decision per
   compiled program, a pure function of (GRIDLLM_NUMCHECK_SEED, op,
   trace #), same determinism contract as faults.py — so a shadowed
   program checks every step it runs while unshadowed programs pay
   nothing.
2. **NaN/Inf tripwire.** Sampler logits (``ops/sampling.py``) and fresh
   KV rows at the pool-write boundary (``ops/kvcache.py``) are checked
   finite every step. A NaN here is the first observable symptom of a
   diverged kernel, a poisoned weight load, or an out-of-range int8
   scale — caught at the write, not three requests later in a garbled
   stream.

Violations are recorded here, mirrored to the flight recorder
(``numcheck`` ring), and fail the test session exit-3 in
``tests/conftest.py`` — exactly like lockcheck's cycle check and
statecheck's shared-state verdict. Dormant unless ``GRIDLLM_SANITIZE``
is truthy: the hot-path cost is one module-boolean check per dispatch.

Comparisons honor each dispatcher's validity mask (padding rows and
inactive slots are UNSPECIFIED kernel output by contract — the
differential tests skip them and so does the shadow).
"""

from __future__ import annotations

import functools
import random
import threading
from typing import Any, Callable

from gridllm_tpu.utils.config import env_bool, env_float, env_int

_lock = threading.Lock()
_loaded = False
_armed = False
_sample = 0.0
_rngs: dict[str, random.Random] = {}
_stats = {"shadowed": 0, "finite_checks": 0}
_violations: list[dict[str, Any]] = []


def enabled() -> bool:
    return env_bool("GRIDLLM_SANITIZE")


def _load() -> None:
    global _loaded, _armed, _sample
    with _lock:
        if _loaded:
            return
        _armed = enabled()
        _sample = min(max(env_float("GRIDLLM_NUMCHECK_SAMPLE"), 0.0), 1.0)
        _loaded = True


def configure(sample: float | None = None, seed: int | None = None,
              armed: bool | None = None) -> None:
    """Test/driver entry point: override the env-resolved policy (and
    reset the per-op decision streams so a reconfigure is reproducible
    from call #1)."""
    global _loaded, _armed, _sample
    _load()
    with _lock:
        if sample is not None:
            _sample = min(max(sample, 0.0), 1.0)
        if armed is not None:
            _armed = armed
        _rngs.clear()
        if seed is not None:
            _seed_override["seed"] = seed


_seed_override: dict[str, int] = {}


def _decide(op: str) -> bool:
    """One trace-time sampling decision for `op` — pure function of
    (seed, op, call #), the faults.py determinism contract."""
    if _sample >= 1.0:
        return True
    if _sample <= 0.0:
        return False
    with _lock:
        rng = _rngs.get(op)
        if rng is None:
            seed = _seed_override.get("seed",
                                      env_int("GRIDLLM_NUMCHECK_SEED"))
            rng = _rngs[op] = random.Random(f"{seed}|{op}")
        return rng.random() < _sample


def active() -> bool:
    _load()
    return _armed


def _record(kind: str, op: str, **fields: Any) -> None:
    entry = {"kind": kind, "op": op, **fields}
    with _lock:
        _violations.append(entry)
    from gridllm_tpu.obs.flightrec import default_flight_recorder

    default_flight_recorder().record("numcheck", kind, op=op, **fields)


def _on_shadow(op: str, rtol: float, atol: float, excess, maxerr) -> None:
    # NaN excess (kernel went non-finite where the reference is finite)
    # must COUNT: `x > 0` is False for NaN, so test the negation
    if not float(excess) <= 0.0:
        _record("tolerance", op, rtol=rtol, atol=atol,
                excess=float(excess), max_err=float(maxerr))


def _on_finite(site: str, bad) -> None:
    if int(bad):
        _record("nonfinite", site, bad_elements=int(bad))


def shadow(op: str, out: Any, ref_thunk: Callable[[], Any],
           valid: Any = None) -> Any:
    """Maybe weave a reference-comparison into the traced program around
    a kernel dispatch. ``out`` is the kernel output (array, or a tuple
    possibly containing None — the ragged dispatcher's shape);
    ``ref_thunk`` builds the jnp reference lazily (only traced when this
    dispatch is sampled); ``valid`` is an optional bool mask (or
    matching tuple) selecting the contractually-specified elements.
    Returns ``out`` unchanged — the shadow only observes."""
    _load()
    if not _armed or not _decide(op):
        return out
    import jax
    import jax.numpy as jnp

    from gridllm_tpu.ops.kernels import tolerance

    rtol, atol = tolerance(op)
    ref = ref_thunk()
    outs = out if isinstance(out, tuple) else (out,)
    refs = ref if isinstance(ref, tuple) else (ref,)
    valids = valid if isinstance(valid, tuple) else (valid,) * len(outs)
    excess = jnp.float32(0.0)
    maxerr = jnp.float32(0.0)
    for o, r, v in zip(outs, refs, valids):
        if o is None or r is None:
            continue
        of = o.astype(jnp.float32)
        rf = r.astype(jnp.float32)
        err = jnp.abs(of - rf)
        bound = atol + rtol * jnp.abs(rf)
        over = err - bound
        if v is not None:
            mask = jnp.broadcast_to(
                jnp.reshape(v, v.shape + (1,) * (of.ndim - v.ndim)),
                of.shape)
            err = jnp.where(mask, err, 0.0)
            over = jnp.where(mask, over, -jnp.inf)
        excess = jnp.maximum(excess, over.max())
        maxerr = jnp.maximum(maxerr, err.max())
    with _lock:
        _stats["shadowed"] += 1
    # static context (op name, tolerances) closes over the callback;
    # only the two scalars travel through the device boundary
    jax.debug.callback(functools.partial(_on_shadow, op, rtol, atol),
                       excess, maxerr)
    return out


def check_finite(site: str, *arrays: Any) -> None:
    """NaN/Inf tripwire: count non-finite elements across ``arrays``
    (floating-point leaves only) and report any through the callback.
    No-op unless the sanitizer is armed."""
    _load()
    if not _armed:
        return
    import jax
    import jax.numpy as jnp

    bad = jnp.int32(0)
    counted = False
    for a in arrays:
        if a is None or not jnp.issubdtype(a.dtype, jnp.floating):
            continue
        bad = bad + jnp.sum(~jnp.isfinite(a)).astype(jnp.int32)
        counted = True
    if not counted:
        return
    with _lock:
        _stats["finite_checks"] += 1
    jax.debug.callback(functools.partial(_on_finite, site), bad)


def violations() -> list[dict[str, Any]]:
    with _lock:
        return list(_violations)


def report() -> dict[str, Any]:
    with _lock:
        return {"armed": _armed, "sample": _sample,
                "shadowed_dispatches": _stats["shadowed"],
                "finite_checks": _stats["finite_checks"],
                "violations": list(_violations),
                "ok": not _violations}


def assert_clean() -> None:
    v = violations()
    if v:
        lines = [
            f"{x['op']}: {x['kind']} "
            + (f"(excess {x['excess']:.3e} past rtol={x['rtol']} "
               f"atol={x['atol']}, max err {x['max_err']:.3e})"
               if x["kind"] == "tolerance"
               else f"({x['bad_elements']} non-finite elements)")
            for x in v]
        raise NumericsError(
            "kernel numerics violation(s) observed:\n  "
            + "\n  ".join(lines))


class NumericsError(AssertionError):
    """A shadowed kernel dispatch diverged from its jnp reference past
    the registry tolerance, or a tripwired array went non-finite."""


def reset() -> None:
    """Forget observations and decision streams (tests that deliberately
    trip the sanitizer restore cleanliness before session end)."""
    with _lock:
        _violations.clear()
        _rngs.clear()
        _stats["shadowed"] = 0
        _stats["finite_checks"] = 0


def reload_from_env() -> None:
    """Drop any configure() overrides and re-resolve armed/sample/seed
    from the environment on the next use — the exact restore for tests
    that reconfigured the sanitizer (a hardcoded restore would clobber a
    CI run's forced GRIDLLM_NUMCHECK_SAMPLE for every later suite)."""
    global _loaded
    with _lock:
        _loaded = False
        _rngs.clear()
        _seed_override.clear()
