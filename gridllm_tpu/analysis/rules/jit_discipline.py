"""jit-discipline: every jitted engine entry point is observable and
shape-honest.

Invariants over ``engine/engine.py`` (the module that owns every jitted
serving-path program):

1. **Tripwire coverage** — every ``jax.jit`` (decorator or inline call)
   is wrapped by the recompile-tripwire probe (``self.perf.wrap``,
   obs/perf.py). An unwrapped jit is a program whose steady-state
   recompiles are invisible to ``gridllm_recompiles_total`` and the
   storm diagnosis — the exact blind spot PR 4 closed.
2. **No host sync / trace-variant branching inside** — within a jitted
   function body: no ``.item()`` (device sync per call), and no
   ``if``/``while`` whose condition reads a traced (non-static)
   parameter, except ``is``/``is not None`` structure checks, which are
   resolved at trace time. Branching on traced values either crashes at
   trace time or silently multiplies compile signatures.
"""

from __future__ import annotations

import ast

from gridllm_tpu.analysis.core import Finding, Repo, ancestors, dotted_name, rule, str_const

RULE = "jit-discipline"
ENGINE = "gridllm_tpu/engine/engine.py"


def _jit_decorator(dec: ast.AST) -> tuple[bool, set[str]]:
    """(is_jax_jit, static_argnames) for one decorator expression."""
    name = dotted_name(dec)
    if name in ("jax.jit", "jit"):
        return True, set()
    if isinstance(dec, ast.Call) and dotted_name(dec.func).endswith("partial") \
            and dec.args and dotted_name(dec.args[0]) in ("jax.jit", "jit"):
        statics: set[str] = set()
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                if isinstance(kw.value, (ast.Tuple, ast.List)):
                    statics = {s for e in kw.value.elts
                               if (s := str_const(e)) is not None}
                elif (s := str_const(kw.value)) is not None:
                    statics = {s}
        return True, statics
    return False, set()


def _is_none_check_only(test: ast.expr, param: str) -> bool:
    """True when every use of ``param`` in the condition is an
    ``is``/``is not`` comparison (trace-time structure check)."""
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id == param:
            ok = False
            for anc in ancestors(node):
                if isinstance(anc, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in anc.ops):
                    ok = True
                    break
                if anc is test:
                    break
            if not ok:
                return False
    return True


@rule(RULE, "every jax.jit in the engine is tripwire-wrapped; no .item() "
            "or traced-value branching inside jitted bodies")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    f = repo.file(ENGINE)
    if f is None or f.tree is None:
        return [Finding(RULE, ENGINE, 0, "engine module missing/unparsable")]

    wrapped_names: set[str] = set()       # fn names passed to *.wrap(...)
    for node in f.walk():
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "wrap":
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    wrapped_names.add(arg.id)

    for node in f.walk():
        # decorated jitted functions
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            statics: set[str] = set()
            jitted = False
            for dec in node.decorator_list:
                is_jit, st = _jit_decorator(dec)
                if is_jit:
                    jitted, statics = True, st
            if not jitted:
                continue
            if node.name not in wrapped_names:
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"jitted function {node.name}() is never passed to the "
                    "recompile-tripwire probe (self.perf.wrap) — its "
                    "steady-state recompiles are invisible"))
            params = {a.arg for a in node.args.args
                      + node.args.posonlyargs + node.args.kwonlyargs}
            traced = params - statics - {"self"}
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) \
                        and isinstance(inner.func, ast.Attribute) \
                        and inner.func.attr == "item" and not inner.args:
                    findings.append(Finding(
                        RULE, f.rel, inner.lineno,
                        f".item() inside jitted {node.name}() — per-call "
                        "device sync; compute it outside the jit"))
                if isinstance(inner, (ast.If, ast.While)):
                    used = {n.id for n in ast.walk(inner.test)
                            if isinstance(n, ast.Name)} & traced
                    bad = {p for p in used
                           if not _is_none_check_only(inner.test, p)}
                    if bad:
                        findings.append(Finding(
                            RULE, f.rel, inner.lineno,
                            f"python branch on traced value(s) "
                            f"{sorted(bad)} inside jitted {node.name}() — "
                            "crashes at trace time or forks compile "
                            "signatures; use jnp.where/lax.cond or make "
                            "the arg static"))
        # inline jax.jit(...) calls must sit inside a *.wrap(...) call
        if isinstance(node, ast.Call) and dotted_name(node.func) in (
                "jax.jit", "jit"):
            in_wrap = any(
                isinstance(anc, ast.Call)
                and isinstance(anc.func, ast.Attribute)
                and anc.func.attr == "wrap"
                for anc in ancestors(node))
            if not in_wrap:
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    "inline jax.jit(...) not wrapped by the recompile-"
                    "tripwire probe (self.perf.wrap)"))
    return findings
