"""dashboard-drift: Grafana panels, Prometheus alerts, the README metrics
table, and the code's metric registrations must all agree.

Direction 1: every ``gridllm_*`` series referenced by
``deploy/grafana-dashboard.json`` or ``deploy/prometheus-alerts.yml``
must be exported by a registration in code (histogram registrations
export ``_bucket``/``_sum``/``_count``; the bare family name is also
accepted — alert annotations name families).

Direction 2: every metric registered in code must appear in the README
metrics table (brace shorthand like ``gridllm_engine_kv_pages_{used,free}``
expands), and every name the table documents must exist in code.

A dashboard querying a renamed metric renders flat zeros during the
exact incident it was built for — this rule makes that a CI failure
instead of a 3am discovery.
"""

from __future__ import annotations

import itertools
import re

from gridllm_tpu.analysis.core import Finding, Repo, collect_metric_registrations, rule

RULE = "dashboard-drift"
DEPLOY_REFS = ("deploy/grafana-dashboard.json", "deploy/prometheus-alerts.yml")
_NAME = re.compile(r"\bgridllm_[a-z0-9_]+\b")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")
_BRACE = re.compile(r"\{([a-z0-9_,]+)\}")


def expand_braces(token: str) -> list[str]:
    """``a_{x,y}_b`` → [``a_x_b``, ``a_y_b``] (multiple groups multiply)."""
    groups = _BRACE.findall(token)
    if not groups:
        return [token]
    template = _BRACE.sub("{}", token)
    out = []
    for combo in itertools.product(*(g.split(",") for g in groups)):
        out.append(template.format(*combo))
    return out


def readme_table_metrics(readme: str) -> dict[str, int]:
    """Metric names documented in README table rows (lines starting with
    ``|``), brace shorthand expanded → first line number seen."""
    out: dict[str, int] = {}
    for i, line in enumerate(readme.splitlines(), 1):
        if not line.lstrip().startswith("|"):
            continue
        for raw in re.findall(r"`([^`]*gridllm_[^`]*)`", line):
            # brace groups don't end on a \b boundary — match them
            # explicitly; require a name char after the prefix so a bare
            # "`gridllm_`" (prose about the namespace) is not a metric
            for tok in re.findall(
                    r"\bgridllm_[a-z0-9][a-z0-9_]*(?:\{[a-z0-9_,]+\}[a-z0-9_]*)*",
                    raw):
                for name in expand_braces(tok):
                    out.setdefault(name, i)
    return out


@rule(RULE, "grafana/prometheus metric references exist in code; "
            "registered metrics are documented in the README table")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    regs = collect_metric_registrations(repo)
    registered = {r.name: r for r in regs}
    exported: set[str] = set()
    for r in regs:
        if r.kind == "histogram":
            exported.update(r.name + s for s in _HIST_SUFFIXES)
        exported.add(r.name)  # family name: legal in annotations/docs

    # 1. deploy artifacts reference only exported/registered series
    for rel in DEPLOY_REFS:
        text = repo.read_text(rel)
        if text is None:
            findings.append(Finding(RULE, rel, 0, f"{rel} missing"))
            continue
        for i, line in enumerate(text.splitlines(), 1):
            for name in _NAME.findall(line):
                base = name
                for s in _HIST_SUFFIXES:
                    if name.endswith(s):
                        base = name[: -len(s)]
                        break
                if name in exported:
                    # suffixed reference must belong to a histogram
                    if base != name and registered.get(base) \
                            and registered[base].kind != "histogram":
                        findings.append(Finding(
                            RULE, rel, i,
                            f"{name} uses histogram suffix but "
                            f"{base} is a {registered[base].kind}"))
                    # a bare histogram family inside a Grafana QUERY is a
                    # series that never exists — the panel renders flat
                    # zeros. Family names stay legal in alert annotations
                    # and dashboard prose (titles, descriptions).
                    elif base == name and '"expr"' in line \
                            and rel.endswith(".json") \
                            and registered.get(name) \
                            and registered[name].kind == "histogram":
                        findings.append(Finding(
                            RULE, rel, i,
                            f"{name} is a histogram family; queries must "
                            "use the _bucket/_sum/_count series"))
                    continue
                if base in registered and base != name:
                    # e.g. counter referenced with _bucket
                    findings.append(Finding(
                        RULE, rel, i,
                        f"{name}: {base} is a {registered[base].kind}, "
                        "which does not export this series"))
                else:
                    findings.append(Finding(
                        RULE, rel, i,
                        f"{name} is referenced here but no code registers "
                        "it — dashboard/alert drift"))

    # 2. README metrics table <-> registrations, both directions
    readme = repo.read_text("README.md")
    if readme is None:
        findings.append(Finding(RULE, "README.md", 0, "README.md missing"))
        return findings
    documented = readme_table_metrics(readme)
    for r in regs:
        if r.name not in documented:
            findings.append(Finding(
                RULE, r.file, r.line,
                f"{r.name} is registered here but missing from the README "
                "metrics table"))
    for name, line in sorted(documented.items()):
        if name not in registered:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README metrics table documents {name}, which no code "
                "registers"))
    return findings
