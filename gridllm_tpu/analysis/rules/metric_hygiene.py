"""metric-hygiene: naming + label-cardinality policy, one rule shared by
static and runtime checkers (ISSUE 8 satellite — folded in from
tests/test_metric_hygiene.py, which now imports THIS module, so the
policy lives in exactly one place).

Policy, applied to every instrument:

- name matches ``gridllm_[a-z][a-z0-9_]*`` (prefixed, lowercase,
  snake_case — the scrape namespace stays greppable);
- no unbounded-cardinality label (per-request/job/trace ids, raw text):
  one bad label turns a scrape into a memory leak and kills the TSDB;
- non-empty help text (the dashboard hover IS the documentation);
- (static half only) ``tenant``-labeled instruments may be registered
  only by the usage ledger (obs/usage.py), whose TenantLRU caps the
  label's value space — see TENANT_LABEL_ALLOWED_FILES.

The static half checks registration call sites (literal name/help/label
args — a non-literal name is itself a finding, since nothing can audit
it); the runtime half (:func:`lint_registry`) lints live registries so
dynamically built instruments are covered by the test suite.
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import Finding, Repo, collect_metric_registrations, rule

RULE = "metric-hygiene"

NAME_RE = re.compile(r"^gridllm_[a-z][a-z0-9_]*$")

# labels whose value space grows with traffic — forbidden on any instrument
FORBIDDEN_LABELS = {
    "request_id", "requestid", "job_id", "jobid", "id", "trace_id",
    "traceid", "span_id", "prompt", "text", "user", "session",
}

# ISSUE 16: tenant-labeled series are allowed ONLY in the usage ledger,
# where a TenantLRU bounds the label's cardinality at labeling time.
# Anywhere else a `tenant` label is an unbounded-cardinality leak waiting
# for the first adversarial client. This is a static-scan rule, NOT a
# FORBIDDEN_LABELS entry: the runtime lint (lint_registry) runs against
# live registries that legitimately contain the ledger's tenant series.
TENANT_LABEL_ALLOWED_FILES = {"gridllm_tpu/obs/usage.py"}


def lint_registry(registry, origin: str) -> list[str]:
    """Runtime lint over a live MetricsRegistry (obs/metrics.py) — used by
    tests/test_metric_hygiene.py against the instance + process-global
    registries after building a full gateway stack."""
    problems = []
    with registry._lock:
        metrics = list(registry._metrics.values())
    if not metrics:
        problems.append(f"{origin}: no metrics registered — lint is vacuous")
    for m in metrics:
        if not NAME_RE.match(m.name):
            problems.append(f"{origin}: {m.name!r} violates "
                            "gridllm_[a-z0-9_]+ naming")
        for label in m.labelnames:
            if label.lower() in FORBIDDEN_LABELS:
                problems.append(f"{origin}: {m.name!r} carries unbounded-"
                                f"cardinality label {label!r}")
        if not m.help:
            problems.append(f"{origin}: {m.name!r} has no help text")
    return problems


@rule(RULE, "metric names gridllm_-prefixed snake_case, no unbounded-"
            "cardinality labels, non-empty help text")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for r in collect_metric_registrations(repo):
        if not NAME_RE.match(r.name):
            findings.append(Finding(
                RULE, r.file, r.line,
                f"metric name {r.name!r} violates gridllm_[a-z0-9_]+ "
                "naming"))
        if r.help is None or not r.help.strip():
            findings.append(Finding(
                RULE, r.file, r.line,
                f"{r.name}: help text missing or not a string literal"))
        if r.labels is None:
            findings.append(Finding(
                RULE, r.file, r.line,
                f"{r.name}: labels are not a literal tuple — the label "
                "policy cannot be audited statically"))
        else:
            for label in r.labels:
                if label.lower() in FORBIDDEN_LABELS:
                    findings.append(Finding(
                        RULE, r.file, r.line,
                        f"{r.name}: unbounded-cardinality label "
                        f"{label!r}"))
                elif ("tenant" in label.lower()
                        and r.file not in TENANT_LABEL_ALLOWED_FILES):
                    findings.append(Finding(
                        RULE, r.file, r.line,
                        f"{r.name}: label {label!r} — tenant attribution "
                        "belongs in obs/usage.py, where the TenantLRU "
                        "bounds its cardinality; a tenant label anywhere "
                        "else is an unbounded series leak"))
    # a static scan that sees nothing is itself broken
    if not findings and not collect_metric_registrations(repo):
        findings.append(Finding(
            RULE, "gridllm_tpu", 0,
            "no metric registrations found — the static scan is vacuous"))
    return findings
