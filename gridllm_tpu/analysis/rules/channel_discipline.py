"""channel-discipline: every bus channel goes through the typed channel
registry in ``bus/base.py`` (ISSUE 13 — the protocol twin of PR 8's
config-discipline).

Invariants:

1. No raw channel-name string (literal or f-string) as the channel
   argument of a ``bus.publish``/``subscribe``/``psubscribe`` call site
   outside ``gridllm_tpu/bus/`` (implementations relay caller-supplied
   names; tests own their protocol). Call sites use the registered
   ``CH_*`` constants / ``*_channel`` helpers.
2. Publish/subscribe direction matches the registry: a module publishing
   on a family must be a declared publisher, ditto subscribers; every
   declared publisher/subscriber module actually references the family's
   constant/helper (a channel published but never subscribed — or vice
   versa — cannot hide behind the registry).
3. Publisher-side payload keys agree with the declared payload model
   both ways: a ``json.dumps({...})`` literal key that is not declared is
   a finding, and so is a declared key no publisher ever sends (skipped
   when any publish site for the family is statically unauditable, e.g.
   a ``**splat``). Model-typed families (``JobResult``/``StreamChunk``/
   ``WorkerInfo``) check the constructed class where it resolves.
4. The registry's constants/helpers spell exactly the registered
   pattern, and ``durable_channel``/``channel_class`` DERIVE from the
   registry — no hardcoded channel literals inside them, so a channel
   cannot be durable-in-docs but fire-and-forget-in-code.
5. The README "Bus channels" table and the registry agree both ways
   (name, durability, payload), the way config-discipline pins the
   Configuration table.

Like config-discipline, the registry is parsed from the ANALYZED tree so
``--root`` on another checkout validates that checkout; fixture repos
without a bus/base.py registry fall back to the imported registry and
skip the repo-structure checks (2, 4, 5).
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import (
    Finding,
    Repo,
    dotted_name,
    enclosing_function,
    rule,
    str_const,
)

RULE = "channel-discipline"
BUS_BASE = "gridllm_tpu/bus/base.py"
_PUBLISH_ATTRS = {"publish"}
_SUBSCRIBE_ATTRS = {"subscribe", "psubscribe"}


class _Spec:
    __slots__ = ("family", "pattern", "payload", "keys", "durable",
                 "publishers", "subscribers", "helper", "line")

    def __init__(self, family, pattern, payload, keys, durable,
                 publishers, subscribers, helper, line):
        self.family = family
        self.pattern = pattern
        self.payload = payload
        self.keys = keys
        self.durable = durable
        self.publishers = publishers
        self.subscribers = subscribers
        self.helper = helper
        self.line = line


def _tuple_const(node: ast.AST | None) -> tuple[str, ...] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [str_const(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)  # type: ignore[arg-type]
    return None


def _parse_registry(repo: Repo) -> tuple[dict[str, _Spec], bool]:
    """(family -> spec, from_tree). Parsed from the analyzed tree's
    bus/base.py; falls back to the imported registry for fixture repos,
    which then skip the repo-structure checks."""
    f = repo.file(BUS_BASE)
    specs: dict[str, _Spec] = {}
    if f is not None:
        for node in f.walk():
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func).endswith("register_channel")
                    and node.args):
                continue
            family = str_const(node.args[0])
            if family is None:
                continue
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            specs[family] = _Spec(
                family,
                str_const(kw.get("pattern")) or family,
                str_const(kw.get("payload")) or "keys",
                _tuple_const(kw.get("keys")) or (),
                isinstance(kw.get("durable"), ast.Constant)
                and bool(kw["durable"].value),  # type: ignore[union-attr]
                _tuple_const(kw.get("publishers")) or (),
                _tuple_const(kw.get("subscribers")) or (),
                str_const(kw.get("helper")) or "",
                node.lineno,
            )
    if specs:
        return specs, True
    from gridllm_tpu.bus.base import CHANNELS

    return {s.family: _Spec(s.family, s.pattern, s.payload, s.keys,
                            s.durable, s.publishers, s.subscribers,
                            s.helper, 0)
            for s in CHANNELS.values()}, False


def _normalize(pattern: str) -> str:
    return re.sub(r"\{[^{}]*\}", "{}", pattern)


def _fstring_pattern(node: ast.AST,
                     consts: dict[str, str] | None = None) -> str | None:
    """Normalized pattern a return expression spells: a string constant,
    or an f-string whose placeholders become ``{}`` — except names bound
    to module-level string constants (``consts``), which substitute
    their value so single-source prefixes like ``TRACE_CHANNEL_PREFIX``
    stay auditable. None when anything is not statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant) and isinstance(part.value, str):
                out.append(part.value)
            elif isinstance(part, ast.FormattedValue) \
                    and isinstance(part.value, ast.Name):
                bound = (consts or {}).get(part.value.id)
                out.append(bound if bound is not None else "{}")
            else:
                return None
        return "".join(out)
    return None


def _module_str_consts(f) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (top-level statements
    only — f-string prefix constants are module-level by convention)."""
    out: dict[str, str] = {}
    tree = f.tree
    if tree is None:
        return out
    for node in tree.body:
        if isinstance(node, ast.Assign):
            val = str_const(node.value)
            if val is not None:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out[tgt.id] = val
    return out


def _collect_symbols(repo: Repo) -> dict[str, tuple[dict[str, int], str]]:
    """rel -> ({referenced name: first line}, source text) for quick
    "does this module reference the helper" checks. Names include bare
    Name loads, attribute tails, and imported names."""
    out: dict[str, tuple[dict[str, int], str]] = {}
    for f in repo.files:
        names: dict[str, int] = {}
        for node in f.walk():
            if isinstance(node, ast.Name):
                names.setdefault(node.id, node.lineno)
            elif isinstance(node, ast.Attribute):
                names.setdefault(node.attr, node.lineno)
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    names.setdefault(alias.name, node.lineno)
        out[f.rel] = (names, f.text)
    return out


def _resolve_model_class(call: ast.Call) -> str | None:
    """Class name behind ``X.model_dump_json()``: the enclosing function's
    ``X = SomeModel(...)`` / ``X = SomeModel.model_validate*(...)``
    assignment, best-effort (None when unresolvable)."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "model_dump_json"
            and isinstance(call.func.value, ast.Name)):
        return None
    var = call.func.value.id
    fn = enclosing_function(call)
    if fn is None:
        return None
    best: str | None = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and node.lineno < call.lineno \
                and isinstance(node.value, ast.Call):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == var:
                    first = dotted_name(node.value.func).split(".")[0]
                    if first[:1].isupper():
                        best = first
    return best


def _payload_of(call: ast.Call) -> ast.AST | None:
    if len(call.args) > 1:
        return call.args[1]
    for kw in call.keywords:
        if kw.arg == "message":
            return kw.value
    return None


def _dict_literal_keys(node: ast.AST) -> tuple[list[str], bool] | None:
    """(keys, has_splat) for a ``json.dumps({...})`` payload; None when
    the payload is not a statically visible dict literal."""
    if not (isinstance(node, ast.Call)
            and dotted_name(node.func).endswith("json.dumps")
            and node.args and isinstance(node.args[0], ast.Dict)):
        return None
    d = node.args[0]
    keys: list[str] = []
    splat = False
    for k in d.keys:
        if k is None:
            splat = True  # {**payload} — unauditable extras
        else:
            kv = str_const(k)
            if kv is None:
                splat = True
            else:
                keys.append(kv)
    return keys, splat


@rule(RULE, "bus channels go through the typed registry in bus/base.py; "
            "payload keys, durability, direction, and the README Bus "
            "channels table must all agree with it")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    specs, from_tree = _parse_registry(repo)
    by_helper = {s.helper: s for s in specs.values() if s.helper}

    bus_base = repo.file(BUS_BASE)
    constants: dict[str, str] = {}
    helper_fns: dict[str, tuple[str, str, int]] = {}  # name -> (pat, rel, ln)
    for f in repo.package_files():
        mod_consts = _module_str_consts(f)
        for node in f.walk():
            if isinstance(node, ast.Assign) and f.rel == BUS_BASE:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and re.fullmatch(r"CH_[A-Z0-9_]+", tgt.id):
                        val = str_const(node.value)
                        if val is not None:
                            constants[tgt.id] = val
            if isinstance(node, ast.FunctionDef) and (
                    node.name.endswith("_channel")
                    or node.name in by_helper):
                for stmt in node.body:
                    if isinstance(stmt, ast.Return) and stmt.value is not None:
                        pat = _fstring_pattern(stmt.value, mod_consts)
                        if pat is not None:
                            helper_fns[node.name] = (pat, f.rel, node.lineno)

    # -- 4. registry constants/helpers spell the registered pattern;
    #       durable_channel/channel_class derive from the registry
    if from_tree:
        for s in specs.values():
            if not s.helper:
                findings.append(Finding(
                    RULE, BUS_BASE, s.line,
                    f"channel family {s.family!r} declares no helper — "
                    "call sites have no sanctioned spelling"))
            elif s.helper.isupper() or s.helper.startswith("CH_"):
                lit = constants.get(s.helper)
                if lit is None:
                    findings.append(Finding(
                        RULE, BUS_BASE, s.line,
                        f"channel family {s.family!r}: constant "
                        f"{s.helper} is not defined in bus/base.py"))
                elif lit != s.pattern:
                    findings.append(Finding(
                        RULE, BUS_BASE, s.line,
                        f"constant {s.helper} = {lit!r} disagrees with "
                        f"the registered pattern {s.pattern!r}"))
            else:
                got = helper_fns.get(s.helper)
                if got is None:
                    findings.append(Finding(
                        RULE, BUS_BASE, s.line,
                        f"channel family {s.family!r}: helper "
                        f"{s.helper}() not found (or its return is not a "
                        "static f-string)"))
                elif _normalize(got[0]) != _normalize(s.pattern):
                    findings.append(Finding(
                        RULE, got[1], got[2],
                        f"{s.helper}() builds {got[0]!r} but the "
                        f"registered pattern is {s.pattern!r}"))
        if bus_base is not None:
            for node in bus_base.walk():
                if isinstance(node, ast.FunctionDef) \
                        and node.name in ("durable_channel", "channel_class"):
                    for sub in ast.walk(node):
                        val = str_const(sub)
                        # channel-ish literal: colon-joined tokens, no
                        # prose (docstrings have spaces)
                        if val is not None and ":" in val \
                                and " " not in val:
                            findings.append(Finding(
                                RULE, BUS_BASE, sub.lineno,
                                f"{node.name}() hardcodes channel name "
                                f"{val!r} — durability/classification "
                                "must derive from the CHANNELS registry"))

    # -- 1-3. call-site discipline + payload keys
    published_keys: dict[str, set[str]] = {}
    open_payload: set[str] = set()
    for f in repo.files:
        if f.rel.startswith(("tests/", "gridllm_tpu/bus/")):
            continue
        for node in f.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            attr = node.func.attr
            if attr not in _PUBLISH_ATTRS | _SUBSCRIBE_ATTRS:
                continue
            recv = dotted_name(node.func.value)
            if "bus" not in recv.lower().split(".")[-1]:
                continue
            ch = node.args[0] if node.args else None
            if ch is None:
                continue
            lit = str_const(ch)
            if lit is not None:
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"raw channel literal {lit!r} at bus.{attr}() — use "
                    "the registered constant/helper from bus/base.py"))
                continue
            if isinstance(ch, ast.JoinedStr):
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"f-string channel name at bus.{attr}() — use the "
                    "registered helper from bus/base.py"))
                continue
            # resolve the family behind a constant / helper call
            spec: _Spec | None = None
            if isinstance(ch, ast.Call):
                fn_name = dotted_name(ch.func).split(".")[-1]
                spec = by_helper.get(fn_name)
            else:
                sym = dotted_name(ch).split(".")[-1]
                spec = by_helper.get(sym)
            if spec is None:
                continue  # opaque variable — built by a helper upstream
            if from_tree:
                if attr in _PUBLISH_ATTRS and f.rel not in spec.publishers:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{f.rel} publishes on {spec.family!r} but is not "
                        "a declared publisher in the channel registry"))
                if attr in _SUBSCRIBE_ATTRS \
                        and f.rel not in spec.subscribers:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{f.rel} subscribes to {spec.family!r} but is "
                        "not a declared subscriber in the channel "
                        "registry"))
            if attr not in _PUBLISH_ATTRS or spec.payload == "opaque":
                continue
            payload = _payload_of(node)
            if payload is None:
                open_payload.add(spec.family)
                continue
            dict_keys = _dict_literal_keys(payload)
            model = (_resolve_model_class(payload)
                     if isinstance(payload, ast.Call) else None)
            if spec.payload not in ("keys",):
                # model-typed family
                if dict_keys is not None:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{spec.family!r} payload is declared as "
                        f"{spec.payload} but this publish sends a "
                        "json.dumps dict"))
                elif model is not None and model != spec.payload:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{spec.family!r} payload is declared as "
                        f"{spec.payload} but this publish sends "
                        f"{model}"))
                continue
            if dict_keys is None:
                if model is not None:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{spec.family!r} payload is key-declared "
                        f"({', '.join(spec.keys)}) but this publish "
                        f"sends model {model}"))
                else:
                    open_payload.add(spec.family)
                continue
            keys, splat = dict_keys
            if splat:
                open_payload.add(spec.family)
            for k in keys:
                if k not in spec.keys:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"payload key {k!r} published on {spec.family!r} "
                        "is not declared in the channel registry"))
            published_keys.setdefault(spec.family, set()).update(keys)

    for family, sent in sorted(published_keys.items()):
        spec = specs[family]
        if family in open_payload:
            continue  # an unauditable site may send the rest
        for k in spec.keys:
            if k not in sent:
                findings.append(Finding(
                    RULE, BUS_BASE, spec.line,
                    f"channel {family!r} declares payload key {k!r} "
                    "that no publisher ever sends"))

    # -- 2. every declared publisher/subscriber module references the
    #       family's helper (both ways: no ghost channels)
    if from_tree:
        symbols = _collect_symbols(repo)
        for s in specs.values():
            for role, mods in (("publisher", s.publishers),
                               ("subscriber", s.subscribers)):
                if not mods:
                    findings.append(Finding(
                        RULE, BUS_BASE, s.line,
                        f"channel {s.family!r} declares no {role}s — a "
                        "channel nobody speaks on (or listens to) is "
                        "protocol drift"))
                for mod in mods:
                    entry = symbols.get(mod)
                    if entry is None:
                        findings.append(Finding(
                            RULE, BUS_BASE, s.line,
                            f"channel {s.family!r} declares {role} "
                            f"{mod}, which does not exist"))
                    elif s.helper and not any(
                            sym in entry[0] for sym in
                            # a psubscribe side may use the helper's
                            # *_pattern twin (e.g. trace_pattern for
                            # trace_channel) — same family, same module
                            {s.helper,
                             s.helper.replace("_channel", "_pattern")}):
                        findings.append(Finding(
                            RULE, BUS_BASE, s.line,
                            f"channel {s.family!r}: declared {role} "
                            f"{mod} never references {s.helper} — dead "
                            f"{role} declaration or missed migration"))

    # -- 5. README "Bus channels" table <-> registry, both ways
    if from_tree:
        findings.extend(_check_readme(repo, specs))
    return findings


def _who_cell(s: _Spec) -> str:
    """The expected "Publishers → subscribers" README cell: module
    basenames, .py stripped, in declaration order."""
    def short(mods: tuple[str, ...]) -> str:
        return ", ".join(m.rsplit("/", 1)[-1].removesuffix(".py")
                         for m in mods)

    return f"{short(s.publishers)} → {short(s.subscribers)}"


def _check_readme(repo: Repo, specs: dict[str, _Spec]) -> list[Finding]:
    findings: list[Finding] = []
    readme = repo.read_text("README.md")
    if readme is None:
        return [Finding(RULE, "README.md", 0, "README.md missing")]
    in_section = False
    # pattern -> (durable, payload, who, line)
    rows: dict[str, tuple[str, str, str, int]] = {}
    for i, line in enumerate(readme.splitlines(), 1):
        if line.startswith("#"):
            in_section = (line.lstrip("#").strip().lower() == "bus channels")
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 3:
            continue
        m = re.fullmatch(r"`([^`]+)`", cells[0])
        if m is None or m.group(1) in ("Channel",):
            continue
        payload_cell = cells[2].strip("`")
        who_cell = cells[3] if len(cells) > 3 else ""
        rows.setdefault(m.group(1),
                        (cells[1].lower(), payload_cell, who_cell, i))
    if not rows:
        return [Finding(
            RULE, "README.md", 0,
            "README has no \"Bus channels\" table documenting the "
            "channel registry")]
    by_pattern = {s.pattern: s for s in specs.values()}
    for pattern, (durable_cell, payload_cell, who_cell, line) \
            in sorted(rows.items()):
        s = by_pattern.get(pattern)
        if s is None:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README documents channel {pattern!r}, which is not in "
                "the bus/base.py channel registry"))
            continue
        want = "yes" if s.durable else "no"
        if durable_cell != want:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README says channel {pattern!r} durability is "
                f"{durable_cell!r} but the registry says {want!r}"))
        if payload_cell != s.payload:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README says channel {pattern!r} payload is "
                f"{payload_cell!r} but the registry says {s.payload!r}"))
        want_who = _who_cell(s)
        if who_cell and who_cell != want_who:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README says channel {pattern!r} direction is "
                f"{who_cell!r} but the registry says {want_who!r}"))
    for s in specs.values():
        if s.pattern not in rows:
            findings.append(Finding(
                RULE, "README.md", 0,
                f"registered channel {s.pattern!r} missing from the "
                "README Bus channels table"))
    return findings
