"""kernel-parity: every Pallas kernel is registered, referenced, tested,
and documented — both ways (gridcheck v3, ISSUE 14).

``ops/kernels.py``'s ``KERNELS`` tuple is the parity surface: each kernel
declared once with its jnp reference, dispatch-counter label, tolerance,
and owning differential test. Drift this rule catches statically:

1. A ``pl.pallas_call`` site in ``ops/`` inside a function the registry
   doesn't know — a kernel with no declared oracle, no tolerance, and no
   test obligation.
2. A registered kernel whose entry function is missing from
   ``ops/pallas_kernels.py`` (or no longer contains a ``pallas_call``) —
   a stale registry row claiming coverage that no longer exists.
3. Dispatch-label drift: the union of registry labels and
   ``EXTRA_DISPATCH_LABELS`` must equal the set of literal
   ``record_kernel_path(...)`` labels in ``ops/`` exactly, both ways
   (a non-literal label defeats the audit and is flagged too).
4. A registered reference function (``module:fn``) that does not exist
   in the named ``ops/`` module.
5. A registered differential test (``tests/file.py::test_name``) whose
   file or test function does not exist — the kernel's oracle claim is
   untested.
6. The README "Kernels" table and the registry agree both ways,
   including the reference / dispatch-label / tolerance cells (the
   config-discipline treatment, applied to the kernel surface).

Fixture repos without an ``ops/kernels.py`` module skip everything
except the unregistered-``pallas_call`` check against the imported
registry.
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import (
    Finding,
    Repo,
    SourceFile,
    ancestors,
    dotted_name,
    rule,
    str_const,
)

RULE = "kernel-parity"
REGISTRY_MODULE = "gridllm_tpu/ops/kernels.py"
KERNELS_MODULE = "gridllm_tpu/ops/pallas_kernels.py"
OPS_PREFIX = "gridllm_tpu/ops/"
_ROW_NAME = re.compile(r"^`([a-z_]+)`$")
_ROW_TOL = re.compile(r"^`([0-9.e+-]+) / ([0-9.e+-]+)`$")


def _parse_registry(repo: Repo):
    """(kernels, extra_labels, line_of) parsed from the ANALYZED tree's
    ops/kernels.py — ``--root`` on another checkout validates THAT
    checkout's registry. kernels: name -> {field: value}; None when the
    module is absent (fixture repos)."""
    f = repo.file(REGISTRY_MODULE)
    if f is None:
        return None, None, {}
    kernels: dict[str, dict[str, object]] = {}
    lines: dict[str, int] = {}
    extra: set[str] = set()
    for node in f.walk():
        if isinstance(node, ast.Call) \
                and dotted_name(node.func).endswith("KernelSpec"):
            fields: dict[str, object] = {}
            for kw in node.keywords:
                if isinstance(kw.value, ast.Constant):
                    fields[kw.arg] = kw.value.value
                elif isinstance(kw.value, ast.UnaryOp) \
                        and isinstance(kw.value.op, ast.USub) \
                        and isinstance(kw.value.operand, ast.Constant):
                    fields[kw.arg] = -kw.value.operand.value  # type: ignore
            name = fields.get("name")
            if isinstance(name, str):
                kernels[name] = fields
                lines[name] = node.lineno
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target] if isinstance(node, ast.AnnAssign)
                   else [])
        if any(isinstance(t, ast.Name) and t.id == "EXTRA_DISPATCH_LABELS"
               for t in targets) and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                val = str_const(k)
                if val is not None:
                    extra.add(val)
    return kernels, extra, lines


def _enclosing_toplevel_fn(node: ast.AST) -> ast.AST | None:
    """The outermost (module-level) function containing `node`."""
    fn = None
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = anc
    return fn


def _pallas_call_sites(f: SourceFile) -> list[tuple[str | None, int]]:
    """(enclosing module-level function name, line) for every
    ``pl.pallas_call(...)`` call in the file."""
    out: list[tuple[str | None, int]] = []
    for node in f.walk():
        if isinstance(node, ast.Call) \
                and dotted_name(node.func).endswith("pallas_call"):
            fn = _enclosing_toplevel_fn(node)
            out.append((fn.name if fn is not None else None, node.lineno))
    return out


@rule(RULE, "every pl.pallas_call belongs to a KERNELS-registry entry; "
            "registry <-> dispatch labels <-> README Kernels table agree "
            "both ways; each kernel's reference fn and differential test "
            "exist")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    kernels, extra_labels, reg_lines = _parse_registry(repo)
    if kernels is None:
        # fixture fallback: check pallas_call sites against the imported
        # registry (the fixture's source of truth)
        from gridllm_tpu.ops.kernels import kernel_names

        known = set(kernel_names())
        for f in repo.files:
            if not f.rel.startswith(OPS_PREFIX):
                continue
            for fn_name, line in _pallas_call_sites(f):
                if fn_name not in known:
                    findings.append(Finding(
                        RULE, f.rel, line,
                        f"pl.pallas_call inside {fn_name or '<module>'}() "
                        "which is not a registered kernel (ops/kernels.py "
                        "KERNELS)"))
        return findings

    # 1. every pallas_call site belongs to a registered kernel entry fn
    kernel_fns_with_call: set[str] = set()
    for f in repo.files:
        if not f.rel.startswith(OPS_PREFIX) or f.rel == REGISTRY_MODULE:
            continue
        for fn_name, line in _pallas_call_sites(f):
            if fn_name in kernels and f.rel == KERNELS_MODULE:
                kernel_fns_with_call.add(fn_name)
                continue
            findings.append(Finding(
                RULE, f.rel, line,
                f"pl.pallas_call inside {fn_name or '<module>'}() which "
                "is not a registered kernel — declare it in "
                "ops/kernels.py KERNELS (reference, dispatch label, "
                "tolerance, owning test)"))

    # 2. registered kernels actually exist and still launch Pallas
    kfile = repo.file(KERNELS_MODULE)
    toplevel_fns = set()
    if kfile is not None and kfile.tree is not None:
        toplevel_fns = {n.name for n in kfile.tree.body
                        if isinstance(n, ast.FunctionDef)}
    for name, line in sorted(reg_lines.items()):
        if name not in toplevel_fns:
            findings.append(Finding(
                RULE, REGISTRY_MODULE, line,
                f"registered kernel {name!r} has no function in "
                f"{KERNELS_MODULE}"))
        elif name not in kernel_fns_with_call:
            findings.append(Finding(
                RULE, REGISTRY_MODULE, line,
                f"registered kernel {name!r} contains no pl.pallas_call "
                "— stale registry row (or the kernel silently became a "
                "jnp function)"))

    # 3. dispatch labels: registry union EXTRA == record_kernel_path
    # literals in ops/, both ways
    declared = {str(k["dispatch"]) for k in kernels.values()
                if "dispatch" in k} | set(extra_labels or ())
    recorded: dict[str, tuple[str, int]] = {}
    for f in repo.files:
        if not f.rel.startswith(OPS_PREFIX):
            continue
        for node in f.walk():
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func).endswith("record_kernel_path") \
                    and node.args:
                lab = str_const(node.args[0])
                if lab is None:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        "record_kernel_path() needs a literal op label "
                        "for static parity auditing"))
                    continue
                recorded.setdefault(lab, (f.rel, node.lineno))
                if lab not in declared:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"dispatch label {lab!r} is not declared in "
                        "ops/kernels.py (KERNELS dispatch or "
                        "EXTRA_DISPATCH_LABELS)"))
    for lab in sorted(declared - set(recorded)):
        findings.append(Finding(
            RULE, REGISTRY_MODULE, 0,
            f"declared dispatch label {lab!r} is never recorded by "
            "record_kernel_path() in ops/ — dead registry entry, the "
            "dashboard cell it promises stays empty"))

    # 4 + 5. reference functions and differential tests exist
    fn_defs: dict[str, set[str]] = {}
    for f in repo.files:
        if f.tree is None:
            continue
        fn_defs[f.rel] = {
            n.name for n in ast.walk(f.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for name, fields in sorted(kernels.items()):
        line = reg_lines.get(name, 0)
        ref = fields.get("reference")
        if isinstance(ref, str) and ":" in ref:
            mod, _, fn = ref.partition(":")
            rel = f"{OPS_PREFIX}{mod}.py"
            if fn not in fn_defs.get(rel, set()):
                findings.append(Finding(
                    RULE, REGISTRY_MODULE, line,
                    f"kernel {name!r}: reference {ref!r} does not resolve "
                    f"to a function in {rel}"))
        else:
            findings.append(Finding(
                RULE, REGISTRY_MODULE, line,
                f"kernel {name!r}: reference must be a literal "
                "'module:function' under ops/"))
        test = fields.get("test")
        if isinstance(test, str) and "::" in test:
            trel, _, tfn = test.partition("::")
            if trel not in fn_defs:
                findings.append(Finding(
                    RULE, REGISTRY_MODULE, line,
                    f"kernel {name!r}: test file {trel!r} does not exist"))
            elif tfn not in fn_defs[trel]:
                findings.append(Finding(
                    RULE, REGISTRY_MODULE, line,
                    f"kernel {name!r}: differential test {tfn!r} not "
                    f"found in {trel} — the oracle claim is untested"))
        else:
            findings.append(Finding(
                RULE, REGISTRY_MODULE, line,
                f"kernel {name!r}: test must be a literal "
                "'tests/file.py::test_name'"))

    findings.extend(_check_readme(repo, kernels))
    return findings


def _check_readme(repo: Repo, kernels: dict) -> list[Finding]:
    findings: list[Finding] = []
    readme = repo.read_text("README.md")
    if readme is None:
        return [Finding(RULE, "README.md", 0, "README.md missing")]
    documented: dict[str, tuple[list[str], int]] = {}
    in_section = False
    for i, line in enumerate(readme.splitlines(), 1):
        if line.startswith("#"):
            in_section = line.lstrip("#").strip().lower() == "kernels"
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells:
            continue
        m = _ROW_NAME.fullmatch(cells[0])
        if m is not None:
            documented.setdefault(m.group(1), (cells, i))
    if not documented:
        return [Finding(
            RULE, "README.md", 0,
            "README has no Kernels table (| `kernel` | `reference` | "
            "`dispatch label` | `rtol / atol` | `test` |) documenting "
            "the KERNELS registry")]
    for name, (cells, i) in sorted(documented.items()):
        if name not in kernels:
            findings.append(Finding(
                RULE, "README.md", i,
                f"README documents kernel {name!r}, which is not "
                "registered in ops/kernels.py KERNELS"))
            continue
        fields = kernels[name]
        want = {
            1: str(fields.get("reference", "")).partition(":")[2],
            2: str(fields.get("dispatch", "")),
            4: str(fields.get("test", "")),
        }
        for idx, expect in want.items():
            got = cells[idx].strip("`") if len(cells) > idx else ""
            if got != expect:
                findings.append(Finding(
                    RULE, "README.md", i,
                    f"Kernels table row {name!r}: column {idx + 1} says "
                    f"{got!r} but the registry says {expect!r}"))
        if len(cells) > 3:
            m = _ROW_TOL.fullmatch(cells[3])
            reg_tol = (fields.get("rtol"), fields.get("atol"))
            if m is None or (float(m.group(1)), float(m.group(2))) != (
                    float(reg_tol[0] or 0), float(reg_tol[1] or 0)):
                findings.append(Finding(
                    RULE, "README.md", i,
                    f"Kernels table row {name!r}: tolerance cell "
                    f"{cells[3]!r} does not match the registry "
                    f"(`{reg_tol[0]} / {reg_tol[1]}`)"))
    for name in sorted(kernels):
        if name not in documented:
            findings.append(Finding(
                RULE, "README.md", 0,
                f"registered kernel {name!r} missing from the README "
                "Kernels table"))
    return findings
