"""host-sync-discipline: the engine's step/dispatch/ingest loops never
block on the device except at declared points (gridcheck v3, ISSUE 14).

The pipelined runner's whole design is that dispatch returns before the
device finishes and the ONE place a block is fetched is
``_fetch_oldest`` (plus ``_step_spec``'s serial verify fetch). A stray
``.item()`` / ``jax.device_get`` / ``np.asarray`` / ``block_until_ready``
anywhere else in those loops silently stalls the host against the
device every step — the step-time histograms from PR 4 can SEE the
stall (host_sched time balloons) but nothing prevented it. This rule
does, lexically:

Inside the engine's loop functions (``step``, ``_run``, ``_pump_once``,
``_step_spec``, ``_fetch_oldest``, ``_drain_ctl``, ``_try_admit``, and
every ``_dispatch_*`` / ``_ingest*``), the following are findings unless
the line carries a ``# sync-ok`` waiver (the declared sync points):

- ``.item()`` — one device round trip per call;
- ``jax.device_get(...)`` / ``jax.block_until_ready(...)`` /
  ``<x>.block_until_ready()`` — explicit sync;
- ``np.asarray(...)`` / ``np.array(...)`` — implicit transfer+sync when
  the argument is a device array (and in these loops it usually is);
- ``int(...)`` / ``float(...)`` applied to an expression that reads the
  engine's device-state attributes (``self.tokens`` / ``self.cache`` /
  ``self.active`` / ``self.counts`` / ``self.window`` / ``self.wlen`` /
  ``self.sampling``) — a python scalar conversion IS a sync.

A ``# sync-ok`` on a line the rule would not flag is itself a finding
(stale waivers rot into blanket permissions).
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import Finding, Repo, dotted_name, rule

RULE = "host-sync-discipline"
ENGINE = "gridllm_tpu/engine/engine.py"
_WAIVER = "# sync-ok"
_LOOP_FN = re.compile(
    r"^(step|_run|_pump_once|_step_spec|_fetch_oldest|_drain_ctl|"
    r"_try_admit|_dispatch_\w+|_ingest\w*)$")
_DEVICE_ATTRS = {"tokens", "cache", "active", "counts", "window", "wlen",
                 "sampling"}


def _reads_device_state(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _DEVICE_ATTRS \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "self":
            return True
    return False


def _flag_line(node: ast.Call) -> str | None:
    """The violation message for one call node, or None."""
    fn = dotted_name(node.func)
    leaf = fn.rsplit(".", 1)[-1]
    if leaf == "item" and not node.args and isinstance(node.func,
                                                      ast.Attribute):
        return ".item() — one device round trip per call"
    if fn.endswith("device_get"):
        return "jax.device_get — explicit device sync"
    if leaf == "block_until_ready":
        return "block_until_ready — explicit device sync"
    if fn in ("np.asarray", "np.array", "numpy.asarray", "numpy.array"):
        return f"{fn}() — implicit transfer+sync on device arrays"
    if isinstance(node.func, ast.Name) and node.func.id in ("int", "float") \
            and node.args and _reads_device_state(node.args[0]):
        return (f"{node.func.id}() on engine device state — a python "
                "scalar conversion is a sync")
    return None


@rule(RULE, "no .item()/device_get/np.asarray/block_until_ready or "
            "scalar conversion of device state inside the engine "
            "step/dispatch/ingest loops, except at # sync-ok points")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    f = repo.file(ENGINE)
    if f is None or f.tree is None:
        return findings
    lines = f.lines
    waiver_lines = {i for i, line in enumerate(lines, 1) if _WAIVER in line}
    used_waivers: set[int] = set()
    in_scope_lines: set[int] = set()

    for node in ast.walk(f.tree):
        if not (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and _LOOP_FN.match(node.name)):
            continue
        for sub in ast.walk(node):
            if hasattr(sub, "lineno"):
                in_scope_lines.add(sub.lineno)
            if not isinstance(sub, ast.Call):
                continue
            msg = _flag_line(sub)
            if msg is None:
                continue
            if sub.lineno in waiver_lines:
                used_waivers.add(sub.lineno)
                continue
            findings.append(Finding(
                RULE, f.rel, sub.lineno,
                f"host sync inside {node.name}(): {msg}; fetch through "
                "_fetch_oldest, or declare a deliberate sync point with "
                "# sync-ok"))

    for lineno in sorted(waiver_lines & in_scope_lines - used_waivers):
        findings.append(Finding(
            RULE, f.rel, lineno,
            "# sync-ok waiver on a line the rule does not flag — stale "
            "waiver, remove it"))
    return findings
