"""lock-discipline: the engine's documented lock protocol, checked.

Two invariants from ``engine/engine.py`` (both previously enforced only
by a comment at the ``_alloc_lock`` declaration):

1. **Guarded mutation** — every mutating ``PageAllocator`` call
   (``something.alloc.<mutator>(...)``) happens lexically inside a
   ``with`` block that acquires ``_alloc_lock``. Page allocation runs on
   the driving thread while KV export/import mutates the same free lists
   from executor threads; one unguarded call is a refcount corruption.
2. **Lock order** — where both are held, ``_alloc_lock`` comes BEFORE
   ``dispatch_lock``: never acquire ``_alloc_lock`` inside a block that
   already holds ``dispatch_lock`` (including item order within a single
   ``with a, b:``). The inversion is the classic two-thread deadlock.

The runtime sanitizer (``analysis/lockcheck.py``) proves the same
properties dynamically under the chaos/disagg suites; this rule catches
them at review time, on paths the suites never execute.
"""

from __future__ import annotations

import ast

from gridllm_tpu.analysis.core import Finding, Repo, ancestors, dotted_name, rule

RULE = "lock-discipline"

# PageAllocator methods that mutate free lists / refcounts / the reuse LRU
MUTATORS = {"alloc", "free", "match_prefix", "pin_prefix", "unpin_pages",
            "claim_page", "register_claimed", "evict_cached"}
ALLOC_LOCK = "_alloc_lock"
DISPATCH_LOCK = "dispatch_lock"


def _lock_items(node: ast.With) -> list[str]:
    """Which of the two protocol locks a with-statement acquires, in
    item order (by dotted-name suffix, so self._alloc_lock and
    eng.dispatch_lock both resolve)."""
    out = []
    for item in node.items:
        name = dotted_name(item.context_expr)
        if name.endswith(ALLOC_LOCK):
            out.append(ALLOC_LOCK)
        elif name.endswith(DISPATCH_LOCK):
            out.append(DISPATCH_LOCK)
    return out


def _holds(node: ast.AST, lock: str) -> bool:
    """Is ``node`` lexically inside a with-block acquiring ``lock``?"""
    for anc in ancestors(node):
        if isinstance(anc, ast.With) and lock in _lock_items(anc):
            return True
    return False


@rule(RULE, "PageAllocator mutation only under _alloc_lock; "
            "never _alloc_lock inside dispatch_lock (order inversion)")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for f in repo.package_files():
        for node in f.walk():
            # 1. guarded mutation: <recv>.alloc.<mutator>(...)
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS \
                    and isinstance(node.func.value, ast.Attribute) \
                    and node.func.value.attr == "alloc":
                if not _holds(node, ALLOC_LOCK):
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{dotted_name(node.func)}() mutates PageAllocator "
                        f"state outside a `with ... {ALLOC_LOCK}` block"))
            # 2. order: _alloc_lock acquired while dispatch_lock held
            if isinstance(node, ast.With):
                items = _lock_items(node)
                if ALLOC_LOCK in items and DISPATCH_LOCK in items \
                        and items.index(DISPATCH_LOCK) < items.index(ALLOC_LOCK):
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        "lock-order inversion in one statement: "
                        f"{DISPATCH_LOCK} listed before {ALLOC_LOCK} "
                        f"(documented order is {ALLOC_LOCK} first)"))
                elif ALLOC_LOCK in items and DISPATCH_LOCK not in items:
                    for anc in ancestors(node):
                        if isinstance(anc, ast.With) \
                                and DISPATCH_LOCK in _lock_items(anc) \
                                and ALLOC_LOCK not in _lock_items(anc):
                            findings.append(Finding(
                                RULE, f.rel, node.lineno,
                                f"lock-order inversion: {ALLOC_LOCK} "
                                f"acquired inside a {DISPATCH_LOCK} block "
                                f"(documented order is {ALLOC_LOCK} first, "
                                f"engine/engine.py)"))
                            break
    return findings
