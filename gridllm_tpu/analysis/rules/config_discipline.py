"""config-discipline: every ``GRIDLLM_*`` env read goes through the
central registry in ``utils/config.py``.

Three invariants:

1. No direct ``os.environ`` / ``os.getenv`` read of a ``GRIDLLM_*`` name
   anywhere outside ``utils/config.py`` (tests excepted — they own their
   environment). Reads must use the typed accessors
   (``env_str``/``env_int``/``env_float``/``env_bool``/``env_raw``).
2. Every ``GRIDLLM_*`` token that appears in package source (accessor
   calls, docstrings, error messages alike) names a REGISTERED variable —
   stale knob names in docs are drift too.
3. The README "Configuration" table and the registry agree both ways:
   every registered variable is documented, every documented variable is
   registered.
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import Finding, Repo, dotted_name, rule, str_const

RULE = "config-discipline"
CONFIG_MODULE = "gridllm_tpu/utils/config.py"
ACCESSORS = {"env_str", "env_int", "env_float", "env_bool", "env_raw",
             "env_int_lenient", "env_float_lenient"}
_ENV_TOKEN = re.compile(r"\bGRIDLLM_[A-Z][A-Z0-9_]+\b")


def _registered_vars(repo: Repo) -> dict[str, str]:
    """name -> default, parsed from the ANALYZED tree's utils/config.py —
    ``--root`` on another checkout must validate against THAT checkout's
    registry, not whatever version this process imported. register_env
    calls are literal by construction (the rule itself enforces literal
    names). Fixture repos without a config module fall back to the
    imported registry, which for them is the source of truth."""
    for f in repo.files:
        if f.rel == CONFIG_MODULE:
            out: dict[str, str] = {}
            for node in f.walk():
                if isinstance(node, ast.Call) \
                        and dotted_name(node.func).endswith("register_env") \
                        and node.args:
                    name = str_const(node.args[0])
                    if name:
                        default = (str_const(node.args[1])
                                   if len(node.args) > 1 else None)
                        out[name] = default if default is not None else ""
            if out:
                return out
    from gridllm_tpu.utils.config import ENV_VARS

    return {v.name: v.default for v in ENV_VARS.values()}


def _is_environ_read(node: ast.AST) -> str | None:
    """Return the env-var name when ``node`` reads the process environment
    directly: os.environ.get/[...]/setdefault/pop or os.getenv."""
    if isinstance(node, ast.Call):
        fn = dotted_name(node.func)
        # setdefault/pop are WRITES (launchers establishing defaults,
        # tests cleaning up) — only true reads are in scope
        if fn.endswith("environ.get") or fn.endswith("getenv"):
            return str_const(node.args[0]) if node.args else "?"
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if dotted_name(node.value).endswith("environ"):
            return str_const(node.slice) or "?"
    return None


@rule(RULE, "GRIDLLM_* env reads must go through utils/config.py's "
            "registry; registry and README table must agree")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    registered = _registered_vars(repo)

    for f in repo.files:
        is_config = f.rel == CONFIG_MODULE
        is_test = f.rel.startswith("tests/")
        for node in f.walk():
            # 1. direct environment reads of GRIDLLM_* outside config.py
            # (tests own their environment — read ban does not apply)
            name = _is_environ_read(node)
            if name is not None and not is_config and not is_test \
                    and name.startswith("GRIDLLM_"):
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"direct os.environ read of {name}: route it through "
                    "the env registry (utils/config.py env_str/env_int/"
                    "env_float/env_bool/env_raw)"))
            # 2a. accessor calls must name registered vars
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                    and node.func.id in ACCESSORS and node.args:
                var = str_const(node.args[0])
                if var is None:
                    # inside config.py the accessors delegate to each other
                    # with a pass-through name (env_int_lenient -> env_int);
                    # that is the implementation, not a call site
                    if not is_config:
                        findings.append(Finding(
                            RULE, f.rel, node.lineno,
                            f"{node.func.id}() needs a literal env-var name "
                            "for static checking"))
                elif var not in registered:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"{node.func.id}({var!r}): not in ENV_VARS — "
                        "register_env it in utils/config.py"))
        # 2b. any GRIDLLM_* token in source text (docstrings, launcher
        # env dicts, error messages) must name a registered var — a knob
        # nothing reads, or a stale name in docs, is drift. Tests are
        # exempt: analyzer fixtures seed intentionally unregistered names
        if is_test:
            continue
        for i, line in enumerate(f.text.splitlines(), 1):
            for tok in _ENV_TOKEN.findall(line):
                if tok not in registered:
                    findings.append(Finding(
                        RULE, f.rel, i,
                        f"{tok} is not a registered env var (ENV_VARS); "
                        "register it or fix the reference"))

    # 3. README table <-> registry, both directions. "Documented" means a
    # row of the "## Configuration" section's table specifically — a knob
    # name quoted in some OTHER table (the metrics table explains
    # gridllm_recompile_storms_total in terms of GRIDLLM_RECOMPILE_BUDGET)
    # must not satisfy the check, or deleting the real row stays green.
    readme = repo.read_text("README.md")
    if readme is None:
        findings.append(Finding(RULE, "README.md", 0, "README.md missing"))
        return findings
    documented: dict[str, int] = {}
    doc_defaults: dict[str, tuple[str, int]] = {}
    in_config_section = False
    for i, line in enumerate(readme.splitlines(), 1):
        if line.startswith("#"):
            in_config_section = (
                line.lstrip("#").strip().lower() == "configuration")
            continue
        if not line.lstrip().startswith("|"):
            continue
        for tok in _ENV_TOKEN.findall(line):
            if in_config_section:
                documented.setdefault(tok, i)
                # the Default column is part of the contract too — a row
                # is | `VAR` | `default`-or-*(empty)* | description |
                cells = [c.strip() for c in line.strip().strip("|").split("|")]
                if len(cells) >= 2 and tok in cells[0]:
                    default = _parse_default_cell(cells[1])
                    if default is not None:
                        doc_defaults.setdefault(tok, (default, i))
            elif tok not in registered:
                # stale knob name in some other README table is drift too
                findings.append(Finding(
                    RULE, "README.md", i,
                    f"README references {tok}, which is not registered "
                    "in ENV_VARS"))
    if not documented:
        findings.append(Finding(
            RULE, "README.md", 0,
            "README has no Configuration-section table documenting "
            "GRIDLLM_* variables"))
    for var in registered:
        if var not in documented:
            findings.append(Finding(
                RULE, "README.md", 0,
                f"registered env var {var} missing from the README "
                "Configuration table"))
    for var, line in sorted(documented.items()):
        if var not in registered:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README documents {var}, which is not registered in "
                "ENV_VARS"))
    for var, (default, line) in sorted(doc_defaults.items()):
        reg_default = registered.get(var)
        if reg_default is not None and default != reg_default:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README documents default {default!r} for {var} but the "
                f"registry default is {reg_default!r}"))
    return findings


def _parse_default_cell(cell: str) -> str | None:
    """The Default-column cell as a registry default string: ``*(empty)*``
    means \"\", a backticked value means its contents. Anything else is
    prose we can't compare — return None and skip (the name/description
    checks still apply)."""
    if cell == "*(empty)*":
        return ""
    m = re.fullmatch(r"`([^`]*)`", cell)
    return m.group(1) if m else None
