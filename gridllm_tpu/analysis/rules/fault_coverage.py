"""fault-coverage: the fault-injection registry and its call sites agree
both ways (ISSUE 13).

``faults.py``'s ``SITES`` tuple is the chaos surface the fault-tolerance
machinery claims to cover. A site registered there but wired nowhere
means a chaos spec can name it, parse cleanly, and silently inject
NOTHING — the test goes green having tested nothing. A call site using
an unregistered name raises at runtime only when that path executes.
Both are drift this rule catches statically:

1. Every site in ``SITES`` has at least one live ``faults.inject(...)``
   / ``faults.check(...)`` call site in package code.
2. Every literal site name at an inject/check call site is registered
   (and is a literal — a computed site name defeats static audit).
3. Each critical subsystem carries at least one live site: ``bus/``,
   ``transfer/``, and ``worker/`` by directory, the KV host tier by its
   ``kvtier.*`` site names (its injection points guard engine-side tier
   operations). Chaos specs for those subsystems can therefore never
   inject nothing.
4. The README fault-site table and ``SITES`` agree both ways (the
   config-discipline treatment, applied to the chaos surface).

Fixture repos without a ``gridllm_tpu/faults.py`` skip everything except
the literal-site check against the imported registry.
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import Finding, Repo, dotted_name, rule, str_const

RULE = "fault-coverage"
FAULTS_MODULE = "gridllm_tpu/faults.py"
_SITE_ROW = re.compile(r"^`([a-z_]+\.[a-z_]+)`$")

# critical subsystems: directory prefixes that must carry ≥ 1 live site,
# plus site-name prefixes whose wiring may live outside their home dir
CRITICAL_DIRS = {
    "bus": "gridllm_tpu/bus/",
    "transfer": "gridllm_tpu/transfer/",
    "worker": "gridllm_tpu/worker/",
}
CRITICAL_SITE_PREFIXES = {
    "kvtier": "kvtier.",
}


def _parse_sites(repo: Repo) -> dict[str, int] | None:
    """site -> lineno from the analyzed tree's faults.py SITES tuple;
    None when the module is absent (fixture repos)."""
    f = repo.file(FAULTS_MODULE)
    if f is None:
        return None
    for node in f.walk():
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "SITES"
                for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            out: dict[str, int] = {}
            for elt in node.value.elts:
                val = str_const(elt)
                if val is not None:
                    out[val] = elt.lineno
            return out
    return {}


def _call_sites(repo: Repo) -> list[tuple[str, int, str | None]]:
    """(file, line, literal-site-or-None) for every faults.inject/check
    call outside faults.py itself and tests."""
    out: list[tuple[str, int, str | None]] = []
    for f in repo.package_files():
        if f.rel == FAULTS_MODULE:
            continue
        imported_bare: set[str] = set()
        for node in f.walk():
            if isinstance(node, ast.ImportFrom) \
                    and (node.module or "").endswith("faults"):
                imported_bare.update(
                    a.asname or a.name for a in node.names
                    if a.name in ("inject", "check"))
        for node in f.walk():
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            is_site_call = (
                fn in ("faults.inject", "faults.check")
                or fn.endswith(".faults.inject")
                or fn.endswith(".faults.check")
                or (isinstance(node.func, ast.Name)
                    and node.func.id in imported_bare))
            if not is_site_call:
                continue
            out.append((f.rel, node.lineno,
                        str_const(node.args[0]) if node.args else None))
    return out


@rule(RULE, "every registered fault site is wired to a live inject/check "
            "call site and vice versa; bus/transfer/worker/kvtier each "
            "carry at least one; README fault table matches SITES")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    sites = _parse_sites(repo)
    calls = _call_sites(repo)
    if sites is None:
        # fixture fallback: literal check against the imported registry
        from gridllm_tpu.faults import SITES

        for rel, line, lit in calls:
            if lit is not None and lit not in SITES:
                findings.append(Finding(
                    RULE, rel, line,
                    f"fault site {lit!r} is not registered in "
                    "faults.py SITES"))
        return findings

    live: dict[str, list[tuple[str, int]]] = {}
    for rel, line, lit in calls:
        if lit is None:
            findings.append(Finding(
                RULE, rel, line,
                "faults.inject/check needs a literal site name for "
                "static coverage auditing"))
            continue
        if lit not in sites:
            findings.append(Finding(
                RULE, rel, line,
                f"fault site {lit!r} is not registered in faults.py "
                "SITES — a typo here would fail loudly only when this "
                "path runs"))
            continue
        live.setdefault(lit, []).append((rel, line))

    for site, line in sorted(sites.items()):
        if site not in live:
            findings.append(Finding(
                RULE, FAULTS_MODULE, line,
                f"fault site {site!r} is registered but has no live "
                "inject()/check() call site — a chaos spec naming it "
                "injects nothing"))

    for name, prefix in sorted(CRITICAL_DIRS.items()):
        if not any(f.rel.startswith(prefix) for f in repo.files):
            continue  # subsystem absent (fixture repo)
        if not any(rel.startswith(prefix)
                   for uses in live.values() for rel, _ in uses):
            findings.append(Finding(
                RULE, FAULTS_MODULE, 0,
                f"critical subsystem {name!r} ({prefix}) carries no live "
                "fault site — its failure paths are untestable by "
                "GRIDLLM_FAULT_SPEC"))
    for name, site_prefix in sorted(CRITICAL_SITE_PREFIXES.items()):
        named = [s for s in sites if s.startswith(site_prefix)]
        if named and not any(s in live for s in named):
            findings.append(Finding(
                RULE, FAULTS_MODULE, 0,
                f"critical subsystem {name!r} registers sites "
                f"({', '.join(named)}) but none is wired to a live call "
                "site"))

    findings.extend(_check_readme(repo, sites))
    return findings


def _check_readme(repo: Repo, sites: dict[str, int]) -> list[Finding]:
    findings: list[Finding] = []
    readme = repo.read_text("README.md")
    if readme is None:
        return [Finding(RULE, "README.md", 0, "README.md missing")]
    documented: dict[str, int] = {}
    in_fault_section = False
    for i, line in enumerate(readme.splitlines(), 1):
        if line.startswith("#"):
            # anchor on the fault section the way channel-discipline
            # anchors on "Bus channels": a backticked dotted name in some
            # unrelated table must not read as a documented fault site
            in_fault_section = "fault" in line.lstrip("#").strip().lower()
            continue
        if not in_fault_section or not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells:
            continue
        m = _SITE_ROW.fullmatch(cells[0])
        if m is not None:
            documented.setdefault(m.group(1), i)
    if not documented:
        return [Finding(
            RULE, "README.md", 0,
            "README has no fault-site table (| `site.name` | effect |) "
            "documenting faults.py SITES")]
    for site, line in sorted(documented.items()):
        if site not in sites:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README documents fault site {site!r}, which is not "
                "registered in faults.py SITES"))
    for site in sorted(sites):
        if site not in documented:
            findings.append(Finding(
                RULE, "README.md", 0,
                f"registered fault site {site!r} missing from the README "
                "fault-site table"))
    return findings
