"""dtype-discipline: the numerics dtype policy of ``ops/`` is lexically
auditable (gridcheck v3, ISSUE 14).

The kernels and their jnp oracles keep accumulation in float32 no matter
what dtype the serving path feeds them (bf16 weights, int8-dequant KV).
That policy only survives review if it is VISIBLE at every site, so this
rule bans the constructs that hide it:

1. **Accumulation dtype** — every ``dot_general`` call in ``ops/`` must
   pin ``preferred_element_type`` (the MXU accumulates in the output
   type; leaving it implicit means bf16 inputs silently accumulate in
   bf16), and every ``jnp.einsum`` must pin ``precision`` (the reference
   paths' equivalent knob).
2. **f32 softmax** — any function calling ``jnp.exp`` / ``jax.nn
   .softmax`` / ``logsumexp`` must establish float32 somewhere in its
   body (an ``astype(jnp.float32)`` cast, an ``jnp.float32`` dtype
   argument, or f32 carry inits): exp/softmax in bf16 loses real
   accuracy at long context.
3. **No dtype-less array construction** — ``jnp.array``/``jnp.asarray``
   in ``ops/`` must pass an explicit dtype; the default-inference path
   is exactly where a python float silently becomes f64-weak/f32 and a
   python int an i32 that later upcasts a whole expression.
4. **Named mask sentinels** — float literals of magnitude >= 1e6 (the
   ``-1e30`` masking class) must be module-level named constants, not
   inline: the value is a dtype commitment (it overflows f16, saturates
   bf16) and must be auditable at one site per module.
5. **QuantPages pairing** — a function that unwraps ``QuantPages`` (an
   ``isinstance(..., QuantPages)`` check) and consumes ``.data`` must
   also consume ``.scale``: int8 page values without their dequant
   scales are garbage that still parses, runs, and decodes.

Scope: ``gridllm_tpu/ops/`` (check 5 also covers ``engine/engine.py``,
which handles QuantPages on the spill/export paths). Waive a deliberate
exception with ``# dtype-ok`` on the offending line.
"""

from __future__ import annotations

import ast

from gridllm_tpu.analysis.core import Finding, Repo, dotted_name, rule

RULE = "dtype-discipline"
OPS_PREFIX = "gridllm_tpu/ops/"
ENGINE = "gridllm_tpu/engine/engine.py"
_WAIVER = "# dtype-ok"
_SENTINEL_MIN = 1e6
_EXPISH = {"exp", "softmax", "logsumexp"}


def _waived(f, lineno: int) -> bool:
    lines = f.lines
    return 0 < lineno <= len(lines) and _WAIVER in lines[lineno - 1]


def _has_f32_anchor(fn: ast.AST) -> bool:
    """True when the function body visibly establishes float32: a
    ``float32`` attribute/name anywhere (astype(jnp.float32), dtype
    args, f32 carry inits)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr == "float32":
            return True
        if isinstance(node, ast.Name) and node.id == "float32":
            return True
    return False


def _toplevel_functions(tree: ast.Module):
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield sub


def _check_quant_pairing(f, findings: list[Finding]) -> None:
    if f.tree is None:
        return
    for fn in _toplevel_functions(f.tree):
        quant_names: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and dotted_name(node.func).endswith("isinstance") \
                    and len(node.args) == 2 \
                    and dotted_name(node.args[1]).endswith("QuantPages") \
                    and isinstance(node.args[0], ast.Name):
                quant_names.add(node.args[0].id)
        if not quant_names:
            continue
        reads: dict[str, set[str]] = {}
        first_data_line: dict[str, int] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in quant_names \
                    and node.attr in ("data", "scale"):
                reads.setdefault(node.value.id, set()).add(node.attr)
                if node.attr == "data":
                    first_data_line.setdefault(node.value.id, node.lineno)
        for name, attrs in sorted(reads.items()):
            if "data" in attrs and "scale" not in attrs \
                    and not _waived(f, first_data_line[name]):
                findings.append(Finding(
                    RULE, f.rel, first_data_line[name],
                    f"{fn.name}() consumes QuantPages {name}.data without "
                    f"its .scale sibling — int8 values without dequant "
                    "scales are silent garbage"))


@rule(RULE, "ops/ numerics policy is visible: dot_general pins "
            "preferred_element_type, einsum pins precision, softmax/exp "
            "functions anchor f32, array constructions carry a dtype, "
            "mask sentinels are named constants, QuantPages .data never "
            "travels without .scale")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for f in repo.package_files():
        in_ops = f.rel.startswith(OPS_PREFIX)
        if not in_ops and f.rel != ENGINE:
            continue
        _check_quant_pairing(f, findings)
        if not in_ops or f.tree is None:
            continue

        # module-level named sentinel assignments (annotated or not) are
        # the allowed homes
        sentinel_lines: set[int] = set()
        for node in f.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                for sub in ast.walk(node):
                    if hasattr(sub, "lineno"):
                        sentinel_lines.add(sub.lineno)

        for node in f.walk():
            if isinstance(node, ast.Call):
                fn_name = dotted_name(node.func)
                kws = {kw.arg for kw in node.keywords}
                if fn_name.endswith("dot_general") \
                        and "preferred_element_type" not in kws \
                        and not _waived(f, node.lineno):
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        "dot_general without preferred_element_type — "
                        "bf16 inputs would accumulate in bf16; pin "
                        "preferred_element_type=jnp.float32"))
                if fn_name.endswith("einsum") and fn_name.startswith("jnp") \
                        and "precision" not in kws \
                        and not _waived(f, node.lineno):
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        "jnp.einsum without precision — reference paths "
                        "pin precision (jax.lax.Precision.HIGHEST) so the "
                        "oracle's accumulation is not backend-dependent"))
                if fn_name in ("jnp.array", "jnp.asarray") \
                        and len(node.args) < 2 and "dtype" not in kws \
                        and not _waived(f, node.lineno):
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        f"dtype-less {fn_name}() — the inferred dtype is "
                        "a silent policy decision; pass one explicitly"))
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, float) \
                    and abs(node.value) >= _SENTINEL_MIN \
                    and node.lineno not in sentinel_lines \
                    and not _waived(f, node.lineno):
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"inline mask sentinel {node.value!r} — name it as a "
                    "module-level constant (it is a dtype commitment: "
                    "overflows f16, saturates bf16)"))

        for fn in _toplevel_functions(f.tree):
            exp_line = None
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = dotted_name(node.func)
                    leaf = name.rsplit(".", 1)[-1]
                    if leaf in _EXPISH and not _waived(f, node.lineno):
                        exp_line = exp_line or node.lineno
            if exp_line is not None and not _has_f32_anchor(fn):
                findings.append(Finding(
                    RULE, f.rel, exp_line,
                    f"{fn.name}() computes exp/softmax without a visible "
                    "float32 anchor — cast inputs (or init carries) in "
                    "f32, or waive a contract-guaranteed-f32 path with "
                    "# dtype-ok"))
    return findings
