"""span-pairing: a tracer span opened with ``begin()`` must be closed on
all paths.

``Tracer.begin`` hands back an open span; a span that never reaches
``end()`` pins its request's trace in the active table until the LRU
seal and reports a phase that never finished — the watchdog then reads
it as a hang. The safe shapes, which this rule enforces for every
``<...tracer...>.begin(...)`` call site outside tests:

- the span is closed by an ``end(span)`` call inside a ``finally`` block
  whose ``try`` covers the ``begin()`` — begin inside the try body, or as
  the statement immediately before the try (a statement in between can
  raise with the span already open), or
- ownership is handed off: the span is stored into an attribute or
  mapping (``self._queue_spans[id] = tracer.begin(...)``) or returned,
  where the holder's lifecycle closes it, or
- the context-manager form ``with tracer.span(...)`` is used instead
  (closed by construction, not begin()).

Dropping the result of ``begin()`` on the floor is always a finding.
"""

from __future__ import annotations

import ast

from gridllm_tpu.analysis.core import (
    Finding,
    Repo,
    ancestors,
    dotted_name,
    enclosing_function,
    rule,
)

RULE = "span-pairing"


def _is_tracer_begin(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "begin"
            and "tracer" in dotted_name(node.func.value).lower())


def _finally_try(node: ast.AST) -> ast.Try | None:
    """The Try whose ``finally`` block contains ``node``, if any."""
    for anc in ancestors(node):
        if isinstance(anc, ast.Try) and any(
                any(node is d for d in ast.walk(stmt))
                for stmt in anc.finalbody):
            return anc
    return None


def _stmt_of(node: ast.AST) -> ast.stmt | None:
    cur: ast.AST | None = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = getattr(cur, "parent", None)
    return cur


def _try_covers_begin(try_node: ast.Try, begin_stmt: ast.stmt) -> bool:
    """Does the try whose finally ends the span actually protect the code
    after begin()? True when begin() is inside the try body, or is the
    statement immediately preceding the try in the same block — any
    statement in between can raise with the span already open, which is
    exactly the leak this rule exists to flag."""
    for stmt in try_node.body:
        if begin_stmt is stmt or any(begin_stmt is d for d in ast.walk(stmt)):
            return True
    block_holder = getattr(begin_stmt, "parent", None)
    if block_holder is not getattr(try_node, "parent", None):
        return False
    for field in ("body", "orelse", "finalbody"):
        block = getattr(block_holder, field, None)
        if (isinstance(block, list) and begin_stmt in block
                and try_node in block):
            return block.index(try_node) == block.index(begin_stmt) + 1
    return False


@rule(RULE, "tracer spans opened with begin() close on all paths "
            "(end() in a finally, or ownership handed off)")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for f in repo.package_files():
        for node in f.walk():
            if not _is_tracer_begin(node):
                continue
            parent = getattr(node, "parent", None)
            # dropped on the floor
            if isinstance(parent, ast.Expr):
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    "tracer.begin() result discarded — the span can never "
                    "be end()ed; bind it or use `with tracer.span(...)`"))
                continue
            # handoff: assigned into an attribute / mapping slot
            if isinstance(parent, ast.Assign):
                targets = parent.targets
                if all(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in targets):
                    continue
                names = [t.id for t in targets if isinstance(t, ast.Name)]
            elif isinstance(parent, ast.Return):
                continue  # caller owns it
            else:
                names = []
            if not names:
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    "tracer.begin() in a form this rule cannot prove "
                    "closed — bind to a local and end() it in a finally"))
                continue
            fn = enclosing_function(node)
            scope = fn if fn is not None else f.tree
            var = names[0]
            begin_stmt = _stmt_of(node)
            closed = handed_off = False
            for inner in ast.walk(scope):
                if isinstance(inner, ast.Call) \
                        and isinstance(inner.func, ast.Attribute) \
                        and inner.func.attr == "end" and inner.args \
                        and isinstance(inner.args[0], ast.Name) \
                        and inner.args[0].id == var:
                    t = _finally_try(inner)
                    if t is not None and begin_stmt is not None \
                            and _try_covers_begin(t, begin_stmt):
                        closed = True
                # later handoff: self._spans[x] = span / return span
                if isinstance(inner, ast.Assign) \
                        and isinstance(inner.value, ast.Name) \
                        and inner.value.id == var \
                        and all(isinstance(t, (ast.Attribute, ast.Subscript))
                                for t in inner.targets):
                    handed_off = True
                if isinstance(inner, ast.Return) \
                        and isinstance(inner.value, ast.Name) \
                        and inner.value.id == var:
                    handed_off = True
            if not closed and not handed_off:
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"span {var!r} from tracer.begin() has no end({var}) "
                    "in a finally whose try covers the begin() (begin must "
                    "be inside the try or immediately precede it) and is "
                    "never handed off — it leaks open on the exception "
                    "path"))
    return findings
