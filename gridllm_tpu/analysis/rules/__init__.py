"""Rule modules for gridllm_tpu.analysis — one invariant per module.

Every module here is imported by ``core.load_rules()``; its ``@rule``
decorators register checks. To add a rule, add a module with::

    from gridllm_tpu.analysis.core import Finding, Repo, rule

    @rule("my-rule", "one-line description")
    def check(repo: Repo) -> list[Finding]:
        ...
"""
