"""async-discipline: no blocking calls inside ``async def`` bodies in the
control-plane subsystems (ISSUE 13).

One stalled event loop stalls everything that shares it: heartbeats stop
beating (the registry reads that as worker death), watchdog sweeps slip,
and stream flushes back up — the exact failure class behind the PR 4
watchdog-profile caveat. The rule bans, directly inside ``async def``
bodies under ``gateway/``, ``scheduler/``, ``worker/``, ``bus/``, and
``transfer/``:

- ``time.sleep`` (use ``asyncio.sleep``)
- synchronous subprocess calls (``subprocess.run``/``call``/
  ``check_call``/``check_output``/``Popen`` — use
  ``asyncio.create_subprocess_*`` or an executor)
- synchronous HTTP (``requests.*``, ``urllib.request.urlopen``,
  ``http.client`` connections)
- synchronous file I/O (``open``, ``Path.read_text``/``write_text``/
  ``read_bytes``/``write_bytes``)
- unbounded ``<lock>.acquire()`` on a threading-style lock (no timeout,
  not awaited — an asyncio lock's awaited acquire is fine)

Routing through an executor is naturally exempt: ``await
asyncio.to_thread(time.sleep, x)`` passes the function, it does not call
it. Code nested inside a *sync* ``def``/``lambda`` within an async
function is exempt too — those closures are typically thread targets or
executor payloads. A deliberate, justified exception carries an
``# async-ok`` comment on the offending line.
"""

from __future__ import annotations

import ast

from gridllm_tpu.analysis.core import Finding, Repo, ancestors, dotted_name, rule

RULE = "async-discipline"

SUBSYSTEMS = (
    "gridllm_tpu/gateway/",
    "gridllm_tpu/scheduler/",
    "gridllm_tpu/worker/",
    "gridllm_tpu/bus/",
    "gridllm_tpu/transfer/",
    "gridllm_tpu/controlplane/",
)

_BLOCKING_CALLS = {
    "time.sleep": "time.sleep() blocks the event loop — use "
                  "asyncio.sleep()",
    "subprocess.run": "synchronous subprocess call blocks the event loop "
                      "— use asyncio.create_subprocess_exec or an "
                      "executor",
    "subprocess.call": "synchronous subprocess call blocks the event loop",
    "subprocess.check_call": "synchronous subprocess call blocks the "
                             "event loop",
    "subprocess.check_output": "synchronous subprocess call blocks the "
                               "event loop",
    "subprocess.Popen": "synchronous subprocess spawn blocks the event "
                        "loop",
    "urllib.request.urlopen": "synchronous HTTP blocks the event loop — "
                              "use the bus/worker HTTP helpers or an "
                              "executor",
    "http.client.HTTPConnection": "synchronous HTTP blocks the event loop",
    "http.client.HTTPSConnection": "synchronous HTTP blocks the event "
                                   "loop",
}

_PATH_IO_ATTRS = {"read_text", "write_text", "read_bytes", "write_bytes"}

_WAIVER = "# async-ok"


def _nearest_function(node: ast.AST) -> ast.AST | None:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return anc
    return None


def _line_waived(f, lineno: int) -> bool:
    lines = f.text.splitlines()
    return 0 < lineno <= len(lines) and _WAIVER in lines[lineno - 1]


def _is_lockish(name: str) -> bool:
    tail = name.split(".")[-1].lower()
    return "lock" in tail or tail in ("mu", "mutex")


@rule(RULE, "no blocking calls (time.sleep, sync HTTP/file I/O, unbounded "
            "lock.acquire, subprocess) inside async def bodies in "
            "gateway/scheduler/worker/bus/transfer")
def check(repo: Repo) -> list[Finding]:
    findings: list[Finding] = []
    for f in repo.package_files():
        if not f.rel.startswith(SUBSYSTEMS):
            continue
        for node in f.walk():
            if not isinstance(node, ast.Call):
                continue
            owner = _nearest_function(node)
            if not isinstance(owner, ast.AsyncFunctionDef):
                continue  # sync code, or a closure handed to a thread
            fn = dotted_name(node.func)
            msg: str | None = None
            for pat, why in _BLOCKING_CALLS.items():
                if fn == pat or fn.endswith("." + pat):
                    msg = why
                    break
            if msg is None and fn == "open":
                msg = ("synchronous open() blocks the event loop — use "
                       "asyncio.to_thread (or do the I/O off-loop)")
            if msg is None and fn.startswith("requests."):
                # module-rooted only: self.requests.append() is a list
                # named "requests", not the HTTP library
                msg = "synchronous requests.* HTTP blocks the event loop"
            if msg is None and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _PATH_IO_ATTRS:
                msg = (f".{node.func.attr}() is synchronous file I/O — "
                       "route it through asyncio.to_thread")
            if msg is None and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire" \
                    and _is_lockish(dotted_name(node.func.value)) \
                    and _acquire_is_unbounded(node) \
                    and not isinstance(getattr(node, "parent", None),
                                       ast.Await):
                msg = ("unbounded lock.acquire() inside an async body can "
                       "park the whole event loop — pass a timeout, use "
                       "an asyncio.Lock, or route through an executor")
            if msg is not None and not _line_waived(f, node.lineno):
                findings.append(Finding(
                    RULE, f.rel, node.lineno,
                    f"{msg} (in async def {owner.name}; waive a "
                    f"deliberate exception with {_WAIVER!r})"))
    return findings


def _acquire_is_unbounded(node: ast.Call) -> bool:
    """True when the acquire can park forever: acquire(), acquire(True),
    acquire(blocking=True). Bounded: a timeout (second positional or
    keyword) or a non-blocking try (first arg / blocking= is False)."""
    if len(node.args) >= 2:
        return False  # acquire(blocking, timeout)
    for kw in node.keywords:
        if kw.arg == "timeout":
            return False
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return False
    if node.args and isinstance(node.args[0], ast.Constant) \
            and node.args[0].value is False:
        return False  # acquire(False): non-blocking try
    return True
