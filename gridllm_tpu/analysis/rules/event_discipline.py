"""event-discipline: every timeline event goes through the typed EVENTS
registry in ``obs/timeline.py`` (ISSUE 17 — the forensic twin of
channel-discipline).

The fleet timeline stitches flight-recorder events from every member
into one causal view; an event name or payload key that drifts from the
registry silently breaks incident assembly and the README's forensics
contract. Invariants:

1. Every emission site resolves statically: a flight-recorder
   ``record(subsystem, event, **fields)`` call (receiver spelled like a
   recorder) or an ``emit_event(name, ...)`` call outside tests and
   ``obs/timeline.py`` itself must have a statically known event name —
   a constant, an inline conditional of constants, a local conditional
   assignment, or a parameter pinned by same-file call sites.
2. Every emitted event is declared exactly once in EVENTS, from a
   declared module, and sends only declared payload keys (``**splat``
   sites require ``open_keys``). ``emit_event``'s envelope arguments
   (member/request_id/stamp) are transport attribution, not payload.
3. Both ways: a declared event no module ever emits — or a declared
   module/key no site ever uses — is a dead declaration (skipped for
   ``open_keys`` events, whose key sets are a lower bound).
4. The README "Timeline events" table and the registry agree both ways
   (name, keys, emitting modules), the way channel-discipline pins the
   Bus channels table.

Like channel-discipline, the registry is parsed from the ANALYZED tree;
fixture repos without an obs/timeline.py registry fall back to the
imported registry and skip the repo-structure checks (3, 4).
"""

from __future__ import annotations

import ast
import re

from gridllm_tpu.analysis.core import (
    Finding,
    Repo,
    dotted_name,
    enclosing_function,
    rule,
    str_const,
)

RULE = "event-discipline"
TIMELINE = "gridllm_tpu/obs/timeline.py"
# emit_event() envelope: attribution the publisher strips into the event
# envelope, never payload keys
_ENVELOPE = {"member", "request_id", "stamp"}


class _Spec:
    __slots__ = ("name", "keys", "modules", "open_keys", "line")

    def __init__(self, name, keys, modules, open_keys, line):
        self.name = name
        self.keys = keys
        self.modules = modules
        self.open_keys = open_keys
        self.line = line


def _tuple_const(node: ast.AST | None) -> tuple[str, ...] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = [str_const(e) for e in node.elts]
        if all(v is not None for v in vals):
            return tuple(vals)  # type: ignore[arg-type]
    return None


def _parse_registry(repo: Repo) -> tuple[dict[str, _Spec], bool]:
    """(name -> spec, from_tree) — parsed from the analyzed tree's
    obs/timeline.py; imported-registry fallback for fixture repos."""
    f = repo.file(TIMELINE)
    specs: dict[str, _Spec] = {}
    if f is not None:
        for node in f.walk():
            if not (isinstance(node, ast.Call)
                    and dotted_name(node.func).endswith("register_event")
                    and node.args):
                continue
            name = str_const(node.args[0])
            if name is None:
                continue
            kw = {k.arg: k.value for k in node.keywords if k.arg}
            specs[name] = _Spec(
                name,
                _tuple_const(kw.get("keys")) or (),
                _tuple_const(kw.get("modules")) or (),
                isinstance(kw.get("open_keys"), ast.Constant)
                and bool(kw["open_keys"].value),  # type: ignore[union-attr]
                node.lineno,
            )
    if specs:
        return specs, True
    from gridllm_tpu.obs.timeline import EVENTS

    return {s.name: _Spec(s.name, s.keys, s.modules, s.open_keys, 0)
            for s in EVENTS.values()}, False


def _is_recorder(recv: str) -> bool:
    low = recv.lower()
    return "flightrec" in low or "recorder" in low


def _resolve_event_names(f, call: ast.Call,
                         arg: ast.AST) -> list[str] | None:
    """Statically known spellings of an event-name argument: a constant,
    an inline ``a if c else b`` of constants, a Name assigned such a
    conditional in the enclosing function, or a parameter whose value is
    pinned by every same-file call site. None when unresolvable."""
    s = str_const(arg)
    if s is not None:
        return [s]
    if isinstance(arg, ast.IfExp):
        a, b = str_const(arg.body), str_const(arg.orelse)
        if a is not None and b is not None:
            return [a, b]
    if isinstance(arg, ast.Name):
        fn = enclosing_function(call)
        if fn is None:
            return None
        for st in ast.walk(fn):
            if (isinstance(st, ast.Assign) and len(st.targets) == 1
                    and isinstance(st.targets[0], ast.Name)
                    and st.targets[0].id == arg.id
                    and isinstance(st.value, ast.IfExp)):
                a = str_const(st.value.body)
                b = str_const(st.value.orelse)
                if a is not None and b is not None:
                    return [a, b]
        params = [p.arg for p in fn.args.args]
        if arg.id in params:
            idx = params.index(arg.id)
            names = []
            for c in f.walk():
                if (isinstance(c, ast.Call)
                        and isinstance(c.func, (ast.Name, ast.Attribute))
                        and dotted_name(c.func).split(".")[-1] == fn.name
                        and len(c.args) > idx):
                    s2 = str_const(c.args[idx])
                    if s2 is not None:
                        names.append(s2)
            if names:
                return sorted(set(names))
    return None


class _Site:
    __slots__ = ("names", "keys", "splat", "rel", "line")

    def __init__(self, names, keys, splat, rel, line):
        self.names = names
        self.keys = keys
        self.splat = splat
        self.rel = rel
        self.line = line


def _collect_sites(repo: Repo) -> tuple[list[_Site], list[Finding]]:
    """Every timeline-event emission site in the package (tests and the
    registry module itself excluded)."""
    sites: list[_Site] = []
    findings: list[Finding] = []
    for f in repo.package_files():
        if f.rel == TIMELINE:
            continue
        for node in f.walk():
            if not isinstance(node, ast.Call):
                continue
            names: list[str] | None = None
            kw_start = node.keywords
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "record" \
                    and _is_recorder(dotted_name(node.func.value)):
                if len(node.args) < 2:
                    continue
                sub = str_const(node.args[0])
                evs = _resolve_event_names(f, node, node.args[1])
                if sub is None or evs is None:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        "flight-recorder record() with a statically "
                        "unresolvable subsystem/event name — the timeline "
                        "cannot be checked against the EVENTS registry"))
                    continue
                names = [f"{sub}.{ev}" for ev in evs]
                envelope: set[str] = set()
            elif dotted_name(node.func).split(".")[-1] == "emit_event":
                arg = node.args[0] if node.args else None
                names = (_resolve_event_names(f, node, arg)
                         if arg is not None else None)
                if names is None:
                    findings.append(Finding(
                        RULE, f.rel, node.lineno,
                        "emit_event() with a statically unresolvable "
                        "event name — the timeline cannot be checked "
                        "against the EVENTS registry"))
                    continue
                envelope = _ENVELOPE
            else:
                continue
            keys: set[str] = set()
            splat = False
            for kw in kw_start:
                if kw.arg is None:
                    splat = True
                elif kw.arg not in envelope:
                    keys.add(kw.arg)
            sites.append(_Site(names, keys, splat, f.rel, node.lineno))
    return sites, findings


@rule(RULE, "timeline events go through the typed EVENTS registry in "
            "obs/timeline.py; emission sites, payload keys, modules, and "
            "the README Timeline events table must all agree with it")
def check(repo: Repo) -> list[Finding]:
    specs, from_tree = _parse_registry(repo)
    sites, findings = _collect_sites(repo)

    emitted: set[str] = set()
    used_keys: dict[str, set[str]] = {}
    used_mods: dict[str, set[str]] = {}
    for site in sites:
        for name in site.names:
            spec = specs.get(name)
            if spec is None:
                findings.append(Finding(
                    RULE, site.rel, site.line,
                    f"timeline event {name!r} is emitted but not declared "
                    "in the EVENTS registry (obs/timeline.py)"))
                continue
            emitted.add(name)
            used_mods.setdefault(name, set()).add(site.rel)
            used_keys.setdefault(name, set()).update(site.keys)
            if site.rel not in spec.modules:
                findings.append(Finding(
                    RULE, site.rel, site.line,
                    f"{site.rel} emits timeline event {name!r} but is not "
                    "a declared module in the EVENTS registry"))
            if site.splat and not spec.open_keys:
                findings.append(Finding(
                    RULE, site.rel, site.line,
                    f"timeline event {name!r} is emitted with dynamic "
                    "**fields but is not declared open_keys"))
            for k in sorted(site.keys):
                if k not in spec.keys:
                    findings.append(Finding(
                        RULE, site.rel, site.line,
                        f"payload key {k!r} on timeline event {name!r} is "
                        "not declared in the EVENTS registry"))

    # -- 3. dead declarations (real repo only — fixture repos have no
    #       emission sites for the imported registry)
    if from_tree:
        for spec in specs.values():
            if spec.name not in emitted:
                findings.append(Finding(
                    RULE, TIMELINE, spec.line,
                    f"EVENTS declares {spec.name!r}, which no module ever "
                    "emits — dead declaration or missed migration"))
                continue
            for mod in spec.modules:
                if mod not in used_mods.get(spec.name, set()):
                    findings.append(Finding(
                        RULE, TIMELINE, spec.line,
                        f"EVENTS declares {spec.name!r} emitted from "
                        f"{mod}, but that module never emits it"))
            if not spec.open_keys:
                for k in spec.keys:
                    if k not in used_keys.get(spec.name, set()):
                        findings.append(Finding(
                            RULE, TIMELINE, spec.line,
                            f"EVENTS declares payload key {k!r} on "
                            f"{spec.name!r} that no site ever sends"))

    # -- 4. README "Timeline events" table <-> registry, both ways
    if from_tree:
        findings.extend(_check_readme(repo, specs))
    return findings


def _keys_cell(spec: _Spec) -> str:
    if not spec.keys and not spec.open_keys:
        return "—"
    body = ", ".join(spec.keys)
    if spec.open_keys:
        body = f"{body}, …" if body else "…"
    return f"`{body}`"


def _mods_cell(spec: _Spec) -> str:
    return ", ".join(m.rsplit("/", 1)[-1].removesuffix(".py")
                     for m in spec.modules)


def _check_readme(repo: Repo, specs: dict[str, _Spec]) -> list[Finding]:
    findings: list[Finding] = []
    readme = repo.read_text("README.md")
    if readme is None:
        return [Finding(RULE, "README.md", 0, "README.md missing")]
    in_section = False
    rows: dict[str, tuple[str, str, int]] = {}  # name -> (keys, mods, line)
    for i, line in enumerate(readme.splitlines(), 1):
        if line.startswith("#"):
            in_section = (line.lstrip("#").strip().lower()
                          == "timeline events")
            continue
        if not in_section or not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) < 3:
            continue
        m = re.fullmatch(r"`([^`]+)`", cells[0])
        if m is None or m.group(1) in ("Event",):
            continue
        rows.setdefault(m.group(1), (cells[1], cells[2], i))
    if not rows:
        return [Finding(
            RULE, "README.md", 0,
            "README has no \"Timeline events\" table documenting the "
            "EVENTS registry")]
    for name, (keys_cell, mods_cell, line) in sorted(rows.items()):
        spec = specs.get(name)
        if spec is None:
            findings.append(Finding(
                RULE, "README.md", line,
                f"README documents timeline event {name!r}, which is not "
                "in the obs/timeline.py EVENTS registry"))
            continue
        if keys_cell != _keys_cell(spec):
            findings.append(Finding(
                RULE, "README.md", line,
                f"README says timeline event {name!r} keys are "
                f"{keys_cell!r} but the registry says "
                f"{_keys_cell(spec)!r}"))
        if mods_cell != _mods_cell(spec):
            findings.append(Finding(
                RULE, "README.md", line,
                f"README says timeline event {name!r} is emitted from "
                f"{mods_cell!r} but the registry says "
                f"{_mods_cell(spec)!r}"))
    for spec in specs.values():
        if spec.name not in rows:
            findings.append(Finding(
                RULE, "README.md", 0,
                f"registered timeline event {spec.name!r} missing from "
                "the README Timeline events table"))
    return findings
