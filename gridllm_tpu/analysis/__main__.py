"""CLI: ``python -m gridllm_tpu.analysis [--strict] [--json] [--rule R]``.

Exit codes: 0 = clean, 1 = findings, 2 = bad usage. ``--strict`` is the
CI gate spelling — identical checks, and the exit code is the contract
(tier1.yml static-analysis job). Run from the repo root, or point
``--root`` at one.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from gridllm_tpu.analysis.core import RULES, load_rules, run_timed


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m gridllm_tpu.analysis",
        description="GridLLM-TPU repo-wide static invariant analyzer.")
    ap.add_argument("--root", default=".",
                    help="repo root to analyze (default: cwd)")
    ap.add_argument("--rule", action="append", default=None, metavar="NAME",
                    help="run only this rule (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--strict", action="store_true",
                    help="CI spelling: exit 1 on any finding (the default "
                         "behavior; kept explicit so gates read as gates)")
    ap.add_argument("--list", action="store_true",
                    help="list registered rules and exit")
    args = ap.parse_args(argv)

    if args.list:
        load_rules()
        for name in sorted(RULES):
            print(f"{name}: {RULES[name].description}")
        return 0

    root = Path(args.root)
    if not (root / "gridllm_tpu").is_dir():
        print(f"error: {root.resolve()} does not look like a repo root "
              "(no gridllm_tpu/ package)", file=sys.stderr)
        return 2
    try:
        findings, timings = run_timed(root, args.rule)
    except KeyError as e:
        print(f"error: {e.args[0]}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "version": "gridllm-analysis/v1",
            "root": str(root.resolve()),
            "rules": args.rule or sorted(RULES),
            "findings": [f.to_dict() for f in findings],
            # per-rule wall seconds ("_load" = parse + parent-annotate,
            # paid once and shared) — CI watches for a rule gone slow
            "timings": {k: round(v, 6) for k, v in timings.items()},
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        n_rules = len(args.rule) if args.rule else len(RULES)
        print(f"{len(findings)} finding(s) from {n_rules} rule(s).")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
