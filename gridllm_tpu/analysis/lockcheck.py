"""Runtime lock-discipline sanitizer (``GRIDLLM_SANITIZE=1``, ISSUE 8).

The static lock-discipline rule proves the engine's documented protocol
lexically; this module proves it dynamically, on whatever paths the test
actually executes — the two checkers share one invariant set.

What it does when installed (tests/conftest.py installs it when
``GRIDLLM_SANITIZE`` is truthy):

1. **Lock-order graph.** ``threading.Lock``/``RLock`` factories are
   replaced with proxies that record, per thread, the stack of held
   locks. Acquiring lock B while holding lock A adds the edge A→B,
   keyed by each lock's CREATION SITE (``file:line``) so per-engine
   twin instances collapse into one node. A cycle in the site graph is
   a lock-order inversion two threads can interleave into a deadlock —
   ``cycles()`` reports it and the pytest hook fails the run.
2. **Allocator guard.** The engine registers its ``PageAllocator``
   against its ``_alloc_lock`` (:func:`guard_allocator`); every mutating
   allocator call then asserts the calling thread owns the lock and
   raises :class:`LockDisciplineError` immediately — pointing at the
   unguarded call site, not at the refcount corruption three requests
   later.

Reentrant re-acquisition of the same lock instance and edges between
two instances from the same creation site are not edges (an RLock
re-enter and per-engine twins are both benign).

Everything here is stdlib-only and dormant unless explicitly enabled;
the proxies add one monitor-lock round trip per acquire/release (held
stacks are shared state: a cross-thread release mutates the acquirer's).
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable

from gridllm_tpu.utils.config import env_bool

_REAL_LOCK: Callable[[], Any] = threading.Lock
_REAL_RLOCK: Callable[[], Any] = threading.RLock


class LockDisciplineError(AssertionError):
    """A lock-order cycle or an unguarded allocator mutation."""


def enabled() -> bool:
    return env_bool("GRIDLLM_SANITIZE")


# -- monitor ----------------------------------------------------------------

class _Monitor:
    """Process-wide acquisition recorder: per-thread held stacks plus the
    site-level order graph."""

    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        # thread ident -> that thread's held stack, so a cross-thread
        # release (see on_released) can find the acquirer's entry
        self._stacks: dict[int, list[tuple[str, int]]] = {}
        # (site_a, site_b) -> observation count
        self.edges: dict[tuple[str, str], int] = {}

    def _held(self) -> list[tuple[str, int]]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
            with self._mu:
                # ident reuse after thread death replaces the dead
                # thread's (empty) stack — exactly what we want
                self._stacks[threading.get_ident()] = held
        return held

    def on_acquired(self, proxy: "_LockProxy") -> None:
        # _held() before _mu: first-call registration takes _mu itself.
        # All stack mutation happens under _mu because a cross-thread
        # release (below) may delete from THIS thread's stack concurrently.
        held = self._held()
        with self._mu:
            for site, lock_id in held:
                if lock_id == id(proxy) or site == proxy.site:
                    continue  # reentry / same-creation-site twin
                e = (site, proxy.site)
                self.edges[e] = self.edges.get(e, 0) + 1
            held.append((proxy.site, id(proxy)))

    def on_released(self, proxy: "_LockProxy") -> None:
        held = self._held()
        with self._mu:
            for i in range(len(held) - 1, -1, -1):
                if held[i][1] == id(proxy):
                    del held[i]
                    return
            # plain Lock legally allows release from a thread other than
            # the acquirer (handoff patterns). The entry lives on the
            # ACQUIRER's stack — drop it there, or it sticks forever and
            # every later acquire on that thread records bogus edges
            # (false cycles).
            for other in self._stacks.values():
                for i in range(len(other) - 1, -1, -1):
                    if other[i][1] == id(proxy):
                        del other[i]
                        return

    def held_sites(self) -> tuple[str, ...]:
        """Creation sites of the locks the CALLING thread currently
        holds, outermost first (the shared-state sanitizer keys write
        records by these). Lock-free on purpose: this runs on every
        tracked write, and taking ``_mu`` here would serialize hot-path
        writes against all proxy bookkeeping. The list is mutated under
        the GIL (almost always by this thread; a cross-thread release's
        fallback scan is the rare exception), so ``list()`` snapshots a
        consistent before-or-after state — at worst one momentarily
        stale entry, which only widens a lock intersection."""
        held = getattr(self._tls, "held", None)
        if not held:
            return ()
        return tuple(site for site, _ in list(held))

    def snapshot_edges(self) -> dict[tuple[str, str], int]:
        with self._mu:
            return dict(self.edges)

    def cycles(self) -> list[list[str]]:
        """Cycles in the site-level order graph (DFS, each reported once)."""
        edges = self.snapshot_edges()
        graph: dict[str, set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        seen: set[str] = set()
        out: list[list[str]] = []

        def dfs(node: str, stack: list[str], on_stack: set[str]) -> None:
            seen.add(node)
            stack.append(node)
            on_stack.add(node)
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_stack:
                    out.append(stack[stack.index(nxt):] + [nxt])
                elif nxt not in seen:
                    dfs(nxt, stack, on_stack)
            stack.pop()
            on_stack.discard(node)

        for node in sorted(graph):
            if node not in seen:
                dfs(node, [], set())
        return out

    def reset(self) -> None:
        with self._mu:
            self.edges.clear()

    def restore(self, edges: dict[tuple[str, str], int]) -> None:
        """Merge a previously snapshotted edge set back in — lets tests
        that reset the process-global graph hand back what earlier suites
        recorded, so a sanitized session's final verdict still covers them."""
        with self._mu:
            for e, n in edges.items():
                self.edges[e] = self.edges.get(e, 0) + n


_MON = _Monitor()


def _creation_site() -> str:
    """file:line of the frame that called Lock()/RLock(), skipping this
    module and threading internals (Condition() creating its RLock should
    attribute to the Condition's owner, best-effort)."""
    for frame in reversed(traceback.extract_stack()):
        fn = frame.filename
        # exact-match this module (endswith would also skip callers whose
        # file merely ends in "lockcheck.py", e.g. tests/test_lockcheck.py)
        if fn == __file__ or fn.rsplit("/", 1)[-1] == "threading.py":
            continue
        return f"{fn}:{frame.lineno}"
    return "<unknown>"


class _LockProxy:
    """Wraps a real Lock/RLock; records acquire/release with the monitor.
    Unknown attributes (``_is_owned``, ``_release_save``, …) forward to
    the real lock, so ``threading.Condition`` keeps working."""

    def __init__(self, real: Any, site: str):
        self._real = real
        self.site = site

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        got = self._real.acquire(*args, **kwargs)
        if got:
            _MON.on_acquired(self)
        return got

    def release(self) -> None:
        # record BEFORE the real release: once the real lock is free,
        # another thread's acquire can append its own entry for this
        # proxy, and a cross-thread release's fallback scan could then
        # delete the fresh entry instead of the stale one. While we still
        # hold the real lock, at most one entry for this proxy exists.
        # (Releasing an unheld lock: the scan finds nothing, then the
        # real release raises as it should.)
        _MON.on_released(self)
        self._real.release()

    def __enter__(self) -> "_LockProxy":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._real, name)

    def __repr__(self) -> str:
        return f"<sanitized {self._real!r} from {self.site}>"


def make_lock() -> _LockProxy:
    return _LockProxy(_REAL_LOCK(), _creation_site())


def make_rlock() -> _LockProxy:
    return _LockProxy(_REAL_RLOCK(), _creation_site())


_installed = False


def install() -> None:
    """Replace the threading lock factories with sanitized proxies. Locks
    created BEFORE install (import-time locks in third-party modules) stay
    real — the engine/scheduler locks this exists for are created per
    instance, after conftest runs."""
    global _installed
    if _installed:
        return
    threading.Lock = make_lock  # type: ignore[assignment]
    threading.RLock = make_rlock  # type: ignore[assignment]
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Lock = _REAL_LOCK  # type: ignore[assignment]
    threading.RLock = _REAL_RLOCK  # type: ignore[assignment]
    _installed = False


def installed() -> bool:
    return _installed


def current_held_sites() -> tuple[str, ...]:
    """Creation sites of the locks the calling thread holds right now
    (empty when the proxies are not installed)."""
    return _MON.held_sites()


def reset() -> None:
    _MON.reset()


def restore(edges: dict[tuple[str, str], int]) -> None:
    _MON.restore(edges)


def edges() -> dict[tuple[str, str], int]:
    return _MON.snapshot_edges()


def cycles() -> list[list[str]]:
    return _MON.cycles()


def report() -> dict[str, Any]:
    cyc = cycles()
    return {
        "installed": _installed,
        "edges": [{"from": a, "to": b, "count": n}
                  for (a, b), n in sorted(_MON.snapshot_edges().items())],
        "cycles": cyc,
        "ok": not cyc,
    }


def assert_clean() -> None:
    cyc = cycles()
    if cyc:
        lines = [" -> ".join(c) for c in cyc]
        raise LockDisciplineError(
            "lock-order cycle(s) observed (sites are lock creation "
            "points):\n  " + "\n  ".join(lines))


# -- allocator guard --------------------------------------------------------

# PageAllocator methods that mutate free lists / refcounts / the reuse LRU:
# ONE set, owned by the static rule — importing it here means a mutator
# added to the analyzer is automatically guarded at runtime too, so the
# two checkers cannot drift apart
from gridllm_tpu.analysis.rules.lock_discipline import (  # noqa: E402
    MUTATORS as GUARDED_MUTATORS,
)


def _owned(lock: Any) -> bool:
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is not None:
        return bool(is_owned())
    return bool(lock.locked())  # plain Lock: held by someone, best-effort


def guard_allocator(allocator: Any, lock: Any) -> Any:
    """Wrap ``allocator``'s mutating methods to assert ``lock`` is owned
    by the calling thread. Instance-level patch: other allocators (unit
    tests poking PageAllocator directly) are untouched."""
    if getattr(allocator, "_sanitize_guarded", False):
        return allocator

    def wrap(name: str, fn: Callable[..., Any]) -> Callable[..., Any]:
        def checked(*args: Any, **kwargs: Any) -> Any:
            if not _owned(lock):
                raise LockDisciplineError(
                    f"PageAllocator.{name}() called without the engine's "
                    "_alloc_lock held — allocator mutation from an "
                    "unguarded path (see engine/engine.py lock protocol)")
            return fn(*args, **kwargs)

        checked.__name__ = f"sanitized_{name}"
        return checked

    for name in GUARDED_MUTATORS:
        setattr(allocator, name, wrap(name, getattr(allocator, name)))
    allocator._sanitize_guarded = True
    return allocator
