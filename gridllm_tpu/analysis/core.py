"""Static-analysis core (ISSUE 8): repo loader, rule registry, findings.

The analyzer is AST-based and import-light by design: it parses source
text, never imports the modules it checks (except ``utils/config.py``'s
pure-data env registry), and never touches jax — so ``python -m
gridllm_tpu.analysis`` is safe to run on a control-plane host, in CI, and
as a pre-commit hook, in well under a second.

A rule is a function ``check(repo) -> list[Finding]`` registered via the
:func:`rule` decorator. Rules live in ``gridllm_tpu/analysis/rules/`` and
are discovered by import; adding a rule is adding a module there (see
README "Static analysis & sanitizers").
"""

from __future__ import annotations

import ast
import dataclasses
import importlib
import pkgutil
import time
from pathlib import Path
from typing import Any, Callable, Iterator

# directories the repo walker ignores outright
_SKIP_DIRS = {"__pycache__", ".git", ".github", "node_modules", ".claude"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect at one location. ``path`` is repo-relative."""

    rule: str
    path: str
    line: int
    message: str

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed python file. The AST is parsed + parent-annotated ONCE
    (``.parent`` back-references let rules walk upward — enclosing
    with/try/def) and the flattened node list is cached, so all rules
    share one parse and one tree walk per file instead of redoing either
    per rule."""

    def __init__(self, root: Path, path: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix()
        self.text = path.read_text(encoding="utf-8", errors="replace")
        self._tree: ast.Module | None = None
        self._nodes: list[ast.AST] | None = None
        self._lines: list[str] | None = None
        self.parse_error: SyntaxError | None = None

    @property
    def lines(self) -> list[str]:
        """Split source lines, cached — waiver-comment lookups run once
        per candidate node, and re-splitting the text each time is
        O(file × nodes) waste."""
        if self._lines is None:
            self._lines = self.text.splitlines()
        return self._lines

    @property
    def tree(self) -> ast.Module | None:
        if self._tree is None and self.parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.rel)
            except SyntaxError as e:  # surfaced as a finding by run()
                self.parse_error = e
                return None
            for node in ast.walk(self._tree):
                for child in ast.iter_child_nodes(node):
                    child.parent = node  # type: ignore[attr-defined]
        return self._tree

    def walk(self) -> Iterator[ast.AST]:
        if self._nodes is None:
            tree = self.tree
            self._nodes = [] if tree is None else list(ast.walk(tree))
        return iter(self._nodes)


class Repo:
    """The analyzed tree: every .py file under the package, tests, deploy
    scripts, and the top-level entry points, plus raw-text access to
    non-python artifacts (dashboards, alerts, README)."""

    def __init__(self, root: Path):
        self.root = Path(root).resolve()
        self.files: list[SourceFile] = []
        for sub in ("gridllm_tpu", "tests", "deploy"):
            base = self.root / sub
            if base.is_dir():
                for p in sorted(base.rglob("*.py")):
                    if not _SKIP_DIRS.intersection(p.parts):
                        self.files.append(SourceFile(self.root, p))
        for name in ("bench.py",):
            p = self.root / name
            if p.is_file():
                self.files.append(SourceFile(self.root, p))
        self._by_rel = {f.rel: f for f in self.files}
        # parse + parent-annotate every file ONCE, here in the loader —
        # the trees (and cached node lists) are shared by all rules;
        # syntax errors surface exactly once as findings in run()
        for f in self.files:
            f.tree

    def file(self, rel: str) -> SourceFile | None:
        return self._by_rel.get(rel)

    def package_files(self, include_tests: bool = False) -> list[SourceFile]:
        out = [f for f in self.files if f.rel.startswith("gridllm_tpu/")]
        if include_tests:
            out += [f for f in self.files if f.rel.startswith("tests/")]
        return out

    def read_text(self, rel: str) -> str | None:
        p = self.root / rel
        if not p.is_file():
            return None
        return p.read_text(encoding="utf-8", errors="replace")


# -- rule registry ----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    name: str
    description: str
    check: Callable[[Repo], list[Finding]]


RULES: dict[str, Rule] = {}


def rule(name: str, description: str):
    """Register ``check(repo) -> list[Finding]`` under ``name``."""

    def deco(fn: Callable[[Repo], list[Finding]]):
        RULES[name] = Rule(name, description, fn)
        return fn

    return deco


def load_rules() -> None:
    """Import every module in gridllm_tpu.analysis.rules (side effect:
    the @rule decorators populate RULES)."""
    from gridllm_tpu.analysis import rules as rules_pkg

    for mod in pkgutil.iter_modules(rules_pkg.__path__):
        importlib.import_module(f"{rules_pkg.__name__}.{mod.name}")


def run(root: str | Path, rule_names: list[str] | None = None) -> list[Finding]:
    """Run the selected rules (default: all) over the repo at ``root``."""
    return run_timed(root, rule_names)[0]


def run_timed(
    root: str | Path, rule_names: list[str] | None = None,
) -> tuple[list[Finding], dict[str, float]]:
    """Like :func:`run`, also returning per-rule wall time in seconds —
    surfaced in the CLI's ``--json`` output so CI can spot a rule whose
    cost regressed (the repo loader parses every tree once up front;
    a slow rule is a slow RULE, not a re-parse)."""
    load_rules()
    t0 = time.perf_counter()
    repo = Repo(Path(root))
    timings: dict[str, float] = {"_load": time.perf_counter() - t0}
    findings: list[Finding] = []
    for f in repo.files:
        if f.parse_error is not None:
            findings.append(Finding(
                "parse", f.rel, f.parse_error.lineno or 0,
                f"syntax error: {f.parse_error.msg}"))
    names = rule_names if rule_names else sorted(RULES)
    for name in names:
        if name not in RULES:
            raise KeyError(f"unknown rule {name!r}; known: {sorted(RULES)}")
        t0 = time.perf_counter()
        findings.extend(RULES[name].check(repo))
        timings[name] = time.perf_counter() - t0
    findings.sort(key=lambda x: (x.path, x.line, x.rule))
    return findings, timings


# -- shared AST helpers -----------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of an expression: ``self.alloc.free`` →
    "self.alloc.free"; non-name parts render as ``?``."""
    if isinstance(node, ast.Attribute):
        return f"{dotted_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return f"{dotted_name(node.func)}()"
    return "?"


def str_const(node: ast.AST | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "parent", None)


def enclosing_function(node: ast.AST) -> ast.AST | None:
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


@dataclasses.dataclass(frozen=True)
class MetricReg:
    """One ``registry.counter/gauge/histogram("name", "help", (labels,))``
    call site found statically."""

    name: str
    kind: str                      # counter | gauge | histogram
    help: str | None               # None when not a string literal
    labels: tuple[str, ...] | None  # None when not a literal tuple
    file: str
    line: int


_METRIC_KINDS = {"counter", "gauge", "histogram"}


def _call_arg(node: ast.Call, idx: int, kw_name: str) -> ast.AST | None:
    """The expression bound to a parameter, whether passed positionally
    (``idx``) or by keyword (``kw_name``); None when absent."""
    if len(node.args) > idx:
        return node.args[idx]
    for kw in node.keywords:
        if kw.arg == kw_name:
            return kw.value
    return None


def collect_metric_registrations(repo: Repo) -> list[MetricReg]:
    """Every metric-registration call in the package (tests excluded):
    a ``.counter(``/``.gauge(``/``.histogram(`` call whose name argument
    is a ``gridllm_``-prefixed string literal, plus any whose receiver
    looks like a metrics registry (so misnamed metrics still surface).
    Arguments count whether positional or keyword (``labelnames=...``)."""
    out: list[MetricReg] = []
    for f in repo.package_files():
        for node in f.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METRIC_KINDS
                    and (node.args or node.keywords)):
                continue
            name = str_const(_call_arg(node, 0, "name"))
            recv = dotted_name(node.func.value).lower()
            registryish = ("registry" in recv or "metrics" in recv
                           or recv == "m" or "_obs" in recv)
            if name is None or not (name.startswith("gridllm_")
                                    or registryish):
                continue
            help_text = str_const(_call_arg(node, 1, "help"))
            labels_expr = _call_arg(node, 2, "labelnames")
            labels: tuple[str, ...] | None
            if labels_expr is None:
                # no labels passed at all — unless a **kwargs splat could
                # be smuggling some, in which case nothing can be audited
                splat = any(kw.arg is None for kw in node.keywords)
                labels = None if splat else ()
            elif isinstance(labels_expr, (ast.Tuple, ast.List)):
                vals = [str_const(e) for e in labels_expr.elts]
                labels = (tuple(v for v in vals if v is not None)
                          if all(v is not None for v in vals) else None)
            else:
                labels = None  # non-literal labels: unauditable, flagged
            out.append(MetricReg(name or "?", node.func.attr, help_text,
                                 labels, f.rel, node.lineno))
    return out
