"""Runtime shared-state sanitizer (``GRIDLLM_SANITIZE=1``, ISSUE 13).

The lock-order checker (lockcheck.py) proves that the locks the code
DOES take compose without deadlock; it cannot see a mutation that takes
no lock at all. This module covers that gap for a registered set of hot
objects — the scheduler's job tables, the registry's worker map, the
engine's allocator state: every attribute write (and in-place mutation
of dict/list-valued attributes) is recorded keyed by the writing thread
and the lock creation-sites that thread held (from lockcheck's proxy
stacks). An attribute written from two or more threads with NO lock
site common to all of its writes is a cross-thread unguarded mutation —
exactly the class of race the lock-order graph can't flag, reported
with the first write site per thread so the fix is a grep, not a
bisect.

Mechanics: :func:`track_object` patches the object's CLASS
``__setattr__`` once (a dict lookup per write for untracked instances)
and swaps tracked plain-``dict``/``list`` attribute values for
recording subclasses, re-wrapping on rebind. Registration itself
records nothing — object construction is single-threaded by
happens-before (``Thread.start``), and counting it would poison the
intersection with the init thread's (lockless) writes.

Dormant unless ``GRIDLLM_SANITIZE`` is truthy: ``track_object`` is a
no-op, nothing is patched, zero hot-path cost. ``tests/conftest.py``
fails the session (exit 3) on violations, alongside lockcheck's cycle
check. Single-threaded writers never violate, whatever locks they hold
— an asyncio-only subsystem is clean by construction.

Known limits (best-effort, like every sanitizer here): mutations
through an alias taken before tracking, non-dict/list containers
(OrderedDict, set), and reads are not tracked.
"""

from __future__ import annotations

import threading
import traceback
import weakref
from typing import Any, Iterable

from gridllm_tpu.analysis import lockcheck
from gridllm_tpu.utils.config import env_bool

# the monitor's own lock must be a REAL lock: a proxied one would record
# itself into the very held-stacks it is reading
_mu = lockcheck._REAL_LOCK()


class SharedStateError(AssertionError):
    """A registered hot object was mutated cross-thread without any
    common lock."""


def enabled() -> bool:
    return env_bool("GRIDLLM_SANITIZE")


class _Entry:
    __slots__ = ("threads", "common", "writes")

    def __init__(self, tid: int, site: str, held: frozenset[str]):
        self.threads: dict[int, str] = {tid: site}  # tid -> first write site
        self.common: frozenset[str] = held          # ∩ held-locks over writes
        self.writes = 1


# (object name, attr) -> _Entry
_entries: dict[tuple[str, str], _Entry] = {}
# id(obj) -> (name, tracked attrs or None for all)
_tracked: dict[int, tuple[str, frozenset[str] | None]] = {}
# id(obj) -> weakref keeping the cleanup callback alive
_reapers: dict[int, Any] = {}
_patched: set[type] = set()


def _caller_site() -> str:
    for frame in reversed(traceback.extract_stack()):
        if frame.filename != __file__:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


def _record(name: str, attr: str) -> None:
    held = frozenset(lockcheck.current_held_sites())
    tid = threading.get_ident()
    key = (name, attr)
    with _mu:
        e = _entries.get(key)
        if e is None:
            _entries[key] = _Entry(tid, _caller_site(), held)
            return
        e.writes += 1
        e.common = e.common & held
        if tid not in e.threads:
            e.threads[tid] = _caller_site()


class _TrackedDict(dict):
    """dict that reports in-place mutation to the monitor."""

    _ss_name = "?"
    _ss_attr = "?"

    def _note(self) -> None:
        _record(self._ss_name, self._ss_attr)

    def __setitem__(self, k, v):
        self._note()
        dict.__setitem__(self, k, v)

    def __delitem__(self, k):
        self._note()
        dict.__delitem__(self, k)

    def pop(self, *a, **kw):
        self._note()
        return dict.pop(self, *a, **kw)

    def popitem(self):
        self._note()
        return dict.popitem(self)

    def clear(self):
        self._note()
        dict.clear(self)

    def update(self, *a, **kw):
        self._note()
        dict.update(self, *a, **kw)

    def setdefault(self, *a, **kw):
        self._note()
        return dict.setdefault(self, *a, **kw)


class _TrackedList(list):
    """list that reports in-place mutation to the monitor."""

    _ss_name = "?"
    _ss_attr = "?"

    def _note(self) -> None:
        _record(self._ss_name, self._ss_attr)

    def append(self, v):
        self._note()
        list.append(self, v)

    def extend(self, it):
        self._note()
        list.extend(self, it)

    def insert(self, i, v):
        self._note()
        list.insert(self, i, v)

    def pop(self, *a):
        self._note()
        return list.pop(self, *a)

    def remove(self, v):
        self._note()
        list.remove(self, v)

    def clear(self):
        self._note()
        list.clear(self)

    def sort(self, *a, **kw):
        self._note()
        list.sort(self, *a, **kw)

    def reverse(self):
        self._note()
        list.reverse(self)

    def __setitem__(self, i, v):
        self._note()
        list.__setitem__(self, i, v)

    def __delitem__(self, i):
        self._note()
        list.__delitem__(self, i)

    def __iadd__(self, it):
        self._note()
        list.extend(self, it)
        return self


def _wrap_container(name: str, attr: str, val: Any) -> Any:
    """Recording twin for a plain dict/list value; anything else passes
    through (attr rebinds are still tracked by the class patch)."""
    if type(val) is dict:
        w: Any = _TrackedDict(val)
    elif type(val) is list:
        w = _TrackedList(val)
    else:
        return val
    w._ss_name = name
    w._ss_attr = attr
    return w


def track_object(obj: Any, name: str,
                 attrs: Iterable[str] | None = None) -> Any:
    """Register ``obj`` for cross-thread write tracking under ``name``.
    ``attrs`` limits tracking to those attribute names (None = all).
    No-op (returns ``obj`` untouched) unless GRIDLLM_SANITIZE is on."""
    if not enabled():
        return obj
    cls = type(obj)
    if cls not in _patched:
        orig = cls.__setattr__

        def traced_setattr(self: Any, attr: str, value: Any,
                           _orig: Any = orig) -> None:
            ent = _tracked.get(id(self))
            if ent is not None:
                nm, only = ent
                if only is None or attr in only:
                    _record(nm, attr)
                    value = _wrap_container(nm, attr, value)
            _orig(self, attr, value)

        cls.__setattr__ = traced_setattr  # type: ignore[method-assign]
        _patched.add(cls)
    attr_set = frozenset(attrs) if attrs is not None else None
    oid = id(obj)
    _tracked[oid] = (name, attr_set)
    # wrap every tracked dict/list value that already exists — with
    # attrs=None ("all") that is everything currently on the instance
    wrap_attrs = (attr_set if attr_set is not None
                  else tuple(vars(obj)) if hasattr(obj, "__dict__") else ())
    for attr in wrap_attrs:
        cur = getattr(obj, attr, None)
        wrapped = _wrap_container(name, attr, cur)
        if wrapped is not cur:
            # direct install — wrapping is not a mutation and must not
            # seed the entry with the registering thread's lock set
            object.__setattr__(obj, attr, wrapped)
    try:
        # drop the registration when the object dies, so a recycled id()
        # cannot alias a new object onto stale tracking
        _reapers[oid] = weakref.ref(
            obj, lambda _r, oid=oid: (_tracked.pop(oid, None),
                                      _reapers.pop(oid, None)))
    except TypeError:
        pass  # not weakref-able: tracked for the process lifetime
    return obj


def violations() -> list[dict[str, Any]]:
    """Attributes written from ≥ 2 threads with no common lock across
    all of their writes — each with the first write site per thread."""
    with _mu:
        return [{
            "object": name,
            "attr": attr,
            "threads": len(e.threads),
            "writes": e.writes,
            "sites": sorted(e.threads.values()),
        } for (name, attr), e in sorted(_entries.items())
            if len(e.threads) > 1 and not e.common]


def report() -> dict[str, Any]:
    v = violations()
    with _mu:
        tracked = len(_tracked)
        observed = len(_entries)
    return {"tracked_objects": tracked, "observed_attrs": observed,
            "violations": v, "ok": not v}


def assert_clean() -> None:
    v = violations()
    if v:
        lines = [
            f"{x['object']}.{x['attr']}: {x['threads']} threads, "
            f"{x['writes']} writes, no common lock — first writes at "
            + "; ".join(x["sites"]) for x in v]
        raise SharedStateError(
            "cross-thread unguarded mutation of registered shared "
            "state:\n  " + "\n  ".join(lines))


def reset() -> None:
    """Forget observations and registrations (class patches stay, and
    miss on every untracked instance)."""
    with _mu:
        _entries.clear()
        _tracked.clear()
        _reapers.clear()


def snapshot() -> dict[str, Any]:
    """State capture for tests that reset the process-global monitor —
    the lockcheck snapshot/restore pattern: a sanitized session's
    end-of-run verdict must still cover what earlier suites recorded."""
    with _mu:
        return {"entries": dict(_entries), "tracked": dict(_tracked),
                "reapers": dict(_reapers)}


def restore(snap: dict[str, Any]) -> None:
    with _mu:
        _entries.update(snap["entries"])
        _tracked.update(snap["tracked"])
        _reapers.update(snap["reapers"])
