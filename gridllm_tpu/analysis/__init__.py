"""gridllm_tpu.analysis — repo-wide static invariant analyzer + runtime
sanitizers (ISSUE 8, extended by ISSUEs 13 and 14).

Static half: ``python -m gridllm_tpu.analysis`` runs 12 AST-based rules
(config-discipline, lock-discipline, dashboard-drift, jit-discipline,
span-pairing, metric-hygiene, channel-discipline, async-discipline,
fault-coverage, kernel-parity, dtype-discipline, host-sync-discipline)
over the repo and reports ``file:line`` findings in human or JSON form
(``--json`` includes per-rule wall time); ``--strict`` exits nonzero on
any finding and gates tier-1 CI.

Runtime half (all armed by ``GRIDLLM_SANITIZE=1``):
``analysis/lockcheck.py`` instruments ``threading.Lock``/``RLock``
during tests, builds the process lock-order graph, and fails on cycles
or unlocked ``PageAllocator`` mutation; ``analysis/statecheck.py``
tracks attribute writes on registered hot objects (scheduler job
tables, registry worker map, allocator state) keyed by thread and held
locks, and fails on cross-thread mutation with no common lock;
``analysis/numcheck.py`` shadow-executes sampled kernel dispatches
against their KERNELS-registry jnp references at per-op tolerances and
NaN/Inf-tripwires sampler logits and KV writes.
"""

from gridllm_tpu.analysis.core import (  # noqa: F401
    Finding,
    MetricReg,
    Repo,
    Rule,
    RULES,
    collect_metric_registrations,
    load_rules,
    rule,
    run,
)
