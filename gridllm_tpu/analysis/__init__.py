"""gridllm_tpu.analysis — repo-wide static invariant analyzer + runtime
lock-discipline sanitizer (ISSUE 8).

Static half: ``python -m gridllm_tpu.analysis`` runs AST-based rules
(config-discipline, lock-discipline, dashboard-drift, jit-discipline,
span-pairing, metric-hygiene) over the repo and reports ``file:line``
findings in human or JSON form; ``--strict`` exits nonzero on any
finding and gates tier-1 CI.

Runtime half: ``analysis/lockcheck.py`` (``GRIDLLM_SANITIZE=1``)
instruments ``threading.Lock``/``RLock`` during tests, builds the
process lock-order graph, and fails on cycles or unlocked
``PageAllocator`` mutation.
"""

from gridllm_tpu.analysis.core import (  # noqa: F401
    Finding,
    MetricReg,
    Repo,
    Rule,
    RULES,
    collect_metric_registrations,
    load_rules,
    rule,
    run,
)
