"""gridllm_tpu.analysis — repo-wide static invariant analyzer + runtime
sanitizers (ISSUE 8, extended by ISSUE 13).

Static half: ``python -m gridllm_tpu.analysis`` runs AST-based rules
(config-discipline, lock-discipline, dashboard-drift, jit-discipline,
span-pairing, metric-hygiene, channel-discipline, async-discipline,
fault-coverage) over the repo and reports ``file:line`` findings in
human or JSON form; ``--strict`` exits nonzero on any finding and gates
tier-1 CI.

Runtime half (both armed by ``GRIDLLM_SANITIZE=1``):
``analysis/lockcheck.py`` instruments ``threading.Lock``/``RLock``
during tests, builds the process lock-order graph, and fails on cycles
or unlocked ``PageAllocator`` mutation; ``analysis/statecheck.py``
tracks attribute writes on registered hot objects (scheduler job
tables, registry worker map, allocator state) keyed by thread and held
locks, and fails on cross-thread mutation with no common lock.
"""

from gridllm_tpu.analysis.core import (  # noqa: F401
    Finding,
    MetricReg,
    Repo,
    Rule,
    RULES,
    collect_metric_registrations,
    load_rules,
    rule,
    run,
)
