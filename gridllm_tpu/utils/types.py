"""Shared wire types for the scheduler/bus/worker protocol.

Reference analogue: server/src/types/index.ts:1-471 and
client/src/types/index.ts:1-145. Field names here ARE the wire contract
(JSON over the bus, and the HTTP API response surface), so they keep the
reference's camelCase on the bus protocol and Ollama's snake_case on the
HTTP surface. TPU additions (not in the reference, which treats workers as
opaque Ollama hosts): per-worker accelerator topology + model shard layout,
used for topology-aware scheduling (SURVEY.md §2.6, §7).
"""

from __future__ import annotations

import time
from enum import Enum
from typing import Any, Literal

from pydantic import BaseModel, ConfigDict, Field


def now_ms() -> int:
    return int(time.time() * 1000)


def iso_now() -> str:
    t = time.time()
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t)) + f".{int(t*1000)%1000:03d}Z"


class Priority(str, Enum):
    high = "high"
    medium = "medium"
    low = "low"

    @property
    def rank(self) -> int:
        return {"high": 0, "medium": 1, "low": 2}[self.value]


class _Model(BaseModel):
    model_config = ConfigDict(extra="allow", populate_by_name=True)


# ---------------------------------------------------------------------------
# Worker capability / status records (bus hash `workers`)
# ---------------------------------------------------------------------------

class SystemResources(_Model):
    """reference: server/src/types/index.ts:12-23 (SystemResources)."""

    cpuCores: int = 0
    totalMemoryMB: float = 0
    availableMemoryMB: float = 0
    cpuUsagePercent: float = 0
    memoryUsagePercent: float = 0
    diskSpaceGB: float = 0
    platform: str = ""
    architecture: str = ""
    # TPU additions (replace the reference's gpuMemoryMB/gpuUsagePercent)
    tpuChips: int = 0
    hbmTotalMB: float = 0
    hbmFreeMB: float = 0


class TpuTopology(_Model):
    """NEW (no reference analogue): accelerator topology of a worker group.

    A multi-host TPU slice registers as ONE logical worker; the scheduler
    routes by shard layout + topology (SURVEY.md §2.6 'TPU-native equivalent').
    """

    platform: str = "cpu"            # "tpu" | "cpu" | "gpu"
    numDevices: int = 1              # devices visible to this logical worker
    numHosts: int = 1
    meshShape: dict[str, int] = Field(default_factory=dict)  # e.g. {"data":1,"model":8}
    deviceKind: str = ""             # e.g. "TPU v5e"
    iciBandwidthGBps: float = 0.0


class ModelShardLayout(_Model):
    """NEW: how a served model is laid out on the worker's mesh."""

    name: str
    strategy: str = "replicated"     # replicated | tensor | expert | pipeline | hybrid
    meshAxes: dict[str, int] = Field(default_factory=dict)
    dtype: str = "bfloat16"
    maxSeqLen: int = 8192
    maxBatchSlots: int = 8


class ModelInfo(_Model):
    """Ollama-style model record (reference: OllamaModel, types/index.ts:25-38)."""

    name: str
    model: str | None = None
    size: int = 0
    digest: str = ""
    modified_at: str = ""
    details: dict[str, Any] | None = None


class NodeCapabilities(_Model):
    """reference: server/src/types/index.ts:2-10 (NodeCapabilities)."""

    workerId: str
    availableModels: list[ModelInfo] = Field(default_factory=list)
    systemResources: SystemResources | None = None
    performanceTier: Literal["high", "medium", "low"] = "medium"
    maxConcurrentTasks: int = 1
    supportedFormats: list[str] = Field(default_factory=lambda: ["json"])
    lastUpdated: str = Field(default_factory=iso_now)
    # TPU additions
    topology: TpuTopology | None = None
    shardLayouts: list[ModelShardLayout] = Field(default_factory=list)


class WorkerInfo(_Model):
    """reference: server/src/types/index.ts:41-50 (WorkerInfo)."""

    workerId: str
    capabilities: NodeCapabilities
    # "draining" (ISSUE 9): the worker is finishing/migrating its jobs
    # and must receive no new assignments; it keeps heartbeating, so the
    # liveness tiers leave it alone while the scheduler routes around it
    status: Literal["online", "offline", "busy", "error", "draining"] = "online"
    currentJobs: int = 0
    lastHeartbeat: float = Field(default_factory=time.time)
    registeredAt: float = Field(default_factory=time.time)
    totalJobsProcessed: int = 0
    connectionHealth: Literal["healthy", "degraded", "unhealthy"] = "healthy"
    # TPU addition (ISSUE 3): compact digest of prefix keys this worker
    # recently served — serving a request warms its engine's KV prefix
    # cache, so these approximate "prefixes cached here". Refreshed from
    # heartbeats; the scheduler scores cached-prefix overlap against a
    # job's metadata.prefixKey (prefix-affinity routing).
    cachedPrefixes: list[str] = Field(default_factory=list)
    # Disaggregated serving (ISSUE 7): the worker's advertised fleet role.
    # "unified" serves whole requests (today's behavior); "prefill"
    # workers take phase-1 placements and migrate finished KV pages out;
    # "decode" workers take the handoff and run generation from imported
    # pages. Placement is role-strict (scheduler._select_worker) — a
    # homogeneous unified fleet behaves exactly as before.
    role: Literal["unified", "prefill", "decode"] = "unified"
    # decode-slot headroom (open engine batch slots) from the latest
    # heartbeat — the decode-pool placement tiebreaker
    decodeSlotsFree: int = 0
    # host:port of the worker's health HTTP server, for the direct
    # worker-to-worker KV transfer fallback (large payloads)
    httpAddr: str = ""
    # per-model capacity headroom from the latest heartbeat (ISSUE 16):
    # {model: {"slotsFree", "slotsTotal", "kvPagesFree"}} — the demand
    # tracker behind /admin/capacity aggregates these across workers
    modelCapacity: dict[str, dict[str, int]] = Field(default_factory=dict)
    # Active fleet health (ISSUE 19): the health monitor's verdict for
    # this worker, replicated to every registry over health:state.
    # Distinct from `status` (the worker's OWN word about its lifecycle):
    # a quarantined worker may still report status=online while the
    # scheduler routes around it and drains it.
    healthState: Literal["online", "degraded", "quarantined",
                         "probation"] = "online"

    def model_names(self) -> list[str]:
        return [m.name for m in self.capabilities.availableModels]


# ---------------------------------------------------------------------------
# Jobs
# ---------------------------------------------------------------------------

class InferenceRequest(_Model):
    """reference: server/src/types/index.ts:64-93 (InferenceRequest).

    One job as it travels gateway → scheduler → bus → worker. `metadata`
    carries the orphan/retry audit trail exactly as the reference does
    (retryCount / orphaned / originalWorkerId / orphanedAt / requeueCount /
    requestType), because the failure machinery keys off it.
    """

    id: str
    model: str
    prompt: str | None = None
    stream: bool | None = None
    # chat path: structured messages survive end-to-end (fixes reference
    # defect SURVEY.md §2.8: /ollama/api/chat flattened messages to a prompt)
    messages: list[dict[str, Any]] | None = None
    tools: list[dict[str, Any]] | None = None
    format: str | dict[str, Any] | None = None
    # multimodal: base64 images carried to the worker (reference:
    # OllamaService.ts:197-226 / openai.ts:205-243 passthrough). Served
    # models without vision reject per-request at the engine, loudly.
    images: list[str] | None = None
    # embedding path
    input: str | list[str] | None = None
    truncate: bool | None = None
    # common
    options: dict[str, Any] = Field(default_factory=dict)
    priority: Priority = Priority.medium
    timeout: int = 300_000  # ms
    metadata: dict[str, Any] = Field(default_factory=dict)

    @property
    def request_type(self) -> str:
        return self.metadata.get("requestType", "inference")


class JobAssignment(_Model):
    """reference: server/src/types/index.ts:149-155 (JobAssignment)."""

    jobId: str
    workerId: str
    request: InferenceRequest
    assignedAt: float = Field(default_factory=time.time)
    timeout: int = 300_000  # ms


class InferenceResponse(_Model):
    """reference: server/src/types/index.ts:117-138 (InferenceResponse).

    Ollama-native response shape. Unlike the reference — which zeroes timing
    fields on its OpenAI-facade path (SURVEY.md §2.8) — the TPU engine
    measures real durations (nanoseconds, Ollama convention).
    """

    id: str
    model: str | None = None
    created_at: str | None = None
    response: str | None = None
    thinking: str | None = None
    message: dict[str, Any] | None = None  # chat responses
    done: bool = True
    done_reason: str | None = None
    context: list[int] | None = None
    embeddings: list[list[float]] | None = None
    embedding: list[float] | None = None
    total_duration: int | None = None
    load_duration: int | None = None
    prompt_eval_count: int | None = None
    prompt_eval_duration: int | None = None
    eval_count: int | None = None
    eval_duration: int | None = None
    system_fingerprint: str | None = None


class StreamChunk(_Model):
    """One streamed token frame on `job:stream:{id}`.

    reference: client/src/types/index.ts:70-74 (StreamResponse). TPU change:
    a frame may carry MULTIPLE tokens (`response` is the concatenated text)
    — the reference crossed Redis once per token (SURVEY.md §6), we batch.
    """

    id: str
    model: str | None = None
    created_at: str | None = None
    response: str = ""
    thinking: str | None = None
    message: dict[str, Any] | None = None
    done: bool = False
    done_reason: str | None = None
    eval_count: int | None = None
    # absolute char index of this frame's first char in the FULL response
    # text (ISSUE 9): lets the gateway trim any overlap between a dying
    # attempt's in-flight frames and the resumed attempt's re-emission,
    # so the client-observed stream is exactly-once. None on frames from
    # workers that don't track offsets (pre-ISSUE 9 compatibility).
    offset: int | None = None


class JobResult(_Model):
    """Payload on `job:result:{id}` / `job:completed` / `job:failed`."""

    jobId: str
    workerId: str
    success: bool
    response: InferenceResponse | None = None
    error: str | None = None
    # False → the failure is permanent for the whole cluster (e.g.
    # generation requested on an embedding-only model); the scheduler
    # skips the retry ladder and fails the job immediately
    retryable: bool = True
    # True → not a real attempt: the worker refused the assignment
    # (capacity race). The scheduler requeues WITHOUT consuming the retry
    # ladder — three racy over-assignments must not permanently fail a job
    # that never ran (round-1 VERDICT #8)
    nack: bool = False
    completedAt: float = Field(default_factory=time.time)
    processingTimeMs: float = 0
    # per-request cost attribution (ISSUE 16): tenant/model plus token,
    # device-second, KV-page-second, and migrated-byte tallies built by
    # the worker at finish (obs.usage.build_usage). The OWNING shard
    # folds this into its per-tenant ledger exactly once; absent on
    # failures, nacks, and pre-ISSUE 16 workers
    usage: dict[str, Any] | None = None
