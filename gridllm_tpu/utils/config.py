"""Env-driven configuration, validated fail-fast at load.

Reference analogue: dotenv + Joi schemas (server/src/config/index.ts:6-92,
client/src/config/index.ts:6-148). Same shape and defaults; pydantic replaces
Joi. TPU-specific knobs (mesh, dtype, KV cache) join the worker schema per
SURVEY.md §5.6.

Defaults preserved from the reference:
- server port 4000 (server/src/config/index.ts:10)
- workerHeartbeatTimeout 15000 ms (:24), workerCleanupInterval 5000 ms (:25)
- jobTimeout 600000 ms (:28), retryAttempts 3 / retryDelay 5000 ms (:29-30)
- maxConcurrentJobsPerWorker 1 (:31) — the TPU engine supersedes this with
  continuous batching, so the default here is per-engine slot count
- bus key prefix "GridLLM:" (:17)
- worker heartbeatInterval 5000 ms (client/src/config/index.ts:94)
"""

from __future__ import annotations

import dataclasses
import os
import uuid
from typing import Any, Literal

from pydantic import BaseModel, Field, ValidationError


# ---------------------------------------------------------------------------
# GRIDLLM_* environment registry (ISSUE 8)
#
# Every ``GRIDLLM_*`` variable the system reads is declared here ONCE with
# its default and a one-line description, and read ONLY through the typed
# accessors below. The config-discipline rule (gridllm_tpu/analysis/)
# enforces both halves statically: a direct ``os.environ`` read of a
# GRIDLLM_* name outside this module is a finding, and so is an accessor
# call for an unregistered name. The README "Configuration" table is
# cross-checked against this registry by the same rule, so docs cannot
# drift from code.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EnvVar:
    """One registered environment knob: the single source of truth for its
    default and documentation."""

    name: str
    default: str          # raw string form; "" means unset/empty default
    description: str


ENV_VARS: dict[str, EnvVar] = {}


def register_env(name: str, default: str, description: str) -> None:
    if name in ENV_VARS:
        # silent last-writer-wins would let two registrations (a bad
        # merge) disagree on the default with no signal anywhere — the
        # registry is single-source or it is nothing
        raise ValueError(f"duplicate register_env({name!r})")
    ENV_VARS[name] = EnvVar(name, default, description)


def _registered(name: str) -> EnvVar:
    var = ENV_VARS.get(name)
    if var is None:
        raise KeyError(
            f"unregistered env var {name!r}: declare it in "
            "gridllm_tpu/utils/config.py ENV_VARS (register_env) so the "
            "default and description live in one place"
        )
    return var


def env_raw(name: str) -> str | None:
    """The raw environment value, or None when unset. The name must be
    registered — callers with bespoke parsing start here."""
    _registered(name)
    return os.environ.get(name)


def env_str(name: str) -> str:
    var = _registered(name)
    raw = os.environ.get(name)
    return raw if raw is not None else var.default


def env_int(name: str) -> int:
    """Fail-fast: a set-but-malformed value raises (load_config turns that
    into a startup SystemExit) rather than silently serving the default —
    GRIDLLM_PROC_ID=two colliding with the real liaison process is exactly
    the failure mode a registry exists to prevent."""
    var = _registered(name)
    raw = os.environ.get(name)
    if not raw:
        return int(var.default or 0)
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid integer "
            f"(default: {var.default or 0})") from None


def env_float(name: str) -> float:
    var = _registered(name)
    raw = os.environ.get(name)
    if not raw:
        return float(var.default or 0.0)
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid number "
            f"(default: {var.default or 0.0})") from None


def env_int_lenient(name: str) -> int:
    """Like env_int, but a malformed value degrades to the registry
    default instead of raising — for reads on serving paths (engine step,
    KV migration mid-handoff) where an operator typo must fail the launch
    if anything, never a request already in flight."""
    try:
        return env_int(name)
    except ValueError:
        return int(_registered(name).default or 0)


def env_float_lenient(name: str) -> float:
    try:
        return env_float(name)
    except ValueError:
        return float(_registered(name).default or 0.0)


_FALSY = ("0", "off", "false", "no")
_TRUTHY = ("1", "on", "true", "yes")


def env_bool(name: str) -> bool:
    """One boolean grammar for every knob: the truthy/falsy sets below,
    anything else raises. The per-site parsers this replaced disagreed on
    unrecognized values (truthy-set sites read GRIDLLM_DISAGG=disable as
    off, falsy-set sites read it as on) — failing fast beats silently
    picking either side."""
    var = _registered(name)
    raw = os.environ.get(name)
    if not raw:
        return var.default.lower() in _TRUTHY
    low = raw.lower()
    if low in _TRUTHY:
        return True
    if low in _FALSY:
        return False
    raise ValueError(
        f"{name}={raw!r} is not a valid boolean "
        f"(truthy: {'/'.join(_TRUTHY)}; falsy: {'/'.join(_FALSY)})")


# -- registry: one entry per GRIDLLM_* knob, grouped by subsystem -----------

register_env("GRIDLLM_ENV", "development",
             "Deployment environment name (NODE_ENV also honored).")
register_env("GRIDLLM_LOG_LEVEL", "info",
             "Log level for the structured logger (debug/info/warning/error).")
register_env("GRIDLLM_BUS_URL", "",
             "Message-bus endpoint; empty = in-memory bus, "
             "resp://host:port = wire broker/Redis.")
register_env("GRIDLLM_BUS_ENDPOINTS", "",
             "Ordered comma list of resp://host:port broker endpoints "
             "(primary FIRST, warm standbys after) for client-driven "
             "failover with epoch fencing; empty = GRIDLLM_BUS_URL only.")
register_env("GRIDLLM_BUS_REJOIN_GRACE_MS", "10000",
             "After this process's bus session reconnects, hold worker-"
             "death verdicts and orphan sweeps this long (ms) so a "
             "broker bounce is not misread as a fleet-wide worker loss.")
register_env("GRIDLLM_BUS_RING_CAP", "512",
             "Broker replay-ring capacity per durable channel (messages)"
             " — the RESUME window a reconnecting subscriber can recover"
             " after an outage.")

# engine
register_env("GRIDLLM_MODELS", "",
             "Comma-separated model registry names this worker serves.")
register_env("GRIDLLM_CHECKPOINT_DIR", "",
             "Directory holding model checkpoints (safetensors layouts).")
register_env("GRIDLLM_DTYPE", "bfloat16",
             "Model compute/weight dtype.")
register_env("GRIDLLM_MAX_SEQ_LEN", "8192",
             "Maximum sequence length (prompt + generation) per request.")
register_env("GRIDLLM_MAX_BATCH_SLOTS", "8",
             "Continuous-batching slot count per engine.")
register_env("GRIDLLM_KV_PAGE_SIZE", "128",
             "Tokens per KV-cache page.")
register_env("GRIDLLM_STREAM_FLUSH_MS", "20",
             "Token-frame batching window for streamed responses (ms).")
register_env("GRIDLLM_PREFILL_BUCKETS", "512,1024,2048,4096,8192",
             "Comma-separated prefill padding buckets (tokens); prompts "
             "compile per bucket, not per length.")
register_env("GRIDLLM_MESH_SHAPE", "",
             "Device-mesh axes, e.g. \"tp:8\" or \"pp:2,tp:4\"; empty = "
             "single device.")
register_env("GRIDLLM_ALLOW_SYNTHETIC_WEIGHTS", "0",
             "Serve randomly initialized weights when no checkpoint is "
             "found (test/bench only).")
register_env("GRIDLLM_POOL_PAD", "0",
             "Force the lane-padded KV pool layout in Pallas interpret "
             "mode (kernel-coverage testing).")

# ops / kernels
register_env("GRIDLLM_PALLAS", "auto",
             "Pallas kernel policy: auto (TPU only), 1 (force on), "
             "0 (force off), interpret (CPU interpreter mode).")
register_env("GRIDLLM_RAGGED_ATTN", "1",
             "Unified ragged paged-attention kernel for prefill/decode/"
             "verify; 0 restores the legacy per-phase dispatchers.")
register_env("GRIDLLM_MOE_RAGGED", "auto",
             "MoE grouped-matmul via ragged_dot: auto (TPU only), "
             "1 (force on), 0 (dense fallback).")

# tiered KV cache (ISSUE 11): host-RAM spill + int8 KV pages
register_env("GRIDLLM_KV_HOST_BYTES", "0",
             "Host-RAM KV tier capacity in bytes: prefix-cache pages "
             "evicted from HBM spill here and page back in on "
             "match_prefix hits; 0 disables the tier.")
register_env("GRIDLLM_KV_SPILL_INT8", "1",
             "Quantize fp16/bf16 KV pages to int8 (scale-per-page) on "
             "host-tier spill, halving spill bytes; 0 spills raw bytes "
             "(lossless — restored streams byte-identical).")
register_env("GRIDLLM_KV_INT8", "0",
             "Resident int8 KV pool (per-row scales, dequant epilogue in "
             "the attention read path): halves KV HBM at a bounded "
             "accuracy cost; 1 enables.")
register_env("GRIDLLM_PREEMPT_AFTER_MS", "0",
             "Scheduler preemption: a queued higher-priority generation "
             "unplaceable for this long triggers suspend-to-host of one "
             "lower-priority running job; 0 disables preemption.")

# prefix caching
register_env("GRIDLLM_PREFIX_CACHE", "1",
             "Automatic prefix caching of completed requests' KV pages; "
             "0 disables.")
register_env("GRIDLLM_PREFIX_CACHE_PAGES", "-1",
             "Reuse-LRU capacity in pages; -1 = unbounded (whole pool), "
             "0 = off.")
register_env("GRIDLLM_PREFIX_AFFINITY_WEIGHT", "0.25",
             "Load-score bonus for workers whose heartbeat digest holds "
             "the request's prefix key; 0 disables affinity routing.")

# speculative decoding
register_env("GRIDLLM_SPEC_DECODE", "1",
             "Speculative decoding (n-gram drafting + batched "
             "verification); 0 disables.")
register_env("GRIDLLM_SPEC_K", "4",
             "Speculation depth: drafted tokens per slot per verify step "
             "(static per process); 0 disables.")
register_env("GRIDLLM_SPEC_DRAFTER", "ngram",
             "Drafter implementation (\"ngram\" is the phase-1 option).")
register_env("GRIDLLM_SPEC_NGRAM_MAX", "4",
             "Longest n-gram the prompt-lookup drafter matches on.")
register_env("GRIDLLM_SPEC_NGRAM_MIN", "1",
             "Shortest n-gram the prompt-lookup drafter falls back to.")
register_env("GRIDLLM_SPEC_LOOKBACK", "0",
             "Drafter match window over the slot history in tokens; "
             "0 = unbounded.")
register_env("GRIDLLM_SPEC_DRAFT_MODEL", "",
             "Registered config name of a tiny same-tokenizer draft model "
             "for model-based tree drafting; empty keeps n-gram drafting.")
register_env("GRIDLLM_SPEC_DRAFT_CHECKPOINT", "",
             "Checkpoint dir for the draft model; empty = fresh "
             "PRNGKey(0) init (test/bench path).")
register_env("GRIDLLM_SPEC_TREE_WIDTH", "2",
             "Draft-tree sibling fan-out at depth 1 (tree node budget is "
             "1 + K + width - 1); 1 = pure chain.")
register_env("GRIDLLM_SPEC_DRAFT_INGEST", "64",
             "Fixed catch-up chunk width (tokens) of the draft model's "
             "context-ingest forward.")

# multi-host SPMD
register_env("GRIDLLM_COORD_ADDR", "",
             "host:port of process 0 (jax distributed coordinator).")
register_env("GRIDLLM_NUM_PROCS", "1",
             "Total processes in the worker slice.")
register_env("GRIDLLM_PROC_ID", "0",
             "This process's id in the slice (0 = liaison).")

# scheduler / gateway / worker roles
register_env("GRIDLLM_DISAGG", "1",
             "Two-phase prefill/decode placement on split fleets; "
             "0 forces whole-request placement.")
register_env("GRIDLLM_WORKER_ROLE", "unified",
             "Fleet role of this worker: unified, prefill, or decode.")
register_env("GRIDLLM_WORKER_ADVERTISE_ADDR", "",
             "host:port other workers reach this worker's health server "
             "at (direct KV-transfer fallback); empty = 127.0.0.1:port.")
register_env("GRIDLLM_ENFORCE_KEEP_ALIVE", "0",
             "Unload models whose keep_alive window lapses (Ollama "
             "semantics); off by default — TPU reloads cost minutes.")

# KV migration (disaggregated serving)
register_env("GRIDLLM_KVX_CHUNK_BYTES", "262144",
             "KV-migration chunk size on the bus path (bytes).")
register_env("GRIDLLM_KVX_WINDOW", "8",
             "KV-migration chunks in flight before awaiting receiver "
             "progress.")
register_env("GRIDLLM_KVX_TIMEOUT_MS", "15000",
             "End-to-end KV-transfer deadline (ms).")
register_env("GRIDLLM_KVX_HTTP_BYTES", "8388608",
             "Payload size beyond which migration uses one direct "
             "worker-to-worker HTTP POST instead of bus chunks.")

# observability: SLO / watchdog / flight recorder
register_env("GRIDLLM_SLO_ENABLED", "1",
             "SLO engine (attainment, burn rate, goodput); 0 disables.")
register_env("GRIDLLM_SLO_CLASSES", "",
             "JSON object replacing the default per-class objective table "
             "({class: {ttft_ms, itl_ms, e2e_ms, target}}).")
register_env("GRIDLLM_SLO_WINDOWS", "",
             "Comma list of burn-rate window seconds (default 300,3600).")
register_env("GRIDLLM_WATCHDOG_ENABLED", "1",
             "Per-phase hang watchdog; 0 disables.")
register_env("GRIDLLM_WATCHDOG_INTERVAL", "1000",
             "Watchdog sweep interval (ms).")
register_env("GRIDLLM_WATCHDOG_QUEUE_DEADLINE", "120000",
             "Queue-phase hang deadline (ms).")
register_env("GRIDLLM_WATCHDOG_DISPATCH_DEADLINE", "60000",
             "Dispatch-phase hang deadline (ms).")
register_env("GRIDLLM_WATCHDOG_PREFILL_DEADLINE", "240000",
             "Prefill-phase hang deadline (ms).")
register_env("GRIDLLM_WATCHDOG_DECODE_STALL", "60000",
             "Decode-step stall deadline after the first token (ms).")
register_env("GRIDLLM_WATCHDOG_REQUEUE", "1",
             "Cancel + front-requeue jobs the watchdog catches hung; "
             "0 = diagnose only.")
register_env("GRIDLLM_WATCHDOG_PROFILE_S", "0",
             "Auto jax.profiler capture length on decode-step hangs "
             "(seconds); 0 disables (stop-flush starves heartbeats).")
register_env("GRIDLLM_FLIGHTREC_CAPACITY", "256",
             "Flight-recorder ring capacity per subsystem.")

# observability: fleet timeline & incident forensics (ISSUE 17)
register_env("GRIDLLM_TIMELINE", "1",
             "Fleet-wide causal timeline: arm the HLC-stamped event "
             "publisher (and, on control-plane members, the store + "
             "incident collector behind /admin/timeline and "
             "/admin/incidents). 0 disarms all of it.")
register_env("GRIDLLM_TIMELINE_QUEUE", "2048",
             "Bounded timeline publisher queue (events); overflow drops "
             "the OLDEST events and counts them in "
             "gridllm_timeline_dropped_events_total — emitters never "
             "block.")
register_env("GRIDLLM_TIMELINE_FLUSH_MS", "200",
             "Timeline publisher flush interval (ms): queued events "
             "batch onto one obs:event message per flush.")
register_env("GRIDLLM_TIMELINE_BATCH", "256",
             "Max events per obs:event batch message.")
register_env("GRIDLLM_TIMELINE_STORE", "4096",
             "TimelineStore global event ring capacity (per member "
             "running a store).")
register_env("GRIDLLM_TIMELINE_REQUESTS", "512",
             "TimelineStore per-request index: max distinct request ids "
             "(LRU).")
register_env("GRIDLLM_TIMELINE_INCIDENT_WINDOW_MS", "5000",
             "Causal window (± ms around the trigger event) an incident "
             "report snapshots from the fleet timeline.")
register_env("GRIDLLM_TIMELINE_INCIDENTS", "32",
             "Max retained incident reports (oldest evicted).")

# observability: usage attribution / capacity signals
register_env("GRIDLLM_TENANT_HEADER", "X-GridLLM-Tenant",
             "HTTP header the gateway reads the tenant id from; falls "
             "back to a hash of the Authorization bearer, else "
             "'anonymous'.")
register_env("GRIDLLM_TENANT_LRU", "64",
             "Max distinct tenant label values per registry; overflow "
             "tenants are folded into the 'other' bucket.")
register_env("GRIDLLM_CAPACITY_EWMA_HALFLIFE_S", "60",
             "Half-life (seconds) of the per-model arrival/service rate "
             "and wait-time EWMAs behind /admin/capacity.")

# observability: active fleet health (ISSUE 19) — canary prober + detector
register_env("GRIDLLM_PROBE_INTERVAL_MS", "0",
             "Canary probe cadence per scheduler shard (ms between "
             "rounds); each round probes one (worker, model) pair "
             "round-robin. 0 disables the prober.")
register_env("GRIDLLM_PROBE_CONCURRENCY", "1",
             "Max canary probes in flight at once per shard (rate bound: "
             "a slow fleet must never accumulate probe backlog).")
register_env("GRIDLLM_PROBE_TIMEOUT_MS", "15000",
             "Per-probe timeout (ms); a timed-out canary counts as a "
             "failed round for the worker's health verdict.")
register_env("GRIDLLM_PROBE_TOKENS", "8",
             "Tokens each canary generates (greedy, fixed seed) — the "
             "byte-determinism surface the golden hash covers.")
register_env("GRIDLLM_HEALTH_EWMA_HALFLIFE_S", "60",
             "Half-life (seconds) of the per-worker baseline EWMAs "
             "(canary e2e latency, decode ITL, heartbeat gap).")
register_env("GRIDLLM_HEALTH_Z_THRESHOLD", "3.0",
             "z-score above which a baseline observation counts as a "
             "regression strike against its worker.")
register_env("GRIDLLM_HEALTH_MIN_SAMPLES", "5",
             "Baseline observations required before z-score judgments "
             "begin (warmup; earlier observations only train the EWMA).")
register_env("GRIDLLM_HEALTH_DEGRADE_STRIKES", "2",
             "Consecutive regression strikes that move an online worker "
             "to degraded (placement penalty applied).")
register_env("GRIDLLM_HEALTH_QUARANTINE_STRIKES", "3",
             "Consecutive strikes while degraded that quarantine the "
             "worker (drained via the graceful-drain path).")
register_env("GRIDLLM_HEALTH_PROBATION_PASSES", "2",
             "Clean canary rounds a probation (or degraded) worker needs "
             "to rejoin the online pool.")
register_env("GRIDLLM_HEALTH_DEGRADED_PENALTY", "0.5",
             "Load-score penalty _select_worker adds to degraded/"
             "probation workers (same scale as the proportional load "
             "term; mirrors prefix_affinity_weight).")

# elastic serving (ISSUE 20) — snapshot tier, compile cache, placement
register_env("GRIDLLM_WEIGHT_SNAPSHOT_BYTES", "0",
             "Host-RAM weight snapshot tier capacity (bytes). Unloading "
             "a model parks its device params as host arrays keyed by "
             "checkpoint identity; a later load restores via host-to-"
             "device transfer instead of re-reading the checkpoint. "
             "LRU-evicted past capacity; 0 disables the tier.")
register_env("GRIDLLM_COMPILE_CACHE_DIR", "",
             "Persistent XLA compilation-cache directory (wired to "
             "jax_compilation_cache_dir at engine construction). A "
             "swapped-in model reuses compiles from any prior process "
             "that warmed the same shapes. Empty disables.")
register_env("GRIDLLM_PREWARM_COMPILES", "0",
             "When 1, a freshly loaded engine runs a one-token greedy "
             "prewarm request before serving, compiling the smallest "
             "prefill bucket and the decode step so the first real "
             "request skips warmup compiles (with the compile cache "
             "this is a disk hit, not an XLA compile).")
register_env("GRIDLLM_PLACEMENT_INTERVAL_MS", "0",
             "Model-placement controller cadence per scheduler shard "
             "(ms between ticks). Each tick compares per-model demand "
             "(queue depth, scale hints) against resident replicas and "
             "issues load/unload admin ops to live workers. 0 disables "
             "the controller (static placement).")
register_env("GRIDLLM_MODEL_IDLE_TTL_MS", "0",
             "Idle time (ms, no queued/active work and no arrivals) "
             "after which the placement controller unloads a model's "
             "replicas above its min-replica floor, releasing slots and "
             "HBM. 0 disables idle unload (models stay resident).")
register_env("GRIDLLM_SWAP_COOLDOWN_MS", "10000",
             "Hysteresis: minimum gap (ms) between placement actions "
             "for the same model, so demand flapping around a threshold "
             "cannot thrash load/unload cycles.")
register_env("GRIDLLM_MODEL_FLOORS", "",
             "Comma-separated model=N min-replica floors (SLO classes): "
             "the placement controller never drops a listed model below "
             "N replicas, and restores it toward N when under.")

# observability: perf introspection
register_env("GRIDLLM_RECOMPILE_BUDGET", "4",
             "Steady-state recompiles tolerated per window before a "
             "recompile-storm diagnosis.")
register_env("GRIDLLM_RECOMPILE_WINDOW", "60",
             "Recompile-storm budget window (seconds).")
register_env("GRIDLLM_PROFILE_DIR", "",
             "jax.profiler artifact root; empty = /tmp/gridllm-profiles.")
register_env("GRIDLLM_PROFILE_KEEP", "4",
             "Profiler captures kept before the oldest are pruned.")

# fault tolerance (ISSUE 9): drain / resume / retry shaping / deadlines
register_env("GRIDLLM_DRAIN_BUDGET_MS", "5000",
             "Graceful-drain budget: how long a draining worker lets "
             "in-flight jobs finish before live-migrating the rest (ms).")
register_env("GRIDLLM_RESUME_SNAPSHOT_TOKENS", "8",
             "Publish a decode-state resume snapshot every N generated "
             "tokens (crash-resume watermark); 0 disables snapshots.")
register_env("GRIDLLM_RETRY_BACKOFF_MAX_MS", "60000",
             "Cap for the retry ladder's exponential backoff (full "
             "jitter; base is the retry delay).")
register_env("GRIDLLM_RETRY_BUDGET_PER_MIN", "120",
             "Fleet-wide retry budget (token bucket, retries/min): when "
             "burning, further retries shed to immediate failure with "
             "retry_budget_exhausted; 0 = unlimited.")
register_env("GRIDLLM_REQUEST_DEADLINE_MS", "0",
             "Queued-job deadline from submission (ms): jobs still "
             "queued past it are shed with deadline_exceeded (HTTP 504);"
             " 0 disables.")
register_env("GRIDLLM_REQUEST_DEADLINE_CLASSES", "",
             "JSON object of per-SLO-class deadline overrides (ms), e.g."
             " {\"interactive\": 30000, \"batch\": 600000}.")

# deterministic fault injection (ISSUE 9, faults.py)
register_env("GRIDLLM_FAULT_SPEC", "",
             "Deterministic fault-injection spec: comma list of "
             "site=probability, site=@N (Nth call), or site=@N+ (from "
             "the Nth call); empty disables.")
register_env("GRIDLLM_FAULT_SEED", "0",
             "Seed for the per-site fault-injection RNGs; the decision "
             "sequence is a pure function of (seed, site, call #).")

# scaled control plane (ISSUE 15): sharded schedulers + gateway replicas
register_env("GRIDLLM_CONTROLPLANE", "local",
             "Control-plane mode: local (scheduler in-process, the "
             "default single-box layout) or gateway (stateless replica "
             "that publishes submissions to scheduler shards over the "
             "bus; run shards with python -m gridllm_tpu.controlplane).")
register_env("GRIDLLM_CONTROLPLANE_ID", "",
             "Stable member id of this control-plane process (gateway "
             "replica or scheduler shard); empty = generated cp-<hex>.")
register_env("GRIDLLM_SHARD_COUNT", "1",
             "Scheduler shard count M: the job-id space is partitioned "
             "deterministically over M shards (every member must agree).")
register_env("GRIDLLM_SHARD_ID", "0",
             "Home shard index of this scheduler-shard process (0..M-1);"
             " the shard also adopts orphaned partitions whose lease "
             "expires.")
register_env("GRIDLLM_SHARD_LEASE_TTL_MS", "6000",
             "Shard-ownership lease TTL (ms): a shard silent past this "
             "is presumed dead and its partition is adopted (epoch "
             "bump) by a surviving shard.")
register_env("GRIDLLM_SHARD_RENEW_MS", "2000",
             "Shard lease renew/sweep interval (ms); must be well under "
             "the lease TTL.")
register_env("GRIDLLM_SHARD_STATUS_MS", "2000",
             "Control-plane status-envelope publish interval (ms) — "
             "feeds the gateway replicas' fleet-wide /metrics, "
             "/admin/slo, and /health/workers aggregation.")
register_env("GRIDLLM_SHARD_HEALTH_PORT", "4100",
             "HTTP port a scheduler-shard process serves /metrics, "
             "/admin/slo, and /admin/dump on; 0 disables the listener.")
register_env("GRIDLLM_RATELIMIT_SCOPE", "replica",
             "Gateway rate-limit bucket scope: replica (per-process "
             "buckets — N replicas multiply every limit by N) or fleet "
             "(bucket state shared through the bus so the limit is "
             "fleet-wide).")

# static analysis / sanitizers (ISSUE 8)
register_env("GRIDLLM_ENDPOINT", "http://localhost:4000",
             "Gateway endpoint the integration differential harness "
             "drives (tests/integration).")
register_env("GRIDLLM_SANITIZE", "0",
             "Runtime lock-discipline sanitizer: instrument Lock/RLock "
             "acquires, build the lock-order graph, fail tests on cycles "
             "or unlocked allocator mutation.")
register_env("GRIDLLM_NUMCHECK_SAMPLE", "0.05",
             "Numerics sanitizer (on the GRIDLLM_SANITIZE switch): "
             "fraction of kernel dispatches shadow-executed against their "
             "jnp reference at the KERNELS-registry tolerance (1.0 = every "
             "dispatch; CI numcheck-smoke forces 1.0).")
register_env("GRIDLLM_NUMCHECK_SEED", "0",
             "Seed for the numerics sanitizer's per-op sampling streams; "
             "decisions are a pure function of (seed, op, trace #).")


def _env(name: str, default: Any) -> Any:
    raw = os.environ.get(name)
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


class BusConfig(BaseModel):
    """reference: redis block of server/src/config/index.ts:12-18."""

    url: str = ""                      # "" → in-memory bus; "resp://host:port" → wire
    host: str = "localhost"
    port: int = 6379
    password: str | None = None
    db: int = 0
    key_prefix: str = "GridLLM:"
    # Bus HA (ISSUE 10): ordered broker endpoint list, primary first —
    # clients walk it on every (re)connect, promote the first reachable
    # standby only after every earlier endpoint failed, and fence off
    # resurrected stale primaries by epoch. Empty = url only.
    endpoints: list[str] = Field(default_factory=list)


class SchedulerConfig(BaseModel):
    """reference: performance/scheduling block, server/src/config/index.ts:22-33."""

    worker_heartbeat_timeout_ms: int = Field(15_000, gt=0)
    worker_cleanup_interval_ms: int = Field(5_000, gt=0)
    connection_monitor_interval_ms: int = Field(5_000, gt=0)
    quick_disconnect_window_ms: int = Field(15_000, gt=0)
    orphan_assign_threshold_ms: int = Field(10_000, gt=0)
    job_timeout_ms: int = Field(600_000, gt=0)
    retry_attempts: int = Field(3, ge=0)
    retry_delay_ms: int = Field(5_000, ge=0)
    # Retry shaping (ISSUE 9): retry_delay_ms is the BASE of a capped
    # exponential backoff with full jitter (delay ~ U[0, min(cap,
    # base·2^attempt)]), and the fleet-wide retry budget is a token
    # bucket — when a degraded fleet is burning retries faster than the
    # budget refills, further retries shed to immediate failure with
    # ``retry_budget_exhausted`` instead of melting the fleet.
    retry_backoff_max_ms: int = Field(60_000, ge=0)
    retry_budget_per_min: float = Field(120, ge=0)
    # Per-class request deadlines (ISSUE 9): a job still QUEUED past its
    # deadline (measured from first submission) is shed with
    # ``deadline_exceeded`` (the gateway maps it to HTTP 504) instead of
    # occupying the queue. 0 disables; the class dict overrides per
    # SLO class (obs classify_request).
    request_deadline_ms: int = Field(0, ge=0)
    request_deadline_classes: dict[str, int] = Field(default_factory=dict)
    # Partition-aware liveness (ISSUE 10): while this process's own bus
    # session is degraded, the registry suspends worker-death verdicts
    # and the scheduler defers orphan sweeps; both stay held this long
    # after the session rejoins so heartbeats published during the
    # outage can land (the RESUME replay) before anyone is pronounced
    # dead. Without this, a 10-second broker restart triggers a mass
    # orphan-requeue storm of perfectly healthy jobs.
    bus_rejoin_grace_ms: int = Field(10_000, ge=0)
    # Preemption-based priority (ISSUE 11): when a queued generation of a
    # strictly higher priority class has been unplaceable for this long
    # (ms) and a lower-priority job is running on a worker serving its
    # model, the scheduler asks that worker to suspend the job to the
    # host KV tier (``job_preempt``); the victim requeues at the BACK of
    # its own priority class with its resume watermark and pages back in
    # from host when pressure clears. 0 (default) disables preemption.
    preempt_after_ms: int = Field(0, ge=0)
    # capacity NACKs requeue without consuming the retry ladder, but only
    # this many times — a nack storm then falls through to the real ladder
    max_nacks: int = Field(25, ge=0)
    max_concurrent_jobs_per_worker: int = Field(1, ge=1)
    # TPU change: the reference polled a 1 s tick (JobScheduler.ts:128-135);
    # we dispatch event-driven, with this tick only as a fallback sweep.
    sweep_interval_ms: int = Field(1_000, gt=0)
    # Prefix-affinity routing (ISSUE 3): a worker whose heartbeat digest
    # contains the job's prefixKey gets this subtracted from its
    # proportional-load score. Affinity never overrides the load cap
    # (candidates are pre-filtered by availability) — it breaks ties and
    # outweighs load differences up to this fraction, so a hot worker
    # still sheds. 0 disables the term.
    prefix_affinity_weight: float = Field(0.25, ge=0)
    # Disaggregated prefill/decode serving (ISSUE 7): when the fleet has
    # BOTH a prefill pool and a decode pool for a model, generation jobs
    # get two-phase placement (prefill worker + planned decode handoff
    # with KV-page migration). Default on — with a homogeneous unified
    # fleet there are no pools, so nothing changes. GRIDLLM_DISAGG=0
    # forces whole-request placement even on a split fleet.
    disagg_enabled: bool = True


class GatewayConfig(BaseModel):
    """reference: server block, server/src/config/index.ts:8-11, 38-43."""

    host: str = "0.0.0.0"
    port: int = 4000
    max_body_bytes: int = 10 * 1024 * 1024  # express json limit 10mb (index.ts:47)
    rate_limit_window_ms: int = 900_000
    rate_limit_max_requests: int = 100
    rate_limit_enabled: bool = True
    # Multi-replica rate limiting (ISSUE 15): "replica" keeps the
    # original per-process fixed-window buckets — N gateway replicas
    # therefore multiply every limit by N, which is the documented
    # semantics of this scope. "fleet" shares bucket state through the
    # bus (one read-modify-write per counted request) so the limit is
    # fleet-wide regardless of which replica serves the request.
    rate_limit_scope: Literal["replica", "fleet"] = "replica"
    default_request_timeout_ms: int = 300_000
    # Ollama-exact idle residency: unload a model when its keep_alive
    # window passes with no requests (Ollama defaults to 5m). OFF by
    # default — a TPU reload of a 70B checkpoint costs minutes, so the
    # default here keeps weights resident and honors keep_alive only as
    # the advertised /api/ps expiry. GRIDLLM_ENFORCE_KEEP_ALIVE=1 opts in.
    enforce_keep_alive: bool = False


class EngineConfig(BaseModel):
    """TPU engine knobs — NEW (replaces the reference's ollama block,
    client/src/config/index.ts:82-89)."""

    models: str = ""                   # comma-separated model specs to serve
    checkpoint_dir: str = ""
    dtype: str = "bfloat16"
    max_seq_len: int = 8192
    max_batch_slots: int = 8           # continuous-batching slot count
    prefill_buckets: str = "512,1024,2048,4096,8192"
    kv_page_size: int = 128
    stream_flush_ms: int = 20          # token-frame batching window
    # mesh axes (parallel/mesh.py): e.g. "tp:8", "pp:2,tp:4", "dp:2,tp:4";
    # "" → single device
    mesh_shape: str = ""
    decode_steps_per_host_sync: int = 8


class WorkerConfig(BaseModel):
    """reference: client/src/config/index.ts:6-148."""

    worker_id: str = Field(default_factory=lambda: f"worker-{uuid.uuid4().hex[:12]}")
    host: str = "0.0.0.0"
    port: int = 3000
    heartbeat_interval_ms: int = Field(5_000, gt=0)
    resource_monitor_interval_ms: int = Field(10_000, gt=0)
    max_reconnect_attempts: int = 10
    max_concurrent_tasks: int = 1      # superseded by engine.max_batch_slots when engine present
    performance_tier: str = "medium"
    # Disaggregated serving (ISSUE 7): this worker's fleet role
    # (GRIDLLM_WORKER_ROLE). "prefill" workers take phase-1 placements
    # and export KV; "decode" workers admit from imported pages;
    # "unified" (default) serves whole requests as before.
    role: Literal["unified", "prefill", "decode"] = "unified"
    # host:port other workers can reach this worker's health HTTP server
    # at (GRIDLLM_WORKER_ADVERTISE_ADDR) — the direct worker-to-worker
    # KV-transfer fallback path. "" → 127.0.0.1:{port} (single-host
    # deployments and tests).
    advertise_addr: str = ""
    # Graceful drain (ISSUE 9): on SIGTERM / POST /admin/drain, how long
    # in-flight jobs get to finish before the worker live-migrates the
    # remaining decodes (suspend + KV export + job:drain handoff).
    drain_budget_ms: int = Field(5_000, ge=0)


class SLOClassConfig(BaseModel):
    """Latency objectives for one request class (ISSUE 2). ``None`` means
    the objective does not apply to the class (embeddings have no ITL)."""

    ttft_ms: float | None = None       # submit → first streamed token
    itl_ms: float | None = None        # mean inter-token latency
    e2e_ms: float | None = None        # submit → final result
    target: float = Field(0.99, gt=0, le=1)  # attainment objective


def default_slo_classes() -> dict[str, SLOClassConfig]:
    """Request classes and their default objectives. Classification
    (obs/slo.py classify_request): streaming generation is interactive,
    non-streaming generation is batch, embeddings are their own class."""
    return {
        "interactive": SLOClassConfig(ttft_ms=2_000, itl_ms=200,
                                      e2e_ms=120_000, target=0.99),
        "batch": SLOClassConfig(e2e_ms=300_000, target=0.95),
        "embedding": SLOClassConfig(e2e_ms=10_000, target=0.99),
    }


class SLOConfig(BaseModel):
    """SLO engine knobs (obs/slo.py). ``GRIDLLM_SLO_CLASSES`` may carry a
    JSON object {class: {ttft_ms, itl_ms, e2e_ms, target}} that REPLACES
    the defaults wholesale (partial per-class merges would make the
    effective objective ambiguous)."""

    enabled: bool = True
    classes: dict[str, SLOClassConfig] = Field(
        default_factory=default_slo_classes)
    # burn-rate windows (seconds): one fast window for paging, one slow
    # window for ticket-level alerts (multi-window burn-rate alerting)
    windows_s: list[int] = Field(default_factory=lambda: [300, 3600])


class WatchdogConfig(BaseModel):
    """Hang watchdog (obs/watchdog.py): per-phase deadlines after which a
    request is flagged as wedged. Defaults are generous — first-compile on
    a cold worker is minutes, and a false hang requeue wastes real work."""

    enabled: bool = True
    interval_ms: int = Field(1_000, gt=0)
    # open queue.wait span older than this → phase "queue"
    queue_deadline_ms: int = Field(120_000, gt=0)
    # assigned, no stream frame yet → "dispatch" past this ...
    dispatch_deadline_ms: int = Field(60_000, gt=0)
    # ... and "prefill" past this (gateway-side the two are only
    # distinguishable by age; worker-side engine probes refine it)
    prefill_deadline_ms: int = Field(240_000, gt=0)
    # first token seen but no frame for this long → "decode-step"
    decode_stall_ms: int = Field(60_000, gt=0)
    # abort + requeue hung ACTIVE jobs (reason "hang"); queue-phase hangs
    # are diagnosis-only (there is nothing to requeue)
    requeue: bool = True
    # on a decode-step hang, auto-start a short jax.profiler capture
    # (obs/perf.py) so the trace covers the wedge itself; 0 (default)
    # disables — OPT-IN via GRIDLLM_WATCHDOG_PROFILE_S because the
    # capture's stop-flush serializes profiler data while holding the
    # GIL for seconds, which can starve heartbeats/streams mid-incident
    # and turn a surgical hang-requeue into a worker-crash orphaning.
    # Only meaningful when the engine runs in THIS process (bench,
    # single-process deploys) — split deployments use the worker health
    # port's POST /admin/profile instead.
    profile_on_hang_s: float = Field(0.0, ge=0)


class ControlPlaneConfig(BaseModel):
    """Horizontally scaled control plane (ISSUE 15): N stateless gateway
    replicas in front of M scheduler shards, each owning a deterministic
    partition of the job-id space via bus-backed leases fenced by epoch.

    ``mode`` selects what THIS process is: ``local`` (default) keeps the
    scheduler in the gateway process — exactly the pre-ISSUE-15 layout;
    ``gateway`` runs a stateless replica that publishes submissions on
    ``ctrl:submit`` and rebuilds streaming state from the durable
    result/stream channels (any replica can serve any request). Shard
    processes run ``python -m gridllm_tpu.controlplane`` and are
    configured by ``shard_id``/``num_shards`` plus the lease timers."""

    mode: Literal["local", "gateway"] = "local"
    member_id: str = ""                # "" → generated cp-<hex>
    num_shards: int = Field(1, ge=1)
    shard_id: int = Field(0, ge=0)
    lease_ttl_ms: int = Field(6_000, gt=0)
    renew_interval_ms: int = Field(2_000, gt=0)
    status_interval_ms: int = Field(2_000, gt=0)
    shard_health_port: int = Field(4_100, ge=0)


class TimelineConfig(BaseModel):
    """Fleet timeline & incident forensics (ISSUE 17): the HLC-stamped
    event publisher every member arms, plus the store/collector sizes on
    members that serve /admin/timeline + /admin/incidents."""

    enabled: bool = True
    queue_capacity: int = Field(2_048, gt=0)
    flush_ms: float = Field(200.0, gt=0)
    batch_max: int = Field(256, gt=0)
    store_capacity: int = Field(4_096, gt=0)
    store_requests: int = Field(512, gt=0)
    incident_window_ms: float = Field(5_000.0, gt=0)
    max_incidents: int = Field(32, gt=0)


class ObsConfig(BaseModel):
    """Interpretation-layer observability (ISSUE 2): SLO engine, hang
    watchdog, flight recorder."""

    slo: SLOConfig = Field(default_factory=SLOConfig)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)
    # per-subsystem ring capacity of the flight recorder
    flightrec_capacity: int = Field(256, gt=0)
    # fleet timeline & incident forensics (ISSUE 17)
    timeline: TimelineConfig = Field(default_factory=TimelineConfig)


class Config(BaseModel):
    env: str = "development"
    bus: BusConfig = Field(default_factory=BusConfig)
    scheduler: SchedulerConfig = Field(default_factory=SchedulerConfig)
    gateway: GatewayConfig = Field(default_factory=GatewayConfig)
    worker: WorkerConfig = Field(default_factory=WorkerConfig)
    engine: EngineConfig = Field(default_factory=EngineConfig)
    obs: ObsConfig = Field(default_factory=ObsConfig)
    controlplane: ControlPlaneConfig = Field(
        default_factory=ControlPlaneConfig)


def _slo_config_from_env() -> SLOConfig:
    """SLO objectives from the environment. ``GRIDLLM_SLO_CLASSES`` is a
    JSON object replacing the default class table; ``GRIDLLM_SLO_WINDOWS``
    is a comma list of burn-rate window seconds."""
    import json

    kw: dict[str, Any] = {"enabled": env_bool("GRIDLLM_SLO_ENABLED")}
    raw = env_raw("GRIDLLM_SLO_CLASSES")
    if raw:
        kw["classes"] = {
            name: SLOClassConfig(**spec)
            for name, spec in json.loads(raw).items()
        }
    windows = env_raw("GRIDLLM_SLO_WINDOWS")
    if windows:
        kw["windows_s"] = [int(w) for w in windows.split(",") if w]
    return SLOConfig(**kw)


def _deadline_classes_from_env() -> dict[str, int]:
    """GRIDLLM_REQUEST_DEADLINE_CLASSES: JSON {class: deadline_ms}."""
    import json

    raw = env_raw("GRIDLLM_REQUEST_DEADLINE_CLASSES")
    if not raw:
        return {}
    return {str(k): int(v) for k, v in json.loads(raw).items()}


def load_config() -> Config:
    """Build Config from the environment; raise on invalid values (the
    reference fails fast at import on Joi errors, server/src/config/index.ts:45-49)."""
    try:
        return Config(
            env=_env("NODE_ENV", env_str("GRIDLLM_ENV")),
            bus=BusConfig(
                url=env_str("GRIDLLM_BUS_URL"),
                host=_env("REDIS_HOST", "localhost"),
                port=_env("REDIS_PORT", 6379),
                password=os.environ.get("REDIS_PASSWORD") or None,
                db=_env("REDIS_DB", 0),
                key_prefix=_env("REDIS_KEY_PREFIX", "GridLLM:"),
                endpoints=[e.strip() for e in
                           env_str("GRIDLLM_BUS_ENDPOINTS").split(",")
                           if e.strip()],
            ),
            scheduler=SchedulerConfig(
                worker_heartbeat_timeout_ms=_env("WORKER_HEARTBEAT_TIMEOUT", 15_000),
                worker_cleanup_interval_ms=_env("WORKER_CLEANUP_INTERVAL", 5_000),
                job_timeout_ms=_env("JOB_TIMEOUT", 600_000),
                retry_attempts=_env("JOB_RETRY_ATTEMPTS", 3),
                retry_delay_ms=_env("JOB_RETRY_DELAY", 5_000),
                max_concurrent_jobs_per_worker=_env("MAX_CONCURRENT_JOBS_PER_WORKER", 1),
                sweep_interval_ms=_env("SCHEDULER_SWEEP_INTERVAL", 1_000),
                prefix_affinity_weight=env_float(
                    "GRIDLLM_PREFIX_AFFINITY_WEIGHT"),
                disagg_enabled=env_bool("GRIDLLM_DISAGG"),
                retry_backoff_max_ms=env_int("GRIDLLM_RETRY_BACKOFF_MAX_MS"),
                retry_budget_per_min=env_float(
                    "GRIDLLM_RETRY_BUDGET_PER_MIN"),
                request_deadline_ms=env_int("GRIDLLM_REQUEST_DEADLINE_MS"),
                request_deadline_classes=_deadline_classes_from_env(),
                bus_rejoin_grace_ms=env_int("GRIDLLM_BUS_REJOIN_GRACE_MS"),
                preempt_after_ms=env_int("GRIDLLM_PREEMPT_AFTER_MS"),
            ),
            gateway=GatewayConfig(
                host=_env("HOST", "0.0.0.0"),
                port=_env("PORT", 4000),
                rate_limit_window_ms=_env("RATE_LIMIT_WINDOW_MS", 900_000),
                rate_limit_max_requests=_env("RATE_LIMIT_MAX_REQUESTS", 100),
                rate_limit_enabled=_env("RATE_LIMIT_ENABLED", True),
                rate_limit_scope=env_str("GRIDLLM_RATELIMIT_SCOPE"),
                enforce_keep_alive=env_bool("GRIDLLM_ENFORCE_KEEP_ALIVE"),
            ),
            controlplane=ControlPlaneConfig(
                mode=env_str("GRIDLLM_CONTROLPLANE"),
                member_id=env_str("GRIDLLM_CONTROLPLANE_ID"),
                num_shards=env_int("GRIDLLM_SHARD_COUNT"),
                shard_id=env_int("GRIDLLM_SHARD_ID"),
                lease_ttl_ms=env_int("GRIDLLM_SHARD_LEASE_TTL_MS"),
                renew_interval_ms=env_int("GRIDLLM_SHARD_RENEW_MS"),
                status_interval_ms=env_int("GRIDLLM_SHARD_STATUS_MS"),
                shard_health_port=env_int("GRIDLLM_SHARD_HEALTH_PORT"),
            ),
            worker=WorkerConfig(
                worker_id=_env("WORKER_ID", f"worker-{uuid.uuid4().hex[:12]}"),
                host=_env("WORKER_HOST", "0.0.0.0"),
                port=_env("WORKER_PORT", 3000),
                heartbeat_interval_ms=_env("HEARTBEAT_INTERVAL", 5_000),
                max_reconnect_attempts=_env("MAX_RECONNECT_ATTEMPTS", 10),
                max_concurrent_tasks=_env("MAX_CONCURRENT_TASKS", 1),
                performance_tier=_env("PERFORMANCE_TIER", "medium"),
                role=env_str("GRIDLLM_WORKER_ROLE"),
                advertise_addr=env_str("GRIDLLM_WORKER_ADVERTISE_ADDR"),
                drain_budget_ms=env_int("GRIDLLM_DRAIN_BUDGET_MS"),
            ),
            engine=EngineConfig(
                models=env_str("GRIDLLM_MODELS"),
                checkpoint_dir=env_str("GRIDLLM_CHECKPOINT_DIR"),
                dtype=env_str("GRIDLLM_DTYPE"),
                max_seq_len=env_int("GRIDLLM_MAX_SEQ_LEN"),
                max_batch_slots=env_int("GRIDLLM_MAX_BATCH_SLOTS"),
                kv_page_size=env_int("GRIDLLM_KV_PAGE_SIZE"),
                stream_flush_ms=env_int("GRIDLLM_STREAM_FLUSH_MS"),
                prefill_buckets=env_str("GRIDLLM_PREFILL_BUCKETS"),
                mesh_shape=env_str("GRIDLLM_MESH_SHAPE"),
            ),
            obs=ObsConfig(
                slo=_slo_config_from_env(),
                watchdog=WatchdogConfig(
                    enabled=env_bool("GRIDLLM_WATCHDOG_ENABLED"),
                    interval_ms=env_int("GRIDLLM_WATCHDOG_INTERVAL"),
                    queue_deadline_ms=env_int(
                        "GRIDLLM_WATCHDOG_QUEUE_DEADLINE"),
                    dispatch_deadline_ms=env_int(
                        "GRIDLLM_WATCHDOG_DISPATCH_DEADLINE"),
                    prefill_deadline_ms=env_int(
                        "GRIDLLM_WATCHDOG_PREFILL_DEADLINE"),
                    decode_stall_ms=env_int(
                        "GRIDLLM_WATCHDOG_DECODE_STALL"),
                    requeue=env_bool("GRIDLLM_WATCHDOG_REQUEUE"),
                    profile_on_hang_s=env_float(
                        "GRIDLLM_WATCHDOG_PROFILE_S"),
                ),
                flightrec_capacity=env_int("GRIDLLM_FLIGHTREC_CAPACITY"),
                timeline=TimelineConfig(
                    enabled=env_bool("GRIDLLM_TIMELINE"),
                    queue_capacity=env_int("GRIDLLM_TIMELINE_QUEUE"),
                    flush_ms=env_float("GRIDLLM_TIMELINE_FLUSH_MS"),
                    batch_max=env_int("GRIDLLM_TIMELINE_BATCH"),
                    store_capacity=env_int("GRIDLLM_TIMELINE_STORE"),
                    store_requests=env_int("GRIDLLM_TIMELINE_REQUESTS"),
                    incident_window_ms=env_float(
                        "GRIDLLM_TIMELINE_INCIDENT_WINDOW_MS"),
                    max_incidents=env_int("GRIDLLM_TIMELINE_INCIDENTS"),
                ),
            ),
        )
    except (ValidationError, ValueError) as e:  # pragma: no cover - fail fast
        raise SystemExit(f"Invalid configuration: {e}") from e
