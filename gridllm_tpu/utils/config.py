"""Env-driven configuration, validated fail-fast at load.

Reference analogue: dotenv + Joi schemas (server/src/config/index.ts:6-92,
client/src/config/index.ts:6-148). Same shape and defaults; pydantic replaces
Joi. TPU-specific knobs (mesh, dtype, KV cache) join the worker schema per
SURVEY.md §5.6.

Defaults preserved from the reference:
- server port 4000 (server/src/config/index.ts:10)
- workerHeartbeatTimeout 15000 ms (:24), workerCleanupInterval 5000 ms (:25)
- jobTimeout 600000 ms (:28), retryAttempts 3 / retryDelay 5000 ms (:29-30)
- maxConcurrentJobsPerWorker 1 (:31) — the TPU engine supersedes this with
  continuous batching, so the default here is per-engine slot count
- bus key prefix "GridLLM:" (:17)
- worker heartbeatInterval 5000 ms (client/src/config/index.ts:94)
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Literal

from pydantic import BaseModel, Field, ValidationError


def _env(name: str, default: Any) -> Any:
    raw = os.environ.get(name)
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


class BusConfig(BaseModel):
    """reference: redis block of server/src/config/index.ts:12-18."""

    url: str = ""                      # "" → in-memory bus; "resp://host:port" → wire
    host: str = "localhost"
    port: int = 6379
    password: str | None = None
    db: int = 0
    key_prefix: str = "GridLLM:"


class SchedulerConfig(BaseModel):
    """reference: performance/scheduling block, server/src/config/index.ts:22-33."""

    worker_heartbeat_timeout_ms: int = Field(15_000, gt=0)
    worker_cleanup_interval_ms: int = Field(5_000, gt=0)
    connection_monitor_interval_ms: int = Field(5_000, gt=0)
    quick_disconnect_window_ms: int = Field(15_000, gt=0)
    orphan_assign_threshold_ms: int = Field(10_000, gt=0)
    job_timeout_ms: int = Field(600_000, gt=0)
    retry_attempts: int = Field(3, ge=0)
    retry_delay_ms: int = Field(5_000, ge=0)
    # capacity NACKs requeue without consuming the retry ladder, but only
    # this many times — a nack storm then falls through to the real ladder
    max_nacks: int = Field(25, ge=0)
    max_concurrent_jobs_per_worker: int = Field(1, ge=1)
    # TPU change: the reference polled a 1 s tick (JobScheduler.ts:128-135);
    # we dispatch event-driven, with this tick only as a fallback sweep.
    sweep_interval_ms: int = Field(1_000, gt=0)
    # Prefix-affinity routing (ISSUE 3): a worker whose heartbeat digest
    # contains the job's prefixKey gets this subtracted from its
    # proportional-load score. Affinity never overrides the load cap
    # (candidates are pre-filtered by availability) — it breaks ties and
    # outweighs load differences up to this fraction, so a hot worker
    # still sheds. 0 disables the term.
    prefix_affinity_weight: float = Field(0.25, ge=0)
    # Disaggregated prefill/decode serving (ISSUE 7): when the fleet has
    # BOTH a prefill pool and a decode pool for a model, generation jobs
    # get two-phase placement (prefill worker + planned decode handoff
    # with KV-page migration). Default on — with a homogeneous unified
    # fleet there are no pools, so nothing changes. GRIDLLM_DISAGG=0
    # forces whole-request placement even on a split fleet.
    disagg_enabled: bool = True


class GatewayConfig(BaseModel):
    """reference: server block, server/src/config/index.ts:8-11, 38-43."""

    host: str = "0.0.0.0"
    port: int = 4000
    max_body_bytes: int = 10 * 1024 * 1024  # express json limit 10mb (index.ts:47)
    rate_limit_window_ms: int = 900_000
    rate_limit_max_requests: int = 100
    rate_limit_enabled: bool = True
    default_request_timeout_ms: int = 300_000
    # Ollama-exact idle residency: unload a model when its keep_alive
    # window passes with no requests (Ollama defaults to 5m). OFF by
    # default — a TPU reload of a 70B checkpoint costs minutes, so the
    # default here keeps weights resident and honors keep_alive only as
    # the advertised /api/ps expiry. GRIDLLM_ENFORCE_KEEP_ALIVE=1 opts in.
    enforce_keep_alive: bool = False


class EngineConfig(BaseModel):
    """TPU engine knobs — NEW (replaces the reference's ollama block,
    client/src/config/index.ts:82-89)."""

    models: str = ""                   # comma-separated model specs to serve
    checkpoint_dir: str = ""
    dtype: str = "bfloat16"
    max_seq_len: int = 8192
    max_batch_slots: int = 8           # continuous-batching slot count
    prefill_buckets: str = "512,1024,2048,4096,8192"
    kv_page_size: int = 128
    stream_flush_ms: int = 20          # token-frame batching window
    # mesh axes (parallel/mesh.py): e.g. "tp:8", "pp:2,tp:4", "dp:2,tp:4";
    # "" → single device
    mesh_shape: str = ""
    decode_steps_per_host_sync: int = 8


class WorkerConfig(BaseModel):
    """reference: client/src/config/index.ts:6-148."""

    worker_id: str = Field(default_factory=lambda: f"worker-{uuid.uuid4().hex[:12]}")
    host: str = "0.0.0.0"
    port: int = 3000
    heartbeat_interval_ms: int = Field(5_000, gt=0)
    resource_monitor_interval_ms: int = Field(10_000, gt=0)
    max_reconnect_attempts: int = 10
    max_concurrent_tasks: int = 1      # superseded by engine.max_batch_slots when engine present
    performance_tier: str = "medium"
    # Disaggregated serving (ISSUE 7): this worker's fleet role
    # (GRIDLLM_WORKER_ROLE). "prefill" workers take phase-1 placements
    # and export KV; "decode" workers admit from imported pages;
    # "unified" (default) serves whole requests as before.
    role: Literal["unified", "prefill", "decode"] = "unified"
    # host:port other workers can reach this worker's health HTTP server
    # at (GRIDLLM_WORKER_ADVERTISE_ADDR) — the direct worker-to-worker
    # KV-transfer fallback path. "" → 127.0.0.1:{port} (single-host
    # deployments and tests).
    advertise_addr: str = ""


class SLOClassConfig(BaseModel):
    """Latency objectives for one request class (ISSUE 2). ``None`` means
    the objective does not apply to the class (embeddings have no ITL)."""

    ttft_ms: float | None = None       # submit → first streamed token
    itl_ms: float | None = None        # mean inter-token latency
    e2e_ms: float | None = None        # submit → final result
    target: float = Field(0.99, gt=0, le=1)  # attainment objective


def default_slo_classes() -> dict[str, SLOClassConfig]:
    """Request classes and their default objectives. Classification
    (obs/slo.py classify_request): streaming generation is interactive,
    non-streaming generation is batch, embeddings are their own class."""
    return {
        "interactive": SLOClassConfig(ttft_ms=2_000, itl_ms=200,
                                      e2e_ms=120_000, target=0.99),
        "batch": SLOClassConfig(e2e_ms=300_000, target=0.95),
        "embedding": SLOClassConfig(e2e_ms=10_000, target=0.99),
    }


class SLOConfig(BaseModel):
    """SLO engine knobs (obs/slo.py). ``GRIDLLM_SLO_CLASSES`` may carry a
    JSON object {class: {ttft_ms, itl_ms, e2e_ms, target}} that REPLACES
    the defaults wholesale (partial per-class merges would make the
    effective objective ambiguous)."""

    enabled: bool = True
    classes: dict[str, SLOClassConfig] = Field(
        default_factory=default_slo_classes)
    # burn-rate windows (seconds): one fast window for paging, one slow
    # window for ticket-level alerts (multi-window burn-rate alerting)
    windows_s: list[int] = Field(default_factory=lambda: [300, 3600])


class WatchdogConfig(BaseModel):
    """Hang watchdog (obs/watchdog.py): per-phase deadlines after which a
    request is flagged as wedged. Defaults are generous — first-compile on
    a cold worker is minutes, and a false hang requeue wastes real work."""

    enabled: bool = True
    interval_ms: int = Field(1_000, gt=0)
    # open queue.wait span older than this → phase "queue"
    queue_deadline_ms: int = Field(120_000, gt=0)
    # assigned, no stream frame yet → "dispatch" past this ...
    dispatch_deadline_ms: int = Field(60_000, gt=0)
    # ... and "prefill" past this (gateway-side the two are only
    # distinguishable by age; worker-side engine probes refine it)
    prefill_deadline_ms: int = Field(240_000, gt=0)
    # first token seen but no frame for this long → "decode-step"
    decode_stall_ms: int = Field(60_000, gt=0)
    # abort + requeue hung ACTIVE jobs (reason "hang"); queue-phase hangs
    # are diagnosis-only (there is nothing to requeue)
    requeue: bool = True
    # on a decode-step hang, auto-start a short jax.profiler capture
    # (obs/perf.py) so the trace covers the wedge itself; 0 (default)
    # disables — OPT-IN via GRIDLLM_WATCHDOG_PROFILE_S because the
    # capture's stop-flush serializes profiler data while holding the
    # GIL for seconds, which can starve heartbeats/streams mid-incident
    # and turn a surgical hang-requeue into a worker-crash orphaning.
    # Only meaningful when the engine runs in THIS process (bench,
    # single-process deploys) — split deployments use the worker health
    # port's POST /admin/profile instead.
    profile_on_hang_s: float = Field(0.0, ge=0)


class ObsConfig(BaseModel):
    """Interpretation-layer observability (ISSUE 2): SLO engine, hang
    watchdog, flight recorder."""

    slo: SLOConfig = Field(default_factory=SLOConfig)
    watchdog: WatchdogConfig = Field(default_factory=WatchdogConfig)
    # per-subsystem ring capacity of the flight recorder
    flightrec_capacity: int = Field(256, gt=0)


class Config(BaseModel):
    env: str = "development"
    bus: BusConfig = Field(default_factory=BusConfig)
    scheduler: SchedulerConfig = Field(default_factory=SchedulerConfig)
    gateway: GatewayConfig = Field(default_factory=GatewayConfig)
    worker: WorkerConfig = Field(default_factory=WorkerConfig)
    engine: EngineConfig = Field(default_factory=EngineConfig)
    obs: ObsConfig = Field(default_factory=ObsConfig)


def _slo_config_from_env() -> SLOConfig:
    """SLO objectives from the environment. ``GRIDLLM_SLO_CLASSES`` is a
    JSON object replacing the default class table; ``GRIDLLM_SLO_WINDOWS``
    is a comma list of burn-rate window seconds."""
    import json

    kw: dict[str, Any] = {"enabled": _env("GRIDLLM_SLO_ENABLED", True)}
    raw = os.environ.get("GRIDLLM_SLO_CLASSES")
    if raw:
        kw["classes"] = {
            name: SLOClassConfig(**spec)
            for name, spec in json.loads(raw).items()
        }
    windows = os.environ.get("GRIDLLM_SLO_WINDOWS")
    if windows:
        kw["windows_s"] = [int(w) for w in windows.split(",") if w]
    return SLOConfig(**kw)


def load_config() -> Config:
    """Build Config from the environment; raise on invalid values (the
    reference fails fast at import on Joi errors, server/src/config/index.ts:45-49)."""
    try:
        return Config(
            env=_env("NODE_ENV", _env("GRIDLLM_ENV", "development")),
            bus=BusConfig(
                url=_env("GRIDLLM_BUS_URL", ""),
                host=_env("REDIS_HOST", "localhost"),
                port=_env("REDIS_PORT", 6379),
                password=os.environ.get("REDIS_PASSWORD") or None,
                db=_env("REDIS_DB", 0),
                key_prefix=_env("REDIS_KEY_PREFIX", "GridLLM:"),
            ),
            scheduler=SchedulerConfig(
                worker_heartbeat_timeout_ms=_env("WORKER_HEARTBEAT_TIMEOUT", 15_000),
                worker_cleanup_interval_ms=_env("WORKER_CLEANUP_INTERVAL", 5_000),
                job_timeout_ms=_env("JOB_TIMEOUT", 600_000),
                retry_attempts=_env("JOB_RETRY_ATTEMPTS", 3),
                retry_delay_ms=_env("JOB_RETRY_DELAY", 5_000),
                max_concurrent_jobs_per_worker=_env("MAX_CONCURRENT_JOBS_PER_WORKER", 1),
                sweep_interval_ms=_env("SCHEDULER_SWEEP_INTERVAL", 1_000),
                prefix_affinity_weight=_env(
                    "GRIDLLM_PREFIX_AFFINITY_WEIGHT", 0.25),
                disagg_enabled=_env("GRIDLLM_DISAGG", True),
            ),
            gateway=GatewayConfig(
                host=_env("HOST", "0.0.0.0"),
                port=_env("PORT", 4000),
                rate_limit_window_ms=_env("RATE_LIMIT_WINDOW_MS", 900_000),
                rate_limit_max_requests=_env("RATE_LIMIT_MAX_REQUESTS", 100),
                rate_limit_enabled=_env("RATE_LIMIT_ENABLED", True),
                enforce_keep_alive=_env("GRIDLLM_ENFORCE_KEEP_ALIVE", False),
            ),
            worker=WorkerConfig(
                worker_id=_env("WORKER_ID", f"worker-{uuid.uuid4().hex[:12]}"),
                host=_env("WORKER_HOST", "0.0.0.0"),
                port=_env("WORKER_PORT", 3000),
                heartbeat_interval_ms=_env("HEARTBEAT_INTERVAL", 5_000),
                max_reconnect_attempts=_env("MAX_RECONNECT_ATTEMPTS", 10),
                max_concurrent_tasks=_env("MAX_CONCURRENT_TASKS", 1),
                performance_tier=_env("PERFORMANCE_TIER", "medium"),
                role=_env("GRIDLLM_WORKER_ROLE", "unified"),
                advertise_addr=_env("GRIDLLM_WORKER_ADVERTISE_ADDR", ""),
            ),
            engine=EngineConfig(
                models=_env("GRIDLLM_MODELS", ""),
                checkpoint_dir=_env("GRIDLLM_CHECKPOINT_DIR", ""),
                dtype=_env("GRIDLLM_DTYPE", "bfloat16"),
                max_seq_len=_env("GRIDLLM_MAX_SEQ_LEN", 8192),
                max_batch_slots=_env("GRIDLLM_MAX_BATCH_SLOTS", 8),
                kv_page_size=_env("GRIDLLM_KV_PAGE_SIZE", 128),
                stream_flush_ms=_env("GRIDLLM_STREAM_FLUSH_MS", 20),
                mesh_shape=_env("GRIDLLM_MESH_SHAPE", ""),
            ),
            obs=ObsConfig(
                slo=_slo_config_from_env(),
                watchdog=WatchdogConfig(
                    enabled=_env("GRIDLLM_WATCHDOG_ENABLED", True),
                    interval_ms=_env("GRIDLLM_WATCHDOG_INTERVAL", 1_000),
                    queue_deadline_ms=_env(
                        "GRIDLLM_WATCHDOG_QUEUE_DEADLINE", 120_000),
                    dispatch_deadline_ms=_env(
                        "GRIDLLM_WATCHDOG_DISPATCH_DEADLINE", 60_000),
                    prefill_deadline_ms=_env(
                        "GRIDLLM_WATCHDOG_PREFILL_DEADLINE", 240_000),
                    decode_stall_ms=_env(
                        "GRIDLLM_WATCHDOG_DECODE_STALL", 60_000),
                    requeue=_env("GRIDLLM_WATCHDOG_REQUEUE", True),
                    profile_on_hang_s=_env(
                        "GRIDLLM_WATCHDOG_PROFILE_S", 0.0),
                ),
                flightrec_capacity=_env("GRIDLLM_FLIGHTREC_CAPACITY", 256),
            ),
        )
    except (ValidationError, ValueError) as e:  # pragma: no cover - fail fast
        raise SystemExit(f"Invalid configuration: {e}") from e
