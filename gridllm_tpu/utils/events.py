"""Tiny async event emitter.

Reference analogue: Node's EventEmitter as used by JobScheduler/WorkerRegistry
(events wired to logs at server/src/index.ts:119-212). Handlers may be sync
or async; emission never raises."""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Callable

from gridllm_tpu.utils.logging import get_logger

log = get_logger("utils.events")


class EventEmitter:
    def __init__(self) -> None:
        self._handlers: dict[str, list[Callable[..., Any]]] = {}

    def on(self, event: str, handler: Callable[..., Any]) -> None:
        self._handlers.setdefault(event, []).append(handler)

    def off(self, event: str, handler: Callable[..., Any]) -> None:
        lst = self._handlers.get(event, [])
        if handler in lst:
            lst.remove(handler)

    def emit(self, event: str, *args: Any) -> None:
        for h in list(self._handlers.get(event, [])):
            try:
                result = h(*args)
                if inspect.isawaitable(result):
                    task = asyncio.ensure_future(result)
                    task.add_done_callback(
                        lambda t, ev=event: (
                            t.cancelled() or t.exception() is None or
                            log.error("async event handler failed", event=ev,
                                      error=str(t.exception()))
                        )
                    )
            except Exception as e:
                log.error("event handler failed", event=event, error=str(e))
