"""Structured logging.

Reference analogue: winston logger with domain helpers ``logger.worker`` /
``logger.job`` / ``logger.performance`` (server/src/utils/logger.ts:104-126).
Here: stdlib logging with a structured ``extra``-style kwargs API and the same
domain tags, JSON-ish single-line output, circular-safe serialization
(reference: server/src/utils/logger.ts:12-36).
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from typing import Any

_LEVEL = os.environ.get("GRIDLLM_LOG_LEVEL", "info").upper()
_CONFIGURED = False


def _safe(obj: Any, _depth: int = 0) -> Any:
    """Best-effort JSON-serializable projection (circular/huge-safe)."""
    if _depth > 4:
        return "<depth>"
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _safe(v, _depth + 1) for k, v in list(obj.items())[:50]}
    if isinstance(obj, (list, tuple)):
        return [_safe(v, _depth + 1) for v in list(obj)[:50]]
    if isinstance(obj, BaseException):
        return f"{type(obj).__name__}: {obj}"
    return repr(obj)[:200]


class StructuredLogger:
    """Thin wrapper: ``log.info("msg", job_id=..., worker_id=...)``."""

    def __init__(self, name: str):
        self._log = logging.getLogger(name)

    def _emit(self, level: int, msg: str, kw: dict[str, Any]) -> None:
        if kw:
            try:
                msg = f"{msg} {json.dumps(_safe(kw), default=str)}"
            except Exception:
                msg = f"{msg} <unserializable>"
        self._log.log(level, msg)

    def debug(self, msg: str, **kw: Any) -> None:
        self._emit(logging.DEBUG, msg, kw)

    def info(self, msg: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, kw)

    def warning(self, msg: str, **kw: Any) -> None:
        self._emit(logging.WARNING, msg, kw)

    def error(self, msg: str, **kw: Any) -> None:
        self._emit(logging.ERROR, msg, kw)

    # Domain helpers (reference: server/src/utils/logger.ts:114-126)
    def worker(self, msg: str, worker_id: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, {"type": "worker", "worker_id": worker_id, **kw})

    def job(self, msg: str, job_id: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, {"type": "job", "job_id": job_id, **kw})

    def performance(self, msg: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, {"type": "performance", **kw})


def get_logger(name: str) -> StructuredLogger:
    global _CONFIGURED
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s.%(msecs)03dZ %(levelname)s [%(name)s] %(message)s",
                datefmt="%Y-%m-%dT%H:%M:%S",
            )
        )
        handler.formatter.converter = time.gmtime  # type: ignore[union-attr]
        root = logging.getLogger("gridllm_tpu")
        root.addHandler(handler)
        root.setLevel(getattr(logging, _LEVEL, logging.INFO))
        root.propagate = False
        _CONFIGURED = True
    if not name.startswith("gridllm_tpu"):
        name = f"gridllm_tpu.{name}"
    return StructuredLogger(name)
