"""Structured logging.

Reference analogue: winston logger with domain helpers ``logger.worker`` /
``logger.job`` / ``logger.performance`` (server/src/utils/logger.ts:104-126).
Here: stdlib logging with a structured ``extra``-style kwargs API and the same
domain tags, JSON-ish single-line output, circular-safe serialization
(reference: server/src/utils/logger.ts:12-36).
"""

from __future__ import annotations

import contextvars
import json
import logging
import sys
import time
from contextlib import contextmanager
from typing import Any, Iterator

from gridllm_tpu.utils.config import env_str

_LEVEL = env_str("GRIDLLM_LOG_LEVEL").upper()
_CONFIGURED = False

# Active request id (set while a trace span is open for the request, see
# obs/tracer.py): every structured log record emitted inside the context
# gains a request_id field, so log lines grep-join with span timelines.
_REQUEST_ID: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "gridllm_request_id", default=None
)


@contextmanager
def bind_request_id(request_id: str | None) -> Iterator[None]:
    """Attach ``request_id`` to all structured logs emitted in this context
    (async-task-local via contextvars; engine threads are outside it and
    keep passing ids explicitly)."""
    token = _REQUEST_ID.set(request_id)
    try:
        yield
    finally:
        _REQUEST_ID.reset(token)


def current_request_id() -> str | None:
    return _REQUEST_ID.get()


def _safe(obj: Any, _depth: int = 0) -> Any:
    """Best-effort JSON-serializable projection (circular/huge-safe)."""
    if _depth > 4:
        return "<depth>"
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _safe(v, _depth + 1) for k, v in list(obj.items())[:50]}
    if isinstance(obj, (list, tuple)):
        return [_safe(v, _depth + 1) for v in list(obj)[:50]]
    if isinstance(obj, BaseException):
        return f"{type(obj).__name__}: {obj}"
    return repr(obj)[:200]


class StructuredLogger:
    """Thin wrapper: ``log.info("msg", job_id=..., worker_id=...)``."""

    def __init__(self, name: str):
        self._log = logging.getLogger(name)

    def _emit(self, level: int, msg: str, kw: dict[str, Any]) -> None:
        rid = _REQUEST_ID.get()
        if rid is not None and "request_id" not in kw:
            kw = {"request_id": rid, **kw}
        if kw:
            try:
                msg = f"{msg} {json.dumps(_safe(kw), default=str)}"
            except Exception:
                msg = f"{msg} <unserializable>"
        self._log.log(level, msg)

    def debug(self, msg: str, **kw: Any) -> None:
        self._emit(logging.DEBUG, msg, kw)

    def info(self, msg: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, kw)

    def warning(self, msg: str, **kw: Any) -> None:
        self._emit(logging.WARNING, msg, kw)

    def error(self, msg: str, **kw: Any) -> None:
        self._emit(logging.ERROR, msg, kw)

    # Domain helpers (reference: server/src/utils/logger.ts:114-126)
    def worker(self, msg: str, worker_id: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, {"type": "worker", "worker_id": worker_id, **kw})

    def job(self, msg: str, job_id: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, {"type": "job", "job_id": job_id, **kw})

    def performance(self, msg: str, **kw: Any) -> None:
        self._emit(logging.INFO, msg, {"type": "performance", **kw})


def get_logger(name: str) -> StructuredLogger:
    global _CONFIGURED
    if not _CONFIGURED:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter(
                "%(asctime)s.%(msecs)03dZ %(levelname)s [%(name)s] %(message)s",
                datefmt="%Y-%m-%dT%H:%M:%S",
            )
        )
        handler.formatter.converter = time.gmtime  # type: ignore[union-attr]
        root = logging.getLogger("gridllm_tpu")
        root.addHandler(handler)
        root.setLevel(getattr(logging, _LEVEL, logging.INFO))
        root.propagate = False
        _CONFIGURED = True
    if not name.startswith("gridllm_tpu"):
        name = f"gridllm_tpu.{name}"
    return StructuredLogger(name)
