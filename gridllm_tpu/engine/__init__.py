"""TPU inference engine (SURVEY.md §7 `engine/`).

This is the subsystem the reference outsources to Ollama
(client/src/services/OllamaService.ts:17-27 — an HTTP adapter to an external
daemon). Here it is native: JAX model + paged KV cache + continuous-batching
decode loop + sampler, producing the same behavioral surface the worker
needs (streamed tokens, Ollama timing fields, embeddings).
"""

from gridllm_tpu.engine.engine import (
    EngineConfig,
    GenerationRequest,
    GenerationResult,
    InferenceEngine,
)
from gridllm_tpu.engine.tokenizer import ByteTokenizer, Tokenizer, get_tokenizer

__all__ = [
    "EngineConfig",
    "GenerationRequest",
    "GenerationResult",
    "InferenceEngine",
    "Tokenizer",
    "ByteTokenizer",
    "get_tokenizer",
]
