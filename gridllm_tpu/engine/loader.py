"""Checkpoint loading: HF safetensors directory → (sharded) param pytree.

SURVEY.md §5.4: the reference has no model checkpointing (models live in
Ollama's store); this is the rebuild's native replacement, and §7 names
"HF checkpoint → sharded-layout loading without host-RAM blowups" a hard
part. Approach:

- safetensors are opened with framework="numpy" → tensors are lazily
  mmap-backed; nothing materializes until sliced.
- per-leaf placement: each finished leaf is `jax.device_put` to its
  NamedSharding immediately, so peak host RAM ≈ one stacked leaf group
  (largest: w_down L×F×E), not the whole checkpoint.
- dtype conversion happens on the way in (bf16 by default).
"""

from __future__ import annotations

import json
import os
import re
import threading
from collections import OrderedDict
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.obs import default_registry
from gridllm_tpu.utils.config import env_int
from gridllm_tpu.utils.logging import get_logger

log = get_logger("engine.loader")


def _open_safetensors(path: str) -> dict[str, Callable[[], np.ndarray]]:
    """Map HF tensor name → thunk returning the numpy array (mmap-lazy)."""
    from safetensors import safe_open

    files = sorted(
        os.path.join(path, f) for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    index: dict[str, Callable[[], np.ndarray]] = {}
    for f in files:
        handle = safe_open(f, framework="numpy")
        for name in handle.keys():  # noqa: SIM118 — safe_open has no __iter__
            index[name] = (lambda h, n: lambda: h.get_tensor(n))(handle, name)
    return index


def _name_map(cfg: ModelConfig) -> dict[str, tuple[str, bool]]:
    """The family's HF layout contract — owned by the model module
    (llama.HF_MAP / mixtral.HF_MAP) so loader and state-dict converter
    cannot drift. {} is the layer index; an extra {} the expert index."""
    if cfg.family == "mixtral":
        from gridllm_tpu.models import mixtral

        return mixtral.HF_MAP
    if cfg.family == "gemma2":
        from gridllm_tpu.models import gemma

        return gemma.hf_map(cfg)
    from gridllm_tpu.models import llama

    return llama.hf_map(cfg)


def load_checkpoint(
    cfg: ModelConfig,
    path: str,
    dtype=jnp.bfloat16,
    shardings: Any | None = None,
    quantize: str | None = None,
) -> Any:
    """Load an HF checkpoint dir into our stacked-layer pytree.

    `shardings`: optional pytree (from parallel.param_shardings on params of
    the same structure) — each leaf is placed onto its sharding as soon as it
    is assembled. `quantize="int8"`: matmul leaves are quantized HOST-side
    (ops/quant.py) so the bf16 copy never reaches HBM — required for the
    llama3:70b-on-v5e-8 memory budget (BASELINE config #3).
    """
    from gridllm_tpu.models import hf_layout
    from gridllm_tpu.ops.quant import NO_QUANT_SUBTREES, quantize_np_leaf

    idx = _open_safetensors(path)

    def place(pathkeys: tuple[str, ...], arr: np.ndarray):
        if quantize == "int8" and pathkeys[0] not in NO_QUANT_SUBTREES:
            out = quantize_np_leaf(pathkeys[-1], arr)
            if not hasattr(out, "q"):
                out = jnp.asarray(out, dtype)
        else:
            out = jnp.asarray(arr, dtype)
        if shardings is not None:
            s = shardings
            for k in pathkeys:
                s = s[k]
            out = jax.device_put(out, s)
        log.debug("loaded leaf", leaf="/".join(pathkeys), shape=list(out.shape))
        return out

    def get(name: str) -> np.ndarray:
        return idx[name]()

    if cfg.family == "bert_embed":
        from gridllm_tpu.models import bert_embed

        return bert_embed.from_getter(cfg, get, dtype, place)
    if cfg.family == "llava":
        from gridllm_tpu.models import llava

        return llava.from_getter(cfg, get, dtype, place)
    return hf_layout.to_pytree(cfg, get, _name_map(cfg), dtype, place)


def save_checkpoint(params: Any, cfg: ModelConfig, path: str) -> None:
    """Write our pytree back out as a single HF-layout safetensors file
    (round-trip for tests + lets checkpoints produced here load in HF)."""
    from safetensors.numpy import save_file

    from gridllm_tpu.models import hf_layout

    os.makedirs(path, exist_ok=True)
    if cfg.family == "bert_embed":
        from gridllm_tpu.models import bert_embed

        out = bert_embed.to_hf_tensors(params, cfg)
    else:
        out = hf_layout.to_hf_tensors(params, cfg, _name_map(cfg))
    save_file(out, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_name": cfg.name}, f)


# ---------------------------------------------------------------------------
# Host-RAM weight snapshot tier (ISSUE 20) — the weights twin of the KV
# host tier: unloading a model parks its device params as host numpy
# arrays keyed by checkpoint identity; a later load of the same identity
# restores via host→device transfer instead of re-reading safetensors
# (or re-running init). Capacity-bounded LRU; a miss degrades to the
# normal disk/init path, never an error.

_SNAP_BYTES = default_registry().gauge(
    "gridllm_weight_snapshot_bytes",
    "Host RAM held by parked weight snapshots (engine/loader.py); "
    "bounded by GRIDLLM_WEIGHT_SNAPSHOT_BYTES.",
)
_SNAP_MODELS = default_registry().gauge(
    "gridllm_weight_snapshot_models",
    "Distinct checkpoint identities resident in the weight snapshot "
    "tier (engine/loader.py).",
)
_SNAP_EVENTS = default_registry().counter(
    "gridllm_weight_snapshot_events_total",
    "Weight snapshot tier activity by event: park, hit (restore served "
    "from host RAM), miss (load fell through to disk/init), evict "
    "(LRU capacity pressure).",
    ("event",),
)


class WeightSnapshotTier:
    """LRU of host-side param pytrees, keyed by checkpoint identity.

    Entries survive :meth:`restore` (weights are immutable — the same
    snapshot can warm many future loads); capacity pressure evicts the
    least-recently-touched identity. Thread-safe: parks run on worker
    admin tasks while restores run on engine construction threads.
    """

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = max(int(capacity_bytes), 0)
        self._entries: OrderedDict[str, tuple[Any, int]] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.parks = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def enabled(self) -> bool:
        return self.capacity_bytes > 0

    @staticmethod
    def _host_copy(params: Any) -> tuple[Any, int]:
        size = 0

        def pull(a):
            nonlocal size
            h = np.asarray(jax.device_get(a))
            size += h.nbytes
            return h

        return jax.tree_util.tree_map(pull, params), size

    def park(self, key: str, params: Any) -> bool:
        """Copy ``params`` to host RAM under ``key``. Returns False when
        the tier is disabled or the snapshot alone exceeds capacity."""
        if not self.enabled:
            return False
        host, size = self._host_copy(params)
        if size > self.capacity_bytes:
            log.info("weight snapshot too large for tier; dropped",
                     key=key, bytes=size, capacity=self.capacity_bytes)
            return False
        with self._lock:
            if key in self._entries:
                _, old = self._entries.pop(key)
                self._bytes -= old
            while self._bytes + size > self.capacity_bytes and self._entries:
                old_key, (_, old_size) = self._entries.popitem(last=False)
                self._bytes -= old_size
                self.evictions += 1
                _SNAP_EVENTS.inc(event="evict")
                log.info("weight snapshot evicted", key=old_key, bytes=old_size)
            self._entries[key] = (host, size)
            self._bytes += size
            self.parks += 1
            self._publish()
        _SNAP_EVENTS.inc(event="park")
        log.info("weight snapshot parked", key=key, bytes=size)
        return True

    def restore(self, key: str) -> Any | None:
        """Host pytree for ``key``, or None on miss. The entry is kept
        (moved to MRU) — callers must not mutate the returned arrays."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                _SNAP_EVENTS.inc(event="miss")
                return None
            self._entries.move_to_end(key)
            self.hits += 1
        _SNAP_EVENTS.inc(event="hit")
        return entry[0]

    def drop(self, key: str) -> None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self._bytes -= entry[1]
                self._publish()

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._publish()

    def _publish(self) -> None:
        _SNAP_BYTES.set(self._bytes)
        _SNAP_MODELS.set(len(self._entries))

    def stats(self) -> dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacityBytes": self.capacity_bytes,
                "parks": self.parks,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


_tier: WeightSnapshotTier | None = None
_tier_lock = threading.Lock()


def weight_snapshot_tier() -> WeightSnapshotTier:
    """Process-wide tier, sized from GRIDLLM_WEIGHT_SNAPSHOT_BYTES at
    first touch (all engines in a worker share one host-RAM budget)."""
    global _tier
    with _tier_lock:
        if _tier is None:
            _tier = WeightSnapshotTier(env_int("GRIDLLM_WEIGHT_SNAPSHOT_BYTES"))
        return _tier


def reset_weight_snapshot_tier() -> None:
    """Forget the singleton (tests re-read the env on next touch)."""
    global _tier
    with _tier_lock:
        _tier = None
