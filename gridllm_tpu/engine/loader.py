"""Checkpoint loading: HF safetensors directory → (sharded) param pytree.

SURVEY.md §5.4: the reference has no model checkpointing (models live in
Ollama's store); this is the rebuild's native replacement, and §7 names
"HF checkpoint → sharded-layout loading without host-RAM blowups" a hard
part. Approach:

- safetensors are opened with framework="numpy" → tensors are lazily
  mmap-backed; nothing materializes until sliced.
- per-leaf placement: each finished leaf is `jax.device_put` to its
  NamedSharding immediately, so peak host RAM ≈ one stacked leaf group
  (largest: w_down L×F×E), not the whole checkpoint.
- dtype conversion happens on the way in (bf16 by default).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.utils.logging import get_logger

log = get_logger("engine.loader")


def _open_safetensors(path: str) -> dict[str, Callable[[], np.ndarray]]:
    """Map HF tensor name → thunk returning the numpy array (mmap-lazy)."""
    from safetensors import safe_open

    files = sorted(
        os.path.join(path, f) for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    index: dict[str, Callable[[], np.ndarray]] = {}
    for f in files:
        handle = safe_open(f, framework="numpy")
        for name in handle.keys():  # noqa: SIM118 — safe_open has no __iter__
            index[name] = (lambda h, n: lambda: h.get_tensor(n))(handle, name)
    return index


def _name_map(cfg: ModelConfig) -> dict[str, tuple[str, bool]]:
    """The family's HF layout contract — owned by the model module
    (llama.HF_MAP / mixtral.HF_MAP) so loader and state-dict converter
    cannot drift. {} is the layer index; an extra {} the expert index."""
    if cfg.family == "mixtral":
        from gridllm_tpu.models import mixtral

        return mixtral.HF_MAP
    if cfg.family == "gemma2":
        from gridllm_tpu.models import gemma

        return gemma.hf_map(cfg)
    from gridllm_tpu.models import llama

    return llama.hf_map(cfg)


def load_checkpoint(
    cfg: ModelConfig,
    path: str,
    dtype=jnp.bfloat16,
    shardings: Any | None = None,
    quantize: str | None = None,
) -> Any:
    """Load an HF checkpoint dir into our stacked-layer pytree.

    `shardings`: optional pytree (from parallel.param_shardings on params of
    the same structure) — each leaf is placed onto its sharding as soon as it
    is assembled. `quantize="int8"`: matmul leaves are quantized HOST-side
    (ops/quant.py) so the bf16 copy never reaches HBM — required for the
    llama3:70b-on-v5e-8 memory budget (BASELINE config #3).
    """
    from gridllm_tpu.models import hf_layout
    from gridllm_tpu.ops.quant import NO_QUANT_SUBTREES, quantize_np_leaf

    idx = _open_safetensors(path)

    def place(pathkeys: tuple[str, ...], arr: np.ndarray):
        if quantize == "int8" and pathkeys[0] not in NO_QUANT_SUBTREES:
            out = quantize_np_leaf(pathkeys[-1], arr)
            if not hasattr(out, "q"):
                out = jnp.asarray(out, dtype)
        else:
            out = jnp.asarray(arr, dtype)
        if shardings is not None:
            s = shardings
            for k in pathkeys:
                s = s[k]
            out = jax.device_put(out, s)
        log.debug("loaded leaf", leaf="/".join(pathkeys), shape=list(out.shape))
        return out

    def get(name: str) -> np.ndarray:
        return idx[name]()

    if cfg.family == "bert_embed":
        from gridllm_tpu.models import bert_embed

        return bert_embed.from_getter(cfg, get, dtype, place)
    if cfg.family == "llava":
        from gridllm_tpu.models import llava

        return llava.from_getter(cfg, get, dtype, place)
    return hf_layout.to_pytree(cfg, get, _name_map(cfg), dtype, place)


def save_checkpoint(params: Any, cfg: ModelConfig, path: str) -> None:
    """Write our pytree back out as a single HF-layout safetensors file
    (round-trip for tests + lets checkpoints produced here load in HF)."""
    from safetensors.numpy import save_file

    from gridllm_tpu.models import hf_layout

    os.makedirs(path, exist_ok=True)
    if cfg.family == "bert_embed":
        from gridllm_tpu.models import bert_embed

        out = bert_embed.to_hf_tensors(params, cfg)
    else:
        out = hf_layout.to_hf_tensors(params, cfg, _name_map(cfg))
    save_file(out, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_name": cfg.name}, f)
