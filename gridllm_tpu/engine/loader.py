"""Checkpoint loading: HF safetensors directory → (sharded) param pytree.

SURVEY.md §5.4: the reference has no model checkpointing (models live in
Ollama's store); this is the rebuild's native replacement, and §7 names
"HF checkpoint → sharded-layout loading without host-RAM blowups" a hard
part. Approach:

- safetensors are opened with framework="numpy" → tensors are lazily
  mmap-backed; nothing materializes until sliced.
- per-leaf placement: each finished leaf is `jax.device_put` to its
  NamedSharding immediately, so peak host RAM ≈ one stacked leaf group
  (largest: w_down L×F×E), not the whole checkpoint.
- dtype conversion happens on the way in (bf16 by default).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu.models.configs import ModelConfig
from gridllm_tpu.utils.logging import get_logger

log = get_logger("engine.loader")


def _open_safetensors(path: str) -> dict[str, Callable[[], np.ndarray]]:
    """Map HF tensor name → thunk returning the numpy array (mmap-lazy)."""
    from safetensors import safe_open

    files = sorted(
        os.path.join(path, f) for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no *.safetensors under {path}")
    index: dict[str, Callable[[], np.ndarray]] = {}
    for f in files:
        handle = safe_open(f, framework="numpy")
        for name in handle.keys():  # noqa: SIM118 — safe_open has no __iter__
            index[name] = (lambda h, n: lambda: h.get_tensor(n))(handle, name)
    return index


def _name_map(cfg: ModelConfig) -> dict[str, tuple[str, bool]]:
    """The family's HF layout contract — owned by the model module
    (llama.HF_MAP / mixtral.HF_MAP) so loader and state-dict converter
    cannot drift. {} is the layer index; an extra {} the expert index."""
    if cfg.family == "mixtral":
        from gridllm_tpu.models import mixtral

        return mixtral.HF_MAP
    from gridllm_tpu.models import llama

    return llama.HF_MAP


def load_checkpoint(
    cfg: ModelConfig,
    path: str,
    dtype=jnp.bfloat16,
    shardings: Any | None = None,
) -> Any:
    """Load an HF checkpoint dir into our stacked-layer pytree.

    `shardings`: optional pytree (from parallel.param_shardings on params of
    the same structure) — each leaf is placed onto its sharding as soon as it
    is assembled.
    """
    idx = _open_safetensors(path)
    L = cfg.num_layers
    name_map = _name_map(cfg)

    def place(pathkeys: tuple[str, ...], arr: np.ndarray):
        arr = jnp.asarray(arr, dtype)
        if shardings is not None:
            s = shardings
            for k in pathkeys:
                s = s[k]
            arr = jax.device_put(arr, s)
        return arr

    def leaf(name: str) -> tuple[str, ...]:
        return ("layers", name)

    def load_stacked(name: str, tmpl: str, transpose: bool):
        if "experts" in tmpl:
            def one_layer(i):
                es = [idx[tmpl.format(i, e)]() for e in range(cfg.num_experts)]
                es = [e.T if transpose else e for e in es]
                return np.stack(es)
        else:
            def one_layer(i):
                w = idx[tmpl.format(i)]()
                return w.T if transpose else w
        stacked = np.stack([np.asarray(one_layer(i), np.float32) for i in range(L)])
        out = place(leaf(name), stacked)
        log.debug("loaded leaf", leaf=name, shape=list(out.shape))
        return out

    params: dict[str, Any] = {
        "embed": place(("embed",), np.asarray(idx["model.embed_tokens.weight"]())),
        "layers": {},
        "final_norm": place(("final_norm",), np.asarray(idx["model.norm.weight"]())),
    }
    for name, (tmpl, transpose) in name_map.items():
        params["layers"][name] = load_stacked(name, tmpl, transpose)
    if not cfg.tie_embeddings:
        params["lm_head"] = place(("lm_head",), np.asarray(idx["lm_head.weight"]()).T)
    return params


def save_checkpoint(params: Any, cfg: ModelConfig, path: str) -> None:
    """Write our pytree back out as a single HF-layout safetensors file
    (round-trip for tests + lets checkpoints produced here load in HF)."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    name_map = _name_map(cfg)
    out: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    for name, (tmpl, transpose) in name_map.items():
        stacked = np.asarray(params["layers"][name], np.float32)
        for i in range(cfg.num_layers):
            if "experts" in tmpl:
                for e in range(cfg.num_experts):
                    w = stacked[i, e]
                    out[tmpl.format(i, e)] = w.T.copy() if transpose else w.copy()
            else:
                w = stacked[i]
                out[tmpl.format(i)] = w.T.copy() if transpose else w.copy()
    if not cfg.tie_embeddings:
        out["lm_head.weight"] = np.asarray(params["lm_head"], np.float32).T.copy()
    save_file(out, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump({"model_name": cfg.name}, f)
