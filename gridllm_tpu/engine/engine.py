"""Continuous-batching inference engine.

Replaces the reference's external Ollama daemon (SURVEY.md §0: the entire
compute path was `client/src/services/OllamaService.ts` HTTP calls). Design
(SURVEY.md §7 steps 4-5):

- One static device state: paged KV pool shared by `max_slots` concurrent
  requests, per-slot sampler params, per-slot context token counts. All
  compiled functions are shape-static; prompts pad to the smallest bucket.
- Continuous batching: requests join/leave the batch between decode steps
  (the reference capped workers at 1 job, server/src/config/index.ts:31 —
  here concurrency is a device-state property, not a scheduler constant).
- Decode runs in BLOCKS of `decode_block` fused steps (lax.scan of
  model step + sampler + bookkeeping inside ONE jit call), with up to
  `pipeline_depth` blocks dispatched ahead of the host. Round-3's 76 tok/s
  was dominated by per-step host round-trips (~60-150 ms each over the
  device transport vs ~11 ms of device compute); blocks amortize the fetch
  and the pipeline hides it entirely in steady state. Host-side bookkeeping
  (EOS, stop sequences, num_predict) lags the device by up to
  decode_block × pipeline_depth wasted steps per finishing stream — pure
  compute waste, never a correctness hazard: page-table sentinels drop
  out-of-capacity writes and fetched post-finish tokens are discarded.
- Admission never synchronizes: the prefill samples the first token on
  device and folds it into the step state; the host first sees it in the
  NEXT block's row 0 (blocks return [K+1, S] — input tokens + K sampled),
  matched by a per-slot dispatch-generation tag.
- Speculative decoding (ISSUE 5, default on via GRIDLLM_SPEC_DECODE):
  the host drafts up to GRIDLLM_SPEC_K candidate tokens per slot
  (prompt-lookup n-gram, ops/spec.py), ONE batched verify forward
  (mod.verify_step) scores the whole [S, K+1] candidate block against
  the paged prefix, and the accept/reject kernel (ops/sampling.py
  spec_accept) keeps the longest accepted prefix + one corrected token
  — 1..K+1 tokens per step, greedy streams byte-identical to spec-off,
  sampled streams exactly rejection-sampled. Candidate KV is written
  optimistically and rolled back by length (ops/kvcache.py). The spec
  path fetches every verify step (the next draft depends on this step's
  tokens), trading the block pipeline for multi-token steps — the win
  when the model forward dominates step time and the workload repeats.
- Ollama semantics honored at this layer: sampler option surface (via
  ops/sampling), `seed` determinism per request (unseeded requests draw a
  random seed host-side — seed 0 is NOT a fixed default), real timing
  fields in nanoseconds (the reference zeroed them, SURVEY.md §2.8),
  `stop` sequences, `num_predict`, EOS from the tokenizer.

repeat_penalty follows llama.cpp's penalty_last_n semantics: it applies
over the last `repeat_last_n` context tokens (prompt + generated; -1 →
the request's context size, 0 → disabled), maintained as a device-side
window buffer (ops/sampling.py) capped at EngineConfig.repeat_window.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from collections import deque
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gridllm_tpu import faults
from gridllm_tpu.engine.tokenizer import DetokState, Tokenizer, get_tokenizer
from gridllm_tpu.models import llama
from gridllm_tpu.models.configs import ModelConfig, get_config
from gridllm_tpu.obs import SIZE_BUCKETS, default_flight_recorder, default_registry
from gridllm_tpu.obs.perf import (
    DEVICE_STEP_SECONDS,
    DISPATCH_SECONDS,
    HOST_SCHED_SECONDS,
    RecompileTripwire,
)
from gridllm_tpu.ops.attention import ragged_attention_enabled
from gridllm_tpu.ops.kvcache import (
    PagedKVCache,
    PageAllocator,
    QuantPages,
    commit_tree_path,
    rollback_to_length,
)
from gridllm_tpu.ops.kvtier import set_tier_gauges
from gridllm_tpu.ops.sampling import (
    SamplingParams,
    sample_tokens,
    spec_accept,
    spec_accept_tree,
    window_push,
    window_set_slot,
)
from gridllm_tpu.ops.spec import (
    DraftModelDrafter,
    make_drafter,
    tree_ancestor_mask,
    tree_depths,
    tree_topology,
)
from gridllm_tpu.parallel.mesh import MeshConfig, build_mesh
from gridllm_tpu.parallel.sharding import shard_cache, shard_params
from gridllm_tpu.utils.config import env_bool, env_int, env_str
from gridllm_tpu.utils.logging import get_logger

log = get_logger("engine")

# Engine-plane instruments (process-global registry → the worker's
# /metrics). Updated from the runner thread / step() only, so the metric
# locks are uncontended on the hot path.
_OBS = default_registry()
_TOKENS_TOTAL = _OBS.counter(
    "gridllm_engine_tokens_total",
    "Tokens processed, by model and kind (prefill = prompt tokens "
    "dispatched, decode = tokens sampled and ingested).",
    ("model", "kind"),
)
_STEP_DURATION = _OBS.histogram(
    "gridllm_engine_step_duration_seconds",
    "Per-decode-step wall time (fused-block fetch time divided by the "
    "block's step count), by model.",
    ("model",),
)
_BATCH_OCCUPANCY = _OBS.histogram(
    "gridllm_engine_batch_occupancy",
    "Active slots at each decode-block dispatch, by model.",
    ("model",), buckets=SIZE_BUCKETS,
)
_KV_PAGES_USED = _OBS.gauge(
    "gridllm_engine_kv_pages_used", "KV page-pool pages in use, by model.",
    ("model",),
)
_KV_PAGES_FREE = _OBS.gauge(
    "gridllm_engine_kv_pages_free", "KV page-pool pages free, by model.",
    ("model",),
)
_KV_PAGES_CACHED = _OBS.gauge(
    "gridllm_engine_kv_pages_cached",
    "KV page-pool pages parked in the prefix-cache reuse LRU (refcount 0, "
    "evictable), by model.",
    ("model",),
)
_PREFIX_HIT_RATE = _OBS.gauge(
    "gridllm_prefix_cache_hit_rate",
    "Cumulative prompt-page prefix-cache hit rate (hits / (hits+misses)), "
    "by model.",
    ("model",),
)
# elastic serving (ISSUE 20): cold-start cost, by how the weights arrived
# — "snapshot" (host-RAM weight tier hit), "checkpoint" (safetensors
# re-read), "init" (fresh random init). The ModelColdStartSlow alert keys
# on this series: snapshot restores taking checkpoint-class time mean the
# tier is thrashing or the host is paging.
_MODEL_LOAD_SECONDS = _OBS.histogram(
    "gridllm_model_load_seconds",
    "Engine weight-load wall time at (re)construction, by model and "
    "weight source (snapshot = host-RAM tier hit, checkpoint = disk "
    "safetensors, init = fresh init).",
    ("model", "source"),
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
)

# Persistent XLA compilation cache (ISSUE 20): wiring the jax config at
# first engine construction (idempotent, process-global) means a
# swapped-in model replays its warmup compiles from disk instead of
# re-running XLA — the compile half of fast cold-start. Guarded: an old
# jax without the knobs degrades to no cache, never a startup failure.
_compile_cache_lock = threading.Lock()
_compile_cache_dir: str | None = None


def ensure_compile_cache() -> str | None:
    """Point jax at GRIDLLM_COMPILE_CACHE_DIR (once). Returns the active
    cache dir, or None when disabled/unsupported."""
    global _compile_cache_dir
    with _compile_cache_lock:
        if _compile_cache_dir is not None:
            return _compile_cache_dir or None
        cache_dir = env_str("GRIDLLM_COMPILE_CACHE_DIR")
        _compile_cache_dir = cache_dir or ""
        if not cache_dir:
            return None
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # tiny-model compiles are fast and small — cache them anyway,
            # or the CPU tests/bench never exercise the persistent path
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception as e:  # pragma: no cover - jax version drift
            log.warning("compile cache unavailable", error=str(e))
            _compile_cache_dir = ""
            return None
        log.info("persistent compile cache enabled", dir=cache_dir)
        return cache_dir
# speculative decoding (ISSUE 5): draft-token accounting. proposed =
# drafts sent to a verify step, accepted = drafts the model agreed with,
# rejected = proposed - accepted (a draft discarded because an EARLIER one
# missed counts as rejected too — it was wasted verify work either way).
# The per-step histogram is the acceptance-collapse signal: spec on with
# rate ≈ 0 means drafting is pure overhead (prometheus alert). The
# "drafter" label (ISSUE 18) splits the series by drafting backend —
# "ngram" (prompt-lookup) vs "model" (draft-model tree) — so an A/B or a
# collapse localizes to the backend that caused it.
_SPEC_PROPOSED = _OBS.counter(
    "gridllm_spec_proposed_tokens_total",
    "Draft tokens proposed to speculative verify steps, by model and "
    "drafter kind.",
    ("model", "drafter"),
)
_SPEC_ACCEPTED = _OBS.counter(
    "gridllm_spec_accepted_tokens_total",
    "Draft tokens accepted by speculative verify steps, by model and "
    "drafter kind.",
    ("model", "drafter"),
)
_SPEC_REJECTED = _OBS.counter(
    "gridllm_spec_rejected_tokens_total",
    "Draft tokens rejected (or discarded past the first miss) by "
    "speculative verify steps, by model and drafter kind.",
    ("model", "drafter"),
)
_SPEC_ACCEPT_RATE = _OBS.histogram(
    "gridllm_spec_acceptance_rate",
    "Per-verify-step draft acceptance rate (accepted/proposed, over steps "
    "with at least one proposed draft), by model and drafter kind.",
    ("model", "drafter"), buckets=(0.0, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
)
# flight recorder (obs/flightrec.py): lifecycle events land in the "engine"
# ring; block dispatches are SAMPLED (one record per _FLIGHT_SAMPLE
# generations) so the hot loop stays a deque append every few dozen steps
_FLIGHTREC = default_flight_recorder()
_FLIGHT_SAMPLE = 16


def _model_module(cfg: ModelConfig):
    if cfg.family == "mixtral":
        from gridllm_tpu.models import mixtral

        return mixtral
    if cfg.family == "bert_embed":
        from gridllm_tpu.models import bert_embed

        return bert_embed
    if cfg.family == "llava":
        from gridllm_tpu.models import llava

        return llava
    if cfg.family == "gemma2":
        from gridllm_tpu.models import gemma

        return gemma
    return llama  # llama, qwen2, qwen3 share the decoder skeleton


@dataclasses.dataclass
class EngineConfig:
    model: str
    checkpoint_path: str | None = None   # None → random init (tests/synthetic bench)
    tokenizer: str | None = None         # None/"byte" → ByteTokenizer
    dtype: str = "bfloat16"
    # "int8" → per-out-channel weight-only quantization of the matmul
    # leaves (ops/quant.py). Halves weight HBM + decode bandwidth; the
    # only way llama3:70b fits a v5e-8 slice (BASELINE config #3).
    quantize: str | None = None
    max_slots: int = 8
    page_size: int = 64
    num_pages: int = 1024
    max_pages_per_slot: int = 128
    prefill_buckets: tuple[int, ...] = (64, 256, 1024, 4096)
    mesh: MeshConfig | None = None       # None → no mesh (single device)
    max_queue: int = 512
    seed: int | None = None              # engine-level seed for unseeded reqs
    embed_batch: int = 32                # max texts per embedding forward
    # prompts longer than this prefill in fixed-size chunks against the
    # cached prefix (ONE compiled chunk program for all lengths) instead of
    # padding to the next bucket; rounded down to a multiple of page_size
    # (the in-place page-write kernel requires page-aligned chunk starts)
    prefill_chunk: int = 1024
    # decode steps fused per dispatch in the runner loop (step() always
    # uses 1 — exact per-token semantics for tests/sync callers)
    decode_block: int = 8
    # blocks dispatched ahead of the host fetch (2 = fetch block N while
    # block N+1 computes; enough to hide the transfer latency)
    pipeline_depth: int = 2
    # prefills admitted per block boundary while other streams are running
    # (idle engines admit everything; bounding protects running streams'
    # inter-token latency from admission bursts — VERDICT r03 #3)
    admit_per_block: int = 2
    # static width of the per-slot repeat-penalty window buffer;
    # repeat_last_n (and its -1 → num_ctx resolution) clamps to this
    repeat_window: int = 256
    # automatic prefix caching (ISSUE 3): completed requests park their
    # full KV pages in a content-addressed reuse LRU; new requests skip
    # prefill over their longest cached prefix. None → env
    # GRIDLLM_PREFIX_CACHE (default on; "0" disables — bit-identical to
    # the pre-cache engine). prefix_cache_pages bounds the LRU (None →
    # GRIDLLM_PREFIX_CACHE_PAGES, default unbounded; 0 disables — same
    # semantics as PageAllocator.cache_pages; negative → unbounded).
    prefix_cache: bool | None = None
    prefix_cache_pages: int | None = None
    # speculative decoding (ISSUE 5): n-gram (prompt-lookup) drafting +
    # batched K-token verification. None → env GRIDLLM_SPEC_DECODE
    # (default on; "0" disables — exact legacy decode path). spec_k is the
    # speculation depth K (drafted tokens verified per step; the verify
    # block is [S, K+1] — candidates plus the committed last token), FIXED
    # per process so every shape stays static and the recompile tripwire
    # stays green. None → GRIDLLM_SPEC_K (default 4); 0 also disables.
    # Greedy streams are byte-identical spec-on vs spec-off; sampled
    # streams keep the target distribution via rejection sampling
    # (ops/sampling.py spec_accept).
    spec_decode: bool | None = None
    spec_k: int | None = None
    # draft-model + tree speculation (ISSUE 18). draft_model names a
    # registered config for a tiny SAME-TOKENIZER draft model loaded next
    # to the target (sharing the device mesh) — "" keeps n-gram drafting.
    # None → GRIDLLM_SPEC_DRAFT_MODEL. draft_checkpoint is its weight
    # path ("" → fresh init, the tier-1/bench path); None →
    # GRIDLLM_SPEC_DRAFT_CHECKPOINT. With a draft model active the verify
    # block generalizes from the [S, K+1] chain to a static token TREE:
    # a depth-K greedy chain plus (spec_tree_width - 1) first-level
    # sibling alternatives, verified in one tree-masked forward
    # (ops/spec.py tree_topology). width 1 = pure chain; None →
    # GRIDLLM_SPEC_TREE_WIDTH (default 2). An incompatible draft model
    # (vocab mismatch, no verify/decode path) logs and falls back to
    # n-gram rather than failing the engine.
    draft_model: str | None = None
    draft_checkpoint: str | None = None
    spec_tree_width: int | None = None
    # tiered KV cache (ISSUE 11). kv_host_bytes: host-RAM tier capacity —
    # prefix-cache pages evicted from HBM spill there (wire-codec encoded)
    # and page back in on match_prefix hits; the capacity IS the enable
    # (0 = off). None → GRIDLLM_KV_HOST_BYTES. kv_spill_int8: int8-
    # quantize fp pages on spill (scale-per-page; halves host bytes) —
    # 0 spills raw bytes so tier-on streams stay byte-identical to
    # tier-off. None → GRIDLLM_KV_SPILL_INT8 (default on). kv_int8:
    # resident int8 KV pool (QuantPages — per-row scales, dequant
    # epilogue in the attention read path), halving KV HBM. None →
    # GRIDLLM_KV_INT8 (default off). Single-device pools only: meshes
    # keep the fp layout.
    kv_host_bytes: int | None = None
    kv_spill_int8: bool | None = None
    kv_int8: bool | None = None


@dataclasses.dataclass
class GenerationRequest:
    id: str
    prompt: str | None = None
    prompt_ids: list[int] | None = None  # pre-tokenized (Ollama `context` path)
    options: dict[str, Any] = dataclasses.field(default_factory=dict)
    raw: bool = False                    # skip BOS when prompt_ids is None
    images: list[str] | None = None      # base64 images (vision models only)
    # disaggregated prefill (ISSUE 7): finish at the FIRST host-visible
    # token with done_reason "export" — the prompt's KV pages land in the
    # prefix cache (free+register, exactly the normal finish path) ready
    # for export_prefix_pages; no text is detokenized or streamed
    export_only: bool = False
    # decode resume (ISSUE 9): token ids a previous attempt already
    # generated. They are appended to the prompt for prefill/alloc (so a
    # cached/migrated prefix makes resume cheap) but seeded into the
    # slot's GENERATED state — detok/stop/num_predict/eval_count all
    # continue exactly where the lost worker left off, and the sampler's
    # (seed, step) chain restarts at step = len(resume_ids), so a greedy
    # or seeded stream is byte-identical to the undisturbed run.
    resume_ids: list[int] | None = None
    # chars of the resumed text already delivered downstream: emission
    # restarts past this offset, so clients never see a duplicate char
    resume_sent: int = 0
    # write the (generated ids, text) resume watermark every N surviving
    # tokens (0 = never): each write copies the full generated list, so
    # an every-token cadence would be O(n^2) on the engine hot loop
    snapshot_every: int = 0
    # called from the engine loop: (text_delta, done, result|None)
    on_chunk: Callable[[str, bool, "GenerationResult | None"], None] | None = None


@dataclasses.dataclass
class GenerationResult:
    id: str
    text: str = ""
    token_ids: list[int] = dataclasses.field(default_factory=list)
    context: list[int] = dataclasses.field(default_factory=list)
    done_reason: str = "stop"
    prompt_eval_count: int = 0
    # prompt tokens served from the prefix cache (prefill skipped); always
    # ≤ prompt_eval_count, 0 with caching off
    cached_tokens: int = 0
    prompt_eval_duration_ns: int = 0
    eval_count: int = 0
    eval_duration_ns: int = 0
    load_duration_ns: int = 0
    total_duration_ns: int = 0
    # speculative decoding (ISSUE 5): drafts proposed/accepted for this
    # request's verify steps (both 0 with speculation off)
    spec_proposed: int = 0
    spec_accepted: int = 0
    # usage attribution (ISSUE 16): device-seconds this request's share of
    # decode steps consumed, and KV page-occupancy (pages held × resident
    # wall seconds) — the raw cost signals behind gridllm_usage_*
    decode_device_s: float = 0.0
    kv_page_s: float = 0.0
    retryable: bool = True  # meaningful when done_reason == "error"
    # when done_reason == "error": the failure message. `text` stays the
    # partial output actually generated, so a streaming client's concatenated
    # deltas always equal `text` (they must never be retroactively replaced
    # by an error string).
    error: str = ""


class _Slot:
    __slots__ = (
        "req", "ids", "prompt_len", "generated", "detok", "text", "emitted_len",
        "num_predict", "stop_seqs", "eos_ids", "capacity", "joined_gen",
        "cached_tokens", "spec_proposed", "spec_accepted", "export_only",
        "snapshot",
        "t_start", "t_prefill_ns", "t_first_decode", "t_last_ingest",
        "t_admit_wall", "pages_held", "device_s",
    )

    def __init__(self, req: GenerationRequest, ids: list[int], capacity: int,
                 num_predict: int, stop_seqs: list[str], eos_ids: frozenset[int]):
        self.req = req
        self.ids = ids                   # prompt ids (grows with generation)
        self.prompt_len = len(ids)
        self.generated: list[int] = []
        self.detok = DetokState()
        self.text = ""
        self.emitted_len = 0             # chars of `text` already sent out
        self.num_predict = num_predict
        self.stop_seqs = stop_seqs
        self.eos_ids = eos_ids
        self.capacity = capacity         # max total tokens this slot may hold
        self.cached_tokens = 0           # prompt tokens reused from the prefix cache
        self.spec_proposed = 0           # drafts sent to verify steps
        self.spec_accepted = 0           # drafts the model accepted
        self.export_only = req.export_only  # disagg prefill: stop at token 1
        # last consistent (generated ids, text) pair, published as the
        # crash-resume watermark (ISSUE 9). Written only by the engine
        # thread as ONE immutable tuple per surviving token, so a reader
        # on another thread always sees a matched pair.
        self.snapshot: tuple[list[int], str] | None = None
        # dispatch generation of the FIRST decode block that will see this
        # slot: its row 0 (block-input tokens) carries the prefill-sampled
        # token; blocks with a lower generation predate the slot (or belong
        # to the slot's previous occupant) and are skipped for it
        self.joined_gen = 0
        self.t_start = time.perf_counter_ns()
        self.t_prefill_ns = 0
        self.t_first_decode = 0
        self.t_last_ingest = 0.0  # epoch seconds of last host-visible token
        # usage attribution (ISSUE 16)
        self.t_admit_wall = time.time()  # wall clock at admission
        self.pages_held = 0              # KV pages allocated to this slot
        self.device_s = 0.0              # accumulated decode device-second share

    def holdback(self) -> int:
        """Chars at the tail of `text` that could still become a stop
        sequence (longest proper-prefix match) — must not be emitted yet."""
        hold = 0
        for seq in self.stop_seqs:
            for k in range(min(len(seq), len(self.text)), 0, -1):
                if self.text.endswith(seq[:k]):
                    hold = max(hold, k)
                    break
        return hold


class InferenceEngine:
    """Synchronous core; drive with step() (tests) or the worker's async
    facade (worker/service.py wraps step() in a thread executor)."""

    def __init__(self, config: EngineConfig):
        ensure_compile_cache()
        self.config = config
        try:
            self.cfg = get_config(config.model)
        except KeyError:
            if not config.checkpoint_path:
                raise
            # unregistered name + checkpoint dir → read the HF config.json
            # (serve any local HF-layout checkpoint, no registry edit needed)
            from gridllm_tpu.models.configs import config_from_hf_dir

            self.cfg = config_from_hf_dir(config.model, config.checkpoint_path)
        self.mod = _model_module(self.cfg)
        self.embedding_only = self.cfg.family == "bert_embed"
        self.tokenizer: Tokenizer = get_tokenizer(
            config.tokenizer, self.cfg.vocab_size
        )
        self.mesh = build_mesh(config.mesh) if config.mesh else None
        # family-specific mesh constraints fail HERE (engine startup), not
        # at the first request's trace (e.g. gemma2 has no sp variant)
        getattr(self.mod, "validate_mesh", lambda *_: None)(self.cfg, self.mesh)
        if self.mesh is not None and self.mesh.shape.get("pp", 1) > 1:
            # tp/dp/ep/sp meshes run the kernels inside a full-manual
            # shard_map at the kernel boundary (ops.kvcache.kernel_mesh_axis
            # — kv-heads split over tp, VERDICT r04 #2). The pipeline's
            # partial-manual pp region is the remaining exception: it pins
            # the jnp paths. Per-engine (on the cfg copy) so co-hosted
            # single-device engines keep their kernels.
            self.cfg = dataclasses.replace(self.cfg, use_pallas=False)
        self._rng = random.Random(config.seed)
        # ragged paged attention (ISSUE 6), resolved ONCE at startup: the
        # pool layout (_pool_head_dim) and the admission path both depend
        # on it, and flipping mid-serving would mix incompatible layouts
        self._ragged = ragged_attention_enabled()
        # prefix-cache capacity, resolved ONCE (env reads at startup, not
        # per admission): 0 = off, < 0 = unbounded reuse LRU, > 0 = cap.
        # sp > 1 prefills whole prompts via ring attention — there is no
        # chunked path to start mid-prompt from, so caching is off there.
        sp_prefill = self.mesh is not None and self.mesh.shape.get("sp", 1) > 1
        self._prefix_cache_cap = (
            0 if sp_prefill else self._resolve_prefix_cache_cap()
        )
        # tiered KV cache (ISSUE 11), both knobs resolved ONCE at startup:
        # the pool layout depends on kv_int8, and the host tier outlives
        # device-state resets (content-addressed pages stay valid)
        self._kv_int8 = self._resolve_kv_int8()
        self.host_tier = self._build_host_tier()
        self._lock = threading.Lock()
        # allocator guard (ISSUE 7): page allocation/free runs on the
        # driving thread (admission/finish), while KV export/import runs
        # on the worker's executor threads — both mutate PageAllocator
        # state, so every allocator mutation sits under this lock. Lock
        # order where both are held: _alloc_lock BEFORE dispatch_lock.
        self._alloc_lock = threading.RLock()
        self._kv_install_fn: Callable | None = None  # lazy (ISSUE 7 import)
        self._pending: deque[GenerationRequest] = deque()
        self._slots: dict[int, _Slot] = {}
        self._free_slots = list(range(config.max_slots - 1, -1, -1))
        # dispatch pipeline state (runner thread / step()):
        self._gen = 0                     # generation counter of dispatched blocks
        # (gen, toks, k, dispatch perf_counter ts)
        self._inflight: deque[tuple[int, Any, int, float]] = deque()
        # recompile tripwire (obs/perf.py): every jitted entry point is
        # wrapped; armed after the first naturally completed request, at
        # which point any new compile signature is a flagged steady-state
        # recompile (counter + flight-recorder event with the shapes)
        self.perf = RecompileTripwire(context=self.cfg.name)
        self._perf_armed = False
        # speculative decoding (ISSUE 5): depth resolved in _build_fns
        # (it needs the resolved family module); 0 = off. spec_stats are
        # cumulative host-side totals (bench + batch_state read them).
        self._spec_k = 0
        self._drafter = None
        self._tree_width = 1
        self.spec_stats = {"steps": 0, "proposed": 0, "accepted": 0,
                           "emitted": 0, "draft_ns": 0}
        # step-time decomposition state (runner thread only)
        self._t_prev_fetch: float | None = None
        self._t_ingest_done: float | None = None
        # cross-thread control requests: ("cancel" | "suspend", req_id)
        self._ctl: deque[tuple[str, str]] = deque()
        self._work = threading.Condition()
        self._runner: threading.Thread | None = None
        self._runner_stop = threading.Event()
        # Multi-host SPMD (SURVEY §5.8b): in a worker group every process
        # must issue the SAME jitted computations in the same order or the
        # first cross-host collective deadlocks. The liaison's engine
        # emits one record per device-dispatching action (admit / block /
        # deact / reset) through `plan_sink`; follower engines replay them
        # via apply_plan_op. All record payloads are plain host data
        # (token ids, page rows, resolved sampler values incl. the seed),
        # so replay is bit-identical. `dispatch_lock` makes (emission,
        # dispatch) atomic; worker/main.py shares ONE lock across all of a
        # slice's engines so the liaison's cross-engine dispatch order
        # equals the plan order followers replay (embed dispatches from
        # the executor thread serialize through it too).
        self.plan_sink: Callable[[dict[str, Any]], None] | None = None
        self.dispatch_lock: threading.RLock = threading.RLock()
        self.prewarm_duration_ns = 0
        self._load()
        self._build_fns()
        if env_bool("GRIDLLM_PREWARM_COMPILES") and not self.embedding_only:
            self.prewarm()

    # ---------------------------------------------------------- state setup

    def _load(self) -> None:
        c, mc = self.config, self.cfg
        dtype = jnp.dtype(c.dtype)
        t0 = time.perf_counter_ns()
        if c.quantize and c.quantize != "int8":
            raise ValueError(f"unknown quantize mode: {c.quantize!r}")
        if c.quantize and self.embedding_only:
            # bert_embed consumes its weights with plain dots (no qdot
            # routing) — loud failure beats a TypeError mid-forward
            raise ValueError(
                f"{self.cfg.name}: quantize is not supported for "
                "embedding-only models"
            )

        def _maybe_quant(p):
            if c.quantize == "int8":
                from gridllm_tpu.ops.quant import quantize_params

                return quantize_params(p)
            return p

        # Weight snapshot tier (ISSUE 20): a parked host copy of this
        # exact checkpoint identity skips the safetensors re-read (or
        # re-init) — host→device transfer only. An injected restore fault
        # degrades to the disk/init path below, never a wedged load.
        snap = None
        from gridllm_tpu.engine.loader import weight_snapshot_tier

        tier = weight_snapshot_tier()
        if tier.enabled:
            try:
                faults.inject("swap.snapshot_restore")
                snap = tier.restore(self.snapshot_key())
            except faults.InjectedFault:
                log.warning("weight snapshot restore fault; degrading to "
                            "disk load", model=self.cfg.name)
                snap = None
        if snap is not None:
            # snapshots were parked post-quantization — re-materialize on
            # device as-is (no re-quantize), then reshard if meshed
            self.params = jax.tree_util.tree_map(jnp.asarray, snap)
            if self.mesh is not None:
                self.params = shard_params(self.params, self.mesh)
            self.load_source = "snapshot"
        elif c.checkpoint_path:
            from gridllm_tpu.engine.loader import load_checkpoint
            from gridllm_tpu.parallel.sharding import param_shardings

            shardings = None
            if self.mesh is not None:
                proto = jax.eval_shape(
                    lambda: _maybe_quant(
                        self.mod.init_params(mc, jax.random.PRNGKey(0), dtype)
                    )
                )
                shardings = param_shardings(proto, self.mesh)
            self.params = load_checkpoint(
                mc, c.checkpoint_path, dtype, shardings, quantize=c.quantize
            )
            self.load_source = "checkpoint"
        else:
            self.params = _maybe_quant(
                self.mod.init_params(mc, jax.random.PRNGKey(0), dtype)
            )
            if self.mesh is not None:
                self.params = shard_params(self.params, self.mesh)
            self.load_source = "init"
        if self.embedding_only:
            # no generation state: encoder families have no KV cache,
            # sampler, or decode loop — just the pooled-forward embed path
            self.load_duration_ns = time.perf_counter_ns() - t0
            self.max_context = mc.max_seq_len
            self._set_buckets()
            _MODEL_LOAD_SECONDS.observe(
                self.load_duration_ns / 1e9,
                model=self.cfg.name, source=self.load_source,
            )
            return
        self._init_device_state()
        self.load_duration_ns = time.perf_counter_ns() - t0
        self.max_context = min(
            mc.max_seq_len, c.max_pages_per_slot * c.page_size
        )
        self._set_buckets()
        _MODEL_LOAD_SECONDS.observe(
            self.load_duration_ns / 1e9,
            model=self.cfg.name, source=self.load_source,
        )

    def snapshot_key(self) -> str:
        """Checkpoint identity for the weight snapshot tier: everything
        that changes the materialized param pytree. Two engines with the
        same key are guaranteed interchangeable weights."""
        c = self.config
        return "|".join((
            self.cfg.name,
            c.checkpoint_path or "init",
            str(c.dtype),
            c.quantize or "none",
            str(c.mesh or ""),
        ))

    def park_weights(self) -> bool:
        """Park this engine's params into the host snapshot tier (call
        after stop(), on the unload path). On success the device
        references are dropped so HBM weight gauges fall to zero."""
        from gridllm_tpu.engine.loader import weight_snapshot_tier

        tier = weight_snapshot_tier()
        if not tier.enabled or self.params is None:
            return False
        ok = tier.park(self.snapshot_key(), self.params)
        if ok:
            self.params = None
        return ok

    def prewarm(self) -> None:
        """Compile the serving shapes before the first real request: one
        inline greedy token compiles the smallest prefill bucket plus the
        decode step (and, with the persistent compile cache, writes them
        to disk for every future swap-in of this model). The recompile
        tripwire is re-disarmed afterwards so warmup accounting still
        treats the first REAL request as warmup."""
        if self.embedding_only or self.running:
            return
        t0 = time.perf_counter_ns()
        self.generate(GenerationRequest(
            id="prewarm",
            prompt_ids=[1],
            raw=True,
            options={"temperature": 0, "seed": 0, "num_predict": 1},
        ))
        self._perf_armed = False
        self.prewarm_duration_ns = time.perf_counter_ns() - t0
        log.info("engine prewarmed", model=self.cfg.name,
                 ms=self.prewarm_duration_ns // 1_000_000)

    def _set_buckets(self) -> None:
        # always include max_context so every admissible length maps to a
        # fixed padded shape — a length above the largest configured bucket
        # must not fall through to per-length recompiles
        self._buckets = sorted(
            {min(b, self.max_context) for b in self.config.prefill_buckets}
            | {self.max_context}
        )

    def _resolve_prefix_cache_cap(self) -> int:
        """EngineConfig overrides env; GRIDLLM_PREFIX_CACHE=0 disables,
        GRIDLLM_PREFIX_CACHE_PAGES bounds the reuse LRU (default unbounded
        — the whole page pool doubles as the cache, evicted on demand;
        0 ALSO disables, matching PageAllocator.cache_pages)."""
        on = self.config.prefix_cache
        if on is None:
            on = env_bool("GRIDLLM_PREFIX_CACHE")
        if not on:
            return 0
        pages = self.config.prefix_cache_pages
        if pages is None:
            pages = env_int("GRIDLLM_PREFIX_CACHE_PAGES")
        return max(pages, -1)

    def _resolve_kv_int8(self) -> bool:
        """Resident int8 KV pool (ISSUE 11). EngineConfig overrides env.
        Single-device pools only: a mesh shards the pool arrays and the
        QuantPages scale operands have no shard_map plumbing — meshes
        keep the fp layout (logged, not silent)."""
        on = self.config.kv_int8
        if on is None:
            on = env_bool("GRIDLLM_KV_INT8")
        if not on or self.embedding_only:
            return False
        if self.mesh is not None:
            log.info("int8 KV pool disabled: meshed pools keep the fp "
                     "layout", model=self.cfg.name)
            return False
        return True

    def _build_host_tier(self):
        """Host-RAM KV tier (ISSUE 11): the spill target behind the HBM
        reuse LRU. Needs the prefix cache (the spill unit IS a
        content-addressed cached page) and a process-local unsharded
        pool — the same constraints as KV migration."""
        cap = self.config.kv_host_bytes
        if cap is None:
            cap = env_int("GRIDLLM_KV_HOST_BYTES")
        if cap <= 0 or self.embedding_only:
            return None
        if self._prefix_cache_cap == 0 or self.mesh is not None:
            log.info("host KV tier disabled: needs the prefix cache and "
                     "an unsharded pool", model=self.cfg.name,
                     prefixCache=self._prefix_cache_cap != 0,
                     meshed=self.mesh is not None)
            return None
        spill_int8 = self.config.kv_spill_int8
        if spill_int8 is None:
            spill_int8 = env_bool("GRIDLLM_KV_SPILL_INT8")
        from gridllm_tpu.ops.kvtier import HostKVTier

        log.info("host KV tier enabled", model=self.cfg.name,
                 capacityBytes=cap,
                 spillDtype="int8-page" if spill_int8 else "raw")
        return HostKVTier(cap, model=self.cfg.name, spill_int8=spill_int8)

    def _resolve_spec_k(self) -> int:
        """Speculation depth K (0 = off). EngineConfig overrides env;
        GRIDLLM_SPEC_DECODE=0 disables, GRIDLLM_SPEC_K sets the depth
        (default 4 — a [S, 5] verify block). Fixed per process: K is a
        static jit arg, so a single verify program serves steady state."""
        on = self.config.spec_decode
        if on is None:
            on = env_bool("GRIDLLM_SPEC_DECODE")
        if not on:
            return 0
        k = self.config.spec_k
        if k is None:
            k = env_int("GRIDLLM_SPEC_K")
        return max(int(k), 0)

    def _resolve_draft_model(self) -> str:
        """Draft-model config name ("" = n-gram drafting, the default).
        EngineConfig overrides GRIDLLM_SPEC_DRAFT_MODEL."""
        name = self.config.draft_model
        if name is None:
            name = env_str("GRIDLLM_SPEC_DRAFT_MODEL")
        return (name or "").strip()

    def _resolve_tree_width(self) -> int:
        """Tree sibling fan-out at depth 1 (1 = pure chain). EngineConfig
        overrides GRIDLLM_SPEC_TREE_WIDTH. Clamped so the node budget
        1 + K + (width-1) is at least the root + chain."""
        w = self.config.spec_tree_width
        if w is None:
            w = env_int("GRIDLLM_SPEC_TREE_WIDTH")
        return max(int(w), 1)

    def _build_model_drafter(self, spec_k: int):
        """Construct the draft-model tree drafter (ISSUE 18), or None when
        no draft model is configured / the configured one is incompatible
        with the target — the caller then keeps the n-gram drafter, so a
        bad knob degrades speculation quality instead of failing serving.

        The draft model shares the target's mesh and dtype but owns a
        small fixed-stripe KV pool (DraftModelDrafter): per slot, enough
        pages for the engine's max_context plus the draft chain, page
        size matching the engine's."""
        name = self._resolve_draft_model()
        if not name:
            return None
        try:
            dcfg = get_config(name)
        except Exception:
            log.warning("draft model unknown; falling back to n-gram",
                        model=self.cfg.name, draftModel=name)
            return None
        dmod = _model_module(dcfg)
        if dcfg.vocab_size != self.cfg.vocab_size:
            # acceptance compares token ids — different vocabs make the
            # rejection test meaningless (and usually out-of-range)
            log.warning("draft model vocab mismatch; falling back to n-gram",
                        model=self.cfg.name, draftModel=name,
                        vocab=self.cfg.vocab_size, draftVocab=dcfg.vocab_size)
            return None
        if not (hasattr(dmod, "verify_step") and hasattr(dmod, "decode_step")):
            log.warning("draft model family lacks verify/decode steps; "
                        "falling back to n-gram",
                        model=self.cfg.name, draftModel=name)
            return None
        c = self.config
        dtype = jnp.dtype(c.dtype)
        ckpt = self.config.draft_checkpoint
        if ckpt is None:
            ckpt = env_str("GRIDLLM_SPEC_DRAFT_CHECKPOINT")
        ckpt = (ckpt or "").strip()
        if ckpt:
            from gridllm_tpu.engine.loader import load_checkpoint
            from gridllm_tpu.parallel.sharding import param_shardings

            shardings = None
            if self.mesh is not None:
                proto = jax.eval_shape(
                    lambda: dmod.init_params(dcfg, jax.random.PRNGKey(0),
                                             dtype)
                )
                shardings = param_shardings(proto, self.mesh)
            dparams = load_checkpoint(dcfg, ckpt, dtype, shardings)
        else:
            dparams = dmod.init_params(dcfg, jax.random.PRNGKey(0), dtype)
            if self.mesh is not None:
                dparams = shard_params(dparams, self.mesh)
        # pool sizing: the engine never drafts past its own max_context,
        # and the decode steps write ≤ spec_k rows past it
        mpps = -(-(self.max_context + spec_k + 1) // c.page_size)
        drafter = DraftModelDrafter(
            dmod, dcfg, dparams,
            max_slots=c.max_slots, page_size=c.page_size,
            max_pages_per_slot=mpps, mesh=self.mesh,
            ingest_width=max(env_int("GRIDLLM_SPEC_DRAFT_INGEST"), 1),
            dtype=dtype, wrap=self.perf.wrap,
        )
        log.info("draft-model speculation enabled", model=self.cfg.name,
                 draftModel=name, checkpoint=ckpt or "(fresh init)",
                 treeWidth=self._resolve_tree_width(),
                 draftPoolPages=c.max_slots * mpps)
        return drafter

    def _pool_head_dim(self) -> int:
        """Page-pool head dim: lane-padded to 128 when the Pallas kernels
        will run (Mosaic's alignment constraint), so d=64 models (qwen2.5
        class) keep the kernel decode path instead of the jnp gather
        (VERDICT r04 #5). Resolved with the SAME policy the op dispatchers
        use (_pallas_mode with the per-engine use_pallas override —
        ADVICE r05), so a config that forces kernels on where the env says
        off still gets the padded pool its kernels require. Interpret mode
        keeps the model's dim (tests stay fast) unless GRIDLLM_POOL_PAD=1
        forces the padded layout for coverage. The ops dispatchers
        pad/slice at the boundary."""
        from gridllm_tpu.ops.kvcache import (
            _pallas_mode,
            flat_lanes_ok,
            lane_pad_dim,
            local_kv_heads,
        )

        d = self.cfg.head_dim_
        use, interpret = _pallas_mode(self.cfg.use_pallas)
        if not use:
            return d
        if interpret and not env_bool("GRIDLLM_POOL_PAD"):
            return d
        kvh = local_kv_heads(self.cfg.num_kv_heads, self.mesh)
        if self._ragged and flat_lanes_ok(kvh, d):
            # ragged layout (ISSUE 6): page rows are lane-aligned viewed
            # flat ([ps, KVH*D] — PER tp SHARD, where kv heads split), so
            # the ragged kernel and the DMA write kernels run on the
            # UNPADDED pool — the lane-pad KV-byte overhead /admin/memory
            # itemized drops to zero
            return d
        return lane_pad_dim(d)

    def _init_device_state(self) -> None:
        """(Re)build all device-side mutable generation state: KV pool,
        page allocator, sampler params, context counts, token/active rows."""
        c, mc = self.config, self.cfg
        dtype = jnp.dtype(c.dtype)
        dpool = self._pool_head_dim()
        if dpool != mc.head_dim_:
            # lane padding multiplies KV bytes per page while num_pages is
            # config-fixed — say so at startup instead of silently serving
            # with a pool that costs dpool/d× the HBM the config budgeted
            # (ADVICE r05: d=64 models pay 2×)
            log.warning(
                "page pool lane-padded; KV bytes per page scaled",
                model=mc.name, head_dim=mc.head_dim_, pool_head_dim=dpool,
                kv_bytes_factor=round(dpool / mc.head_dim_, 2),
                num_pages=c.num_pages,
                hint=f"to keep KV HBM at the unpadded budget, set "
                     f"num_pages={int(c.num_pages * mc.head_dim_ / dpool)}",
            )
        if self._kv_int8:
            # resident int8 pool (ISSUE 11): QuantPages where the fp pool
            # arrays would sit — int8 values + one f32 scale per (layer,
            # page, row). Scales init to 1.0 so unwritten rows dequant to
            # exact zeros. Halves KV HBM; the write dispatchers quantize
            # per row at the boundary, the ragged kernel / jnp fallbacks
            # dequantize on read.
            shape = (mc.num_layers, c.num_pages, c.page_size,
                     mc.num_kv_heads, dpool)
            sshape = (mc.num_layers, c.num_pages, c.page_size)
            cache = PagedKVCache(
                k=QuantPages(jnp.zeros(shape, jnp.int8),
                             jnp.ones(sshape, jnp.float32)),
                v=QuantPages(jnp.zeros(shape, jnp.int8),
                             jnp.ones(sshape, jnp.float32)),
                page_table=jnp.full((c.max_slots, c.max_pages_per_slot),
                                    -1, jnp.int32),
                lengths=jnp.zeros((c.max_slots,), jnp.int32),
                page_size=c.page_size,
            )
        else:
            cache = PagedKVCache.create(
                mc.num_layers, c.num_pages, c.page_size, mc.num_kv_heads,
                dpool, c.max_slots, c.max_pages_per_slot,
                dtype=dtype,
            )
        self.cache = shard_cache(cache, self.mesh) if self.mesh else cache
        self.alloc = PageAllocator(
            c.num_pages, c.page_size, c.max_pages_per_slot,
            cache_pages=self._prefix_cache_cap, model=mc.name,
        )
        if self.host_tier is not None:
            # tiered KV cache (ISSUE 11): eviction spills to host RAM,
            # match_prefix misses consult it — both fire under
            # _alloc_lock from inside the allocator
            self.alloc.spill_sink = self._spill_page_to_host
            self.alloc.restore_source = self._restore_page_from_host
        # lock-discipline sanitizer (ISSUE 8): under GRIDLLM_SANITIZE=1
        # every mutating allocator call asserts _alloc_lock ownership at
        # the call site instead of corrupting refcounts three requests
        # later; dormant (no import, no wrap) otherwise
        if env_bool("GRIDLLM_SANITIZE"):
            from gridllm_tpu.analysis.lockcheck import guard_allocator
            from gridllm_tpu.analysis.statecheck import track_object

            guard_allocator(self.alloc, self._alloc_lock)
            # shared-state sanitizer (ISSUE 13): allocator state is
            # mutated from the runner thread AND gateway executor
            # threads — every write must hold _alloc_lock in common,
            # which the write tracker verifies independently of the
            # call-site guard above
            track_object(self.alloc, f"alloc:{mc.name}", (
                "_free", "_owned", "_refs", "_key_of", "_page_by_key",
                "_staged_stats"))
        self.sampling = SamplingParams.defaults(c.max_slots)
        self.counts = jnp.zeros((c.max_slots, mc.vocab_size), jnp.int32)
        # repeat-penalty window: last ≤ repeat_last_n context tokens per
        # slot (ops/sampling.py window_* helpers maintain it + counts)
        self.window = jnp.zeros((c.max_slots, c.repeat_window), jnp.int32)
        self.wlen = jnp.zeros((c.max_slots,), jnp.int32)
        self.tokens = jnp.zeros((c.max_slots,), jnp.int32)
        self.active = jnp.zeros((c.max_slots,), bool)

    def reset_device_state(self) -> None:
        """Recover from a failed jitted step. prefill_fn/decode_fn donate the
        cache/counts buffers, so an exception mid-call can leave self.cache
        referencing deleted arrays; serving again on that state
        deterministically fails every subsequent request. Params are never
        donated and survive; everything else is rebuilt. Callers should
        abort_all() first — slot state is discarded here."""
        if self.embedding_only:
            return
        with self._alloc_lock, self.dispatch_lock:
            self._slots.clear()
            self._inflight.clear()
            self._t_prev_fetch = None  # recovery wall must not read as
            self._t_ingest_done = None  # device/host pace
            self._free_slots = list(range(self.config.max_slots - 1, -1, -1))
            self._init_device_state()
            if self._drafter is not None and hasattr(self._drafter, "reset"):
                # the drafter's jitted entries donate ITS cache — an
                # exception mid-draft can leave it referencing deleted
                # buffers, same failure mode this reset exists to cure
                self._drafter.reset()
            self._update_kv_gauges()
            if self.plan_sink is not None:  # after-success; see _try_admit
                self.plan_sink({"op": "reset"})

    def _build_fns(self) -> None:
        mc = self.cfg
        # pooled hidden states for the embeddings path — batched [B, T],
        # jit-compiled (one program per (batch-bucket, len-bucket) pair)
        # armable=False: embed compiles per (batch-bucket, len-bucket)
        # pair ON DEMAND — a decoder model's first embed request can land
        # long after generation warms, and flagging that bounded,
        # legitimate compile as a steady-state recompile would page on
        # healthy behavior (same for the vision pair below)
        self._embed_fn = self.perf.wrap("embed", jax.jit(
            lambda params, tokens, lens: self.mod.hidden_states(
                params, mc, tokens, seq_lens=lens, mesh=self.mesh
            )
        ), armable=False)
        if self.embedding_only:
            return

        # sp > 1 → sequence-parallel prefill: ring attention splits the
        # prompt's T axis over the sp mesh axis (ops/ring_attention.py)
        attn = None
        if self.mesh is not None and self.mesh.shape["sp"] > 1:
            from gridllm_tpu.ops.ring_attention import ring_attention

            attn = partial(ring_attention, mesh=self.mesh)

        # pp > 1 → pipeline parallelism: layer blocks as token-passing
        # stages (parallel/pipeline.py); same family API, so the jitted
        # step fns below are oblivious to which module serves them
        mod = self.mod
        if self.mesh is not None and self.mesh.shape.get("pp", 1) > 1:
            from gridllm_tpu.parallel import pipeline

            pipeline.validate(self.cfg, self.mesh)
            mod = pipeline

        def _gather_sp(sp: SamplingParams, slot) -> SamplingParams:
            return jax.tree.map(lambda a: a[slot][None], sp)

        # Prefill folds EVERYTHING into device state — the sampled first
        # token lands in `tokens[slot]` and the host never synchronizes on
        # it (it arrives with the next decode block's row 0). sp.step for
        # the slot advances to 1: the prefill sample consumed draw 0.
        # The repeat-penalty window resets to the prompt's last
        # repeat_last_n tokens (llama.cpp penalty_last_n semantics).
        @partial(jax.jit, donate_argnums=(2, 3, 4, 5, 6, 7, 8))
        def prefill_fn(params, prompt, cache, counts, window, wlen, tokens,
                       active, sp, length, slot, table_row, embeds=None):
            logits, cache = mod.prefill(
                params, mc, prompt, length, cache, slot, table_row, attn=attn,
                mesh=self.mesh, embeds=embeds,
            )
            rl = sp.repeat_last_n[slot]
            window, wlen, counts = window_set_slot(
                window, wlen, counts, slot, prompt, jnp.int32(0), length,
                rl, mc.vocab_size,
            )
            tok = sample_tokens(logits[None], _gather_sp(sp, slot), counts[slot][None])[0]
            tokens = tokens.at[slot].set(tok)
            one = jnp.zeros_like(active).at[slot].set(True)
            window, wlen, counts = window_push(
                window, wlen, counts, tokens, one, sp.repeat_last_n,
                mc.vocab_size,
            )
            active = active.at[slot].set(True)
            # step continues from the admission value (0 normally; the
            # already-generated count on a decode resume, ISSUE 9) — the
            # prefill sample consumed that draw, so +1
            sp = dataclasses.replace(
                sp, step=sp.step.at[slot].set(sp.step[slot] + 1))
            return cache, counts, window, wlen, tokens, active, sp

        @partial(jax.jit, donate_argnums=(2, 3, 4, 5, 6, 7, 8))
        def prefill_chunk_fn(params, prompt, cache, counts, window, wlen,
                             tokens, active, sp, start, length, slot,
                             table_row, is_final, embeds=None):
            logits, cache = mod.prefill_chunk(
                params, mc, prompt, start, length, cache, slot, table_row,
                mesh=self.mesh, embeds=embeds,
            )
            rl = sp.repeat_last_n[slot]
            window, wlen, counts = window_set_slot(
                window, wlen, counts, slot, prompt, start, length,
                rl, mc.vocab_size,
            )
            tok = sample_tokens(
                logits[None], _gather_sp(sp, slot), counts[slot][None]
            )[0]
            # intermediate chunks sample garbage (discarded on device);
            # only the final chunk activates the slot and counts its token
            tokens = tokens.at[slot].set(jnp.where(is_final, tok, tokens[slot]))
            one = jnp.zeros_like(active).at[slot].set(is_final)
            window, wlen, counts = window_push(
                window, wlen, counts, tokens, one, sp.repeat_last_n,
                mc.vocab_size,
            )
            active = active.at[slot].set(is_final | active[slot])
            sp = dataclasses.replace(
                sp, step=sp.step.at[slot].set(
                    jnp.where(is_final, sp.step[slot] + 1, sp.step[slot])
                )
            )
            return cache, counts, window, wlen, tokens, active, sp

        # Ragged mixed step (ISSUE 6): ONE forward serving the admitting
        # slot's prefill chunk AND a decode token for every active slot —
        # a mixed prefill+decode step is a single attention launch per
        # layer, so long chunked prefills no longer stall running streams
        # between decode blocks. Bookkeeping is the union of
        # prefill_chunk_fn's (chunk slot rows) and decode_block_fn's
        # (active slot rows) — per-slot state rows are disjoint, so each
        # region's updates are bit-identical to the legacy programs'.
        # Returns a [2, S] block (row 0 = input tokens, row 1 = this
        # step's decode samples) that rides the normal ingest protocol.
        @partial(jax.jit, donate_argnums=(2, 3, 4, 5, 6, 7, 8))
        def mixed_chunk_fn(params, chunk, cache, counts, window, wlen,
                           tokens, active, sp, start, length, slot,
                           table_row, is_final, embeds=None):
            tokens_in = tokens
            active_in = active
            chunk_logits, dec_logits, cache = mod.mixed_step(
                params, mc, chunk, start, length, slot, table_row, tokens,
                cache, active, mesh=self.mesh, embeds=embeds,
            )
            # chunk-slot bookkeeping (exactly prefill_chunk_fn's)
            rl = sp.repeat_last_n[slot]
            window, wlen, counts = window_set_slot(
                window, wlen, counts, slot, chunk, start, length,
                rl, mc.vocab_size,
            )
            tok = sample_tokens(
                chunk_logits[None], _gather_sp(sp, slot), counts[slot][None]
            )[0]
            tokens = tokens.at[slot].set(
                jnp.where(is_final, tok, tokens[slot])
            )
            one = jnp.zeros_like(active).at[slot].set(is_final)
            window, wlen, counts = window_push(
                window, wlen, counts, tokens, one, sp.repeat_last_n,
                mc.vocab_size,
            )
            active = active.at[slot].set(is_final | active[slot])
            sp = dataclasses.replace(
                sp, step=sp.step.at[slot].set(
                    jnp.where(is_final, sp.step[slot] + 1, sp.step[slot])
                )
            )
            # decode bookkeeping for the slots that were active at entry
            # (exactly decode_block_fn's body, k = 1)
            sampled = sample_tokens(dec_logits, sp, counts)
            tokens = jnp.where(active_in, sampled, tokens)
            window, wlen, counts = window_push(
                window, wlen, counts, tokens, active_in, sp.repeat_last_n,
                mc.vocab_size,
            )
            sp = dataclasses.replace(
                sp, step=sp.step + active_in.astype(jnp.int32)
            )
            out = jnp.stack([tokens_in, tokens])  # [2, S]
            return out, cache, counts, window, wlen, tokens, active, sp

        # One decode block: k fused (model step + sample + bookkeeping)
        # iterations under lax.scan. Returns [k+1, S] tokens — row 0 is the
        # block's INPUT tokens (a newly admitted slot's prefill sample),
        # rows 1..k the block's samples.
        @partial(jax.jit, static_argnames=("k",),
                 donate_argnums=(1, 2, 4, 5, 6, 7))
        def decode_block_fn(params, cache, tokens, active, counts, window,
                            wlen, sp, *, k):
            first = tokens

            def body(carry, _):
                tokens, cache, counts, window, wlen, sp = carry
                logits, cache = mod.decode_step(
                    params, mc, tokens, cache, active, mesh=self.mesh
                )
                sampled = sample_tokens(logits, sp, counts)
                tokens = jnp.where(active, sampled, tokens)
                window, wlen, counts = window_push(
                    window, wlen, counts, tokens, active, sp.repeat_last_n,
                    mc.vocab_size,
                )
                sp = dataclasses.replace(
                    sp, step=sp.step + active.astype(jnp.int32)
                )
                return (tokens, cache, counts, window, wlen, sp), tokens

            (tokens, cache, counts, window, wlen, sp), toks = jax.lax.scan(
                body, (tokens, cache, counts, window, wlen, sp), None, length=k
            )
            out = jnp.concatenate([first[None], toks])  # [k+1, S]
            return out, tokens, cache, counts, window, wlen, sp

        # Prefix-cache warm admission (ISSUE 3): the cached region's tokens
        # skip the model forward but must still flow through the
        # repeat-penalty window/counts bookkeeping, or a warm request's
        # sampler state (and therefore its tokens) would diverge from the
        # cold path's. Same chunk shape as prefill_chunk_fn → one compiled
        # program; integer-only state, so warm == cold bit for bit.
        @partial(jax.jit, donate_argnums=(1, 2, 3))
        def window_seed_fn(sp, window, wlen, counts, chunk, start, length,
                           slot):
            rl = sp.repeat_last_n[slot]
            return window_set_slot(
                window, wlen, counts, slot, chunk, start, length, rl,
                mc.vocab_size,
            )

        self._window_seed_fn = self.perf.wrap("window_seed", window_seed_fn)
        # vision models legitimately double the prefill signature space
        # post-warmup: an image request adds the embeds leaf to the same
        # bucket a text request compiled without it, so armed prefill
        # probes would flag the first image request as a steady-state
        # recompile. Decode stays armed — the hot loop's shapes are
        # vision-independent.
        text_only = not self.cfg.vision
        self._prefill_fn = self.perf.wrap("prefill", prefill_fn,
                                          armable=text_only)
        self._prefill_chunk_fn = self.perf.wrap("prefill_chunk",
                                                prefill_chunk_fn,
                                                armable=text_only)
        if self.cfg.vision:
            # vision path (llava family): encode_images per image-count
            # (jit caches per shape — image counts are tiny), splice per
            # (bucket, image-count) pair
            self._encode_fn = self.perf.wrap("encode_images", jax.jit(
                lambda params, px: self.mod.encode_images(params, mc, px)
            ), armable=False)
            self._splice_fn = self.perf.wrap("splice_embeds", jax.jit(
                lambda params, toks, ie, off: self.mod.splice_embeds(
                    params, mc, toks, ie, off
                )
            ), armable=False)
        # ring attention (sp) runs whole-prompt prefill; the chunked path
        # reads the paged prefix instead and has no sp variant yet
        self._use_chunked = attn is None
        # ragged mixed steps need the chunked path AND a family mixed_step
        # (parallel/pipeline.py has no mixed schedule — pp engines keep
        # the legacy per-chunk dispatch even with ragged attention on)
        self._use_mixed = (
            self._ragged and self._use_chunked and hasattr(mod, "mixed_step")
        )
        if self._use_mixed:
            self._mixed_chunk_fn = self.perf.wrap(
                "mixed_chunk", mixed_chunk_fn, armable=text_only
            )
        ps = self.config.page_size
        # page-aligned chunking: the in-place page-write kernel requires
        # chunk starts at page boundaries
        self._chunk_len = max(
            ps, (min(self.config.prefill_chunk, self.max_context) // ps) * ps
        )
        self._decode_block_fn = self.perf.wrap("decode_block", decode_block_fn)

        # Speculative decoding (ISSUE 5): one verify step = ONE batched
        # forward over each slot's [K+1] candidate block (committed last
        # token + K host-drafted candidates) + the accept/reject kernel +
        # the KV rollback commit — all inside one jit call. Emits 1..K+1
        # tokens per slot per dispatch. K is static (fixed per process) so
        # the program compiles once; the recompile tripwire wraps it like
        # every other entry point.
        spec_k = self._resolve_spec_k()
        if not hasattr(mod, "verify_step"):
            # pp>1 routes decode through parallel/pipeline.py, which has
            # no verify schedule yet — serve exact non-speculative decode
            # rather than failing at the first request
            if spec_k:
                log.info("speculative decoding disabled: no verify_step "
                         "for this decode path", model=mc.name)
            self._spec_k = 0
        else:
            self._spec_k = spec_k
            if spec_k:
                # draft-model tree drafting (ISSUE 18) when configured and
                # compatible; n-gram prompt-lookup otherwise
                self._drafter = (self._build_model_drafter(spec_k)
                                 or make_drafter())
            self._tree_width = self._resolve_tree_width()
            # the verify program is built even with speculation off so a
            # multi-host follower can replay a liaison's "verify" plan ops
            # regardless of its own env (K comes from the record; nothing
            # compiles unless a verify is actually dispatched)

            @partial(jax.jit, static_argnames=("k1",),
                     donate_argnums=(1, 2, 4, 5, 6, 7))
            def verify_block_fn(params, cache, tokens, active, counts,
                                window, wlen, sp, drafts, dlen, *, k1):
                # candidates [S, K+1]: col 0 is the device's committed
                # last token — the host never needs to know it (a freshly
                # admitted slot's prefill sample stays device-side, same
                # no-sync admission contract as the block path)
                cand = jnp.concatenate([tokens[:, None], drafts], axis=1)
                logits, cache = mod.verify_step(
                    params, mc, cand, cache, active, mesh=self.mesh
                )
                out, n_emit, last, counts, window, wlen, sp = spec_accept(
                    logits, cand, dlen, sp, counts, window, wlen, active,
                    mc.vocab_size,
                )
                tokens = jnp.where(active, last, tokens)
                # commit accepted length; rejected candidate rows roll back
                cache = rollback_to_length(
                    cache,
                    jnp.minimum(cache.lengths + n_emit, cache.max_context),
                )
                # block protocol: [K+2, S] — row 0 = block-input tokens
                # (a just-admitted slot's prefill sample), rows 1..K+1 the
                # emitted tokens, valid up to n_emit per slot
                block = jnp.concatenate([cand[:, :1].T, out])
                return block, n_emit, tokens, cache, counts, window, wlen, sp

            self._verify_fn = self.perf.wrap("verify_block", verify_block_fn)

            # Tree verification (ISSUE 18): one program per draft-tree
            # TOPOLOGY (parents tuple) — static per process for the local
            # drafter, but a follower replaying a liaison's "verify_tree"
            # plan op rebuilds the fn from the record's parents, so the
            # hosts never need to agree on env knobs. The depth/ancestor
            # arrays are jit-closure constants; per-slot raggedness
            # travels as the node-validity operand (data, not shape), so
            # steady state compiles each topology exactly once.
            self._tree_fns: dict[tuple, Any] = {}

            def _tree_fn_for(parents):
                key = tuple(int(p) for p in parents)
                fn = self._tree_fns.get(key)
                if fn is not None:
                    return fn
                parents_np = np.asarray(key, np.int32)
                depths = tree_depths(parents_np)
                anc = tree_ancestor_mask(parents_np)

                @partial(jax.jit, donate_argnums=(1, 2, 4, 5, 6, 7))
                def verify_tree_fn(params, cache, tokens, active, counts,
                                   window, wlen, sp, drafts, valid):
                    # candidates [S, N]: col 0 = the device's committed
                    # last token (tree root), cols 1.. = drafted nodes in
                    # topological order. Node i's KV is written
                    # optimistically at storage row lengths + i; its
                    # LOGICAL position is lengths + depth[i] (rope +
                    # ancestor-masked attention inside verify_step).
                    cand = jnp.concatenate([tokens[:, None], drafts],
                                           axis=1)
                    logits, cache = mod.verify_step(
                        params, mc, cand, cache, active, mesh=self.mesh,
                        tree_pos=depths, tree_mask=anc,
                    )
                    (out, path, n_emit, last, counts, window, wlen,
                     sp) = spec_accept_tree(
                        logits, cand, parents_np, valid, sp, counts,
                        window, wlen, active, mc.vocab_size,
                    )
                    tokens = jnp.where(active, last, tokens)
                    # compact the accepted root-to-leaf path over the
                    # optimistic rows, then roll forward — rejected
                    # branches vanish without ever touching host state
                    cache = commit_tree_path(cache, path, active)
                    cache = rollback_to_length(
                        cache,
                        jnp.minimum(cache.lengths + n_emit,
                                    cache.max_context),
                    )
                    # block protocol: [N+1, S], same contract as the
                    # chain path (row 0 = block-input tokens)
                    block = jnp.concatenate([cand[:, :1].T, out])
                    return (block, n_emit, tokens, cache, counts, window,
                            wlen, sp)

                fn = self.perf.wrap("verify_tree", verify_tree_fn)
                self._tree_fns[key] = fn
                return fn

            self._tree_fn_for = _tree_fn_for

    # ------------------------------------------------------------ admission

    def submit(self, req: GenerationRequest) -> None:
        if self.embedding_only:
            self._fail(req, f"{self.cfg.name} is an embedding model; "
                            "it does not support generation", retryable=False)
            return
        if req.images and not self.cfg.vision:
            # images travel the full protocol (API-surface parity with the
            # reference's Ollama passthrough); capability is per-model.
            # Loud reject > silently ignoring pixels the client sent.
            self._fail(req, f"{self.cfg.name} does not support image inputs",
                       retryable=False)
            return
        with self._lock:
            if len(self._pending) >= self.config.max_queue:
                raise RuntimeError("engine queue full")
            self._pending.append(req)
        with self._work:
            self._work.notify_all()

    def _tokenize(self, req: GenerationRequest) -> list[int]:
        if req.prompt_ids is not None:
            return list(req.prompt_ids)
        return self.tokenizer.encode(req.prompt or "", add_bos=not req.raw)

    def _bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _fail(self, req: GenerationRequest, msg: str, retryable: bool = True) -> None:
        log.warning("request rejected", id=req.id, reason=msg)
        res = GenerationResult(id=req.id, done_reason="error", error=msg,
                               retryable=retryable)
        if req.on_chunk:
            req.on_chunk("", True, res)

    def _try_admit(self) -> bool:
        """Admit one pending request into a free slot. Returns True if
        admitted (caller loops until False)."""
        with self._lock:
            if not self._pending or not self._free_slots:
                return False
            req = self._pending.popleft()
        ids = self._tokenize(req)
        images = list(req.images or [])
        # decode resume (ISSUE 9): tokens a previous attempt already
        # generated join the PROMPT for prefill/alloc (so a cached or
        # migrated prefix covers them) but seed the slot's generated
        # state below — vision requests can't resume (their KV encodes
        # spliced pixels token ids alone don't address)
        resume = [] if images else [int(t) for t in req.resume_ids or []]
        if resume:
            ids = ids + resume
        if images:
            try:
                ids = self._expand_image_tokens(ids, len(images))
            except ValueError as e:
                self._fail(req, str(e), retryable=False)
                return True
        elif (
            self.cfg.vision and req.prompt_ids is not None
            and self.cfg.vision_cfg
            and self.cfg.vision_cfg.image_token in ids
        ):
            # Ollama `context` round-trip from an image turn: the expanded
            # image-token run is in the context but the pixels are not.
            # Prefilling placeholder embeddings would silently answer
            # about an image the model cannot see — fail loudly instead.
            self._fail(req, "context contains image tokens; follow-ups on "
                            "image conversations must re-send the images",
                       retryable=False)
            return True
        opts = req.options or {}
        # num_ctx caps THIS request's context (Ollama option; engine-wide
        # max_context still bounds it) — VERDICT r03 weak #7
        num_ctx = int(opts.get("num_ctx") or 0)
        eff_ctx = (
            min(num_ctx, self.max_context) if num_ctx > 0 else self.max_context
        )
        # floor of 2: one prompt token + one generated; num_ctx=1 would
        # also make the truncation slice ids[-0:] a no-op
        eff_ctx = max(eff_ctx, 2)
        if len(ids) >= eff_ctx:
            ids = ids[-(eff_ctx - 1):]  # Ollama truncates from the left
            if images:
                vc = self.cfg.vision_cfg
                if ids.count(vc.image_token) != len(images) * vc.num_patches:
                    # truncation cut into an image span — the splice would
                    # misalign patch rows; loud failure beats garbage
                    self._fail(req, "context window too small for image "
                                    "inputs", retryable=False)
                    return True
        num_predict = int(opts.get("num_predict", -1))
        want = (
            # resumed tokens are already in `ids`; capacity reserves only
            # the REMAINING budget so resume matches the original reservation
            len(ids) + max(num_predict - len(resume), 0)
            if num_predict >= 0
            else eff_ctx
        )
        want = min(max(want, len(ids) + 1), eff_ctx)
        if not self.alloc.fits_slot_cap(want):
            self._fail(req, f"context {want} exceeds slot capacity")
            return True
        slot = self._free_slots[-1]
        # longest cached prefix first (pins matched pages via refcount),
        # then allocate the remainder. Images are excluded — token ids
        # alone don't address spliced pixel embeddings — and sp meshes
        # have no chunked path to resume from (cap forced to 0 there).
        with self._alloc_lock:
            cached = 0
            if self._prefix_cache_cap != 0 and not images:
                cached = self.alloc.match_prefix(slot, ids)
            pages = self.alloc.alloc(slot, want)
            if pages is None:
                # pool exhausted: unpin any matched prefix, requeue at
                # front, wait for a slot to free pages
                self.alloc.free(slot)
                with self._lock:
                    self._pending.appendleft(req)
                return False
        self._free_slots.pop()

        stop = opts.get("stop") or []
        stop_seqs = [stop] if isinstance(stop, str) else list(stop)
        st = _Slot(req, ids, want, num_predict, stop_seqs, self.tokenizer.eos_ids)
        if resume:
            # continue, don't restart: generated/detok/text pick up where
            # the lost attempt stopped (num_predict, stop scanning, and
            # eval_count all see the prior tokens), and emission resumes
            # past the chars the client already received
            st.prompt_len = max(len(ids) - len(resume), 0)
            st.generated = list(resume)
            st.text = st.detok.delta(self.tokenizer, st.generated)
            st.emitted_len = max(int(req.resume_sent or 0), 0)

        # per-slot sampler params (Ollama option names)
        seed = opts.get("seed")
        if seed is None:
            seed = self._rng.getrandbits(31)
        # repeat_last_n (llama.cpp penalty_last_n): -1 → the request's
        # context size, 0 → disabled; clamped to the window buffer width
        rl = int(opts.get("repeat_last_n", 64))
        if rl < 0:
            rl = want
        rl = min(rl, self.config.repeat_window)
        upd = {
            "temperature": float(opts.get("temperature", 0.8)),
            "top_k": int(opts.get("top_k", 40)),
            "top_p": float(opts.get("top_p", 0.9)),
            "min_p": float(opts.get("min_p", 0.0)),
            "repeat_penalty": float(opts.get("repeat_penalty", 1.1)),
            "repeat_last_n": rl,
            "seed": int(seed) & 0x7FFFFFFF,
            # the (seed, step) rng chain restarts at the number of draws
            # the lost attempt consumed, so seeded resume samples the
            # same continuation the undisturbed run would have
            "step": len(resume),
        }
        # capped at prompt_len: a warm RESUME's cache match can cover the
        # resumed tokens too, but cached_tokens reports prompt tokens
        # served from cache and must stay <= prompt_eval_count (no-op for
        # ordinary admissions, where prompt_len == len(ids) >= cached)
        st.cached_tokens = min(cached, st.prompt_len)
        row_list = self.alloc.table_row(slot)
        st.pages_held = len(row_list)
        t0 = time.perf_counter_ns()
        with self.dispatch_lock:
            # emit AFTER the dispatch succeeds: a record for a program the
            # liaison never actually issued would make followers replay a
            # phantom computation and silently desync the slice. (If a
            # MULTI-chunk prefill fails partway, the liaison's own stream
            # is already unpaired and the slice-failure machinery tears the
            # group down — there is no cheap reconciliation for that.)
            self._dispatch_prefill(slot, ids, row_list, upd, images=images,
                                   cached=cached)
            if self.plan_sink is not None:
                # SNAPSHOT the ids: the list is also _Slot.ids, which
                # _ingest APPENDS generated tokens to — a by-reference
                # record serialized after the first ingest would make
                # followers prefill phantom tokens and silently desync
                # the slice (caught by the vision replay test comparing
                # follower state against the liaison's actual pool)
                rec = {"op": "admit", "slot": slot, "ids": list(ids),
                       "row": list(row_list), "sp": dict(upd),
                       "cached": cached}
                if images:
                    # raw base64 payload: followers re-run the
                    # deterministic preprocessing + encode themselves
                    rec["images"] = images
                self.plan_sink(rec)
        # dispatch wall time only — the prefill runs asynchronously and its
        # sampled token first becomes host-visible in the next block fetch;
        # t_prefill_ns is finalized there (admission → first-token)
        st.t_prefill_ns = time.perf_counter_ns() - t0
        st.joined_gen = self._gen + 1  # first block dispatched after this
        self._slots[slot] = st
        _TOKENS_TOTAL.inc(len(ids) - cached, model=self.cfg.name,
                          kind="prefill")
        if cached:
            _TOKENS_TOTAL.inc(cached, model=self.cfg.name,
                              kind="prefill_cached")
        _FLIGHTREC.record("engine", "admit", model=self.cfg.name,
                          request=req.id, slot=slot, promptTokens=len(ids),
                          cachedTokens=cached)
        self._update_kv_gauges()
        return True

    def _update_kv_gauges(self) -> None:
        free = self.alloc.free_pages
        cached = self.alloc.cached_pages
        _KV_PAGES_FREE.set(free, model=self.cfg.name)
        _KV_PAGES_CACHED.set(cached, model=self.cfg.name)
        # per-tier residency (ISSUE 11): hbm = reuse-LRU pages at pool
        # bytes/page, host = encoded bytes actually held by the tier
        kv_bytes = self.cache.k.nbytes + self.cache.v.nbytes
        bpp = kv_bytes / max(self.config.num_pages, 1)
        tier = self.host_tier
        set_tier_gauges(
            self.cfg.name, cached, int(cached * bpp),
            tier.pages if tier is not None else 0,
            tier.bytes_used if tier is not None else 0,
        )
        # "used" = pages referenced by live requests; cached-but-evictable
        # pages are their own series so dashboards don't read a warm cache
        # as pool pressure
        _KV_PAGES_USED.set(self.config.num_pages - free - cached,
                           model=self.cfg.name)
        total = self.alloc.hits + self.alloc.misses
        if total:
            _PREFIX_HIT_RATE.set(self.alloc.hits / total, model=self.cfg.name)

    def _expand_image_tokens(self, ids: list[int], n_images: int) -> list[int]:
        """Expand image placeholders to num_patches copies each (the splice
        contract, models/llava.py). Prompts carrying explicit placeholders
        (HF-style `<image>`) must have exactly one per image; marker-free
        prompts (the Ollama API shape — images as a side list) get all
        image spans inserted up front, after BOS, matching Ollama's
        images-before-prompt layout."""
        vc = self.cfg.vision_cfg
        if vc is None:
            raise ValueError(f"{self.cfg.name}: vision model without "
                             "vision_cfg")
        tok, n = vc.image_token, vc.num_patches
        count = ids.count(tok)
        if count == 0:
            at = 1 if (ids and ids[0] == self.tokenizer.bos_id) else 0
            return ids[:at] + [tok] * (n * n_images) + ids[at:]
        if count == n_images * n:
            # already expanded — an Ollama `context` round-trip of a prior
            # image turn (st.ids carries the expanded runs) with the
            # images re-sent; splice positions line up as-is
            return list(ids)
        if count != n_images:
            raise ValueError(
                f"prompt has {count} image placeholder(s) for "
                f"{n_images} image(s)"
            )
        out: list[int] = []
        for t in ids:
            out.extend([tok] * n if t == tok else [t])
        return out

    def _image_embeds(self, images: list[str]) -> jnp.ndarray:
        """base64 images → flattened projected patch rows [n*N, E]."""
        from gridllm_tpu.engine.images import preprocess_images

        px = preprocess_images(images, self.cfg.vision_cfg.image_size)
        emb = self._encode_fn(self.params, jnp.asarray(px))  # [n, N, E]
        return emb.reshape(-1, emb.shape[-1])

    def _dispatch_prefill(self, slot: int, ids: list[int],
                          row_list: list[int], upd: dict[str, Any],
                          images: list[str] | None = None,
                          cached: int = 0) -> None:
        """The device half of admission — everything a multi-host follower
        must replay identically: sampler row update + prefill dispatch.
        All inputs are plain host values (the admit plan record). `cached`
        (page-aligned, from match_prefix) marks the prompt prefix whose KV
        pages are already installed in `row_list`: those tokens skip the
        model forward (window bookkeeping only) and chunked prefill starts
        at the first uncached token."""
        self.sampling = SamplingParams(**{
            f.name: getattr(self.sampling, f.name).at[slot].set(upd[f.name])
            for f in dataclasses.fields(SamplingParams)
        })
        img_flat = self._image_embeds(images) if images else None
        img_tok = self.cfg.vision_cfg.image_token if images else -1
        # counts[slot] is cleared INSIDE prefill_fn / prefill_chunk_fn —
        # no host-side clear here (it would be a dead full-row rewrite)
        row = jnp.asarray(row_list, jnp.int32)
        if cached or (self._use_chunked and len(ids) > self._chunk_len):
            # chunked prefill: repeated invocations of ONE fixed-shape
            # program against the growing cached prefix — no per-length
            # traces, no padding to a distant bucket (VERDICT.md #4)
            c = self._chunk_len
            for s0 in range(0, cached, c):
                # cached region: repeat-penalty window/counts bookkeeping
                # only (no model forward, no page writes) so the sampler
                # state a warm request decodes with is bit-identical to
                # the cold path's
                part = ids[s0 : min(s0 + c, cached)]
                padded = jnp.asarray(part + [0] * (c - len(part)), jnp.int32)
                (self.window, self.wlen, self.counts) = self._window_seed_fn(
                    self.sampling, self.window, self.wlen, self.counts,
                    padded, jnp.int32(s0), jnp.int32(len(part)),
                    jnp.int32(slot),
                )
            for s0 in range(cached, len(ids), c):
                part = ids[s0 : s0 + c]
                padded = jnp.asarray(part + [0] * (c - len(part)), jnp.int32)
                embeds = None
                if img_flat is not None:
                    off = sum(1 for t in ids[:s0] if t == img_tok)
                    embeds = self._splice_fn(
                        self.params, padded, img_flat, jnp.int32(off)
                    )
                if self._use_mixed:
                    # ragged mixed step (ISSUE 6): this chunk AND one
                    # decode token for every active slot share a single
                    # launch — running streams keep generating while the
                    # prompt prefills; the decode rows ride _inflight and
                    # are ingested like any other block
                    self._dispatch_mixed_chunk(
                        padded, s0, len(part), slot, row,
                        s0 + c >= len(ids), embeds,
                    )
                    continue
                (self.cache, self.counts, self.window, self.wlen,
                 self.tokens, self.active, self.sampling) = (
                    self._prefill_chunk_fn(
                        self.params, padded, self.cache, self.counts,
                        self.window, self.wlen, self.tokens, self.active,
                        self.sampling, jnp.int32(s0), jnp.int32(len(part)),
                        jnp.int32(slot), row, jnp.bool_(s0 + c >= len(ids)),
                        embeds=embeds,
                    )
                )
        else:
            bucket = self._bucket_for(len(ids))
            padded = jnp.asarray(
                ids + [0] * (bucket - len(ids)), jnp.int32
            )
            embeds = None
            if img_flat is not None:
                embeds = self._splice_fn(
                    self.params, padded, img_flat, jnp.int32(0)
                )
            (self.cache, self.counts, self.window, self.wlen, self.tokens,
             self.active, self.sampling) = self._prefill_fn(
                self.params, padded, self.cache, self.counts,
                self.window, self.wlen, self.tokens, self.active,
                self.sampling, jnp.int32(len(ids)), jnp.int32(slot), row,
                embeds=embeds,
            )

    def apply_plan_op(self, rec: dict[str, Any]) -> None:
        """Follower-side replay of one liaison plan record (multi-host
        SPMD lockstep — see plan_sink). Must be called in record order
        from ONE thread. Followers never fetch results; their dispatches
        pace themselves against the shared collectives."""
        op = rec["op"]
        if op == "admit":
            self._dispatch_prefill(
                int(rec["slot"]), [int(i) for i in rec["ids"]],
                [int(p) for p in rec["row"]], dict(rec["sp"]),
                images=list(rec.get("images") or []) or None,
                cached=int(rec.get("cached", 0)),
            )
            self._inflight.clear()  # ragged mixed blocks: replay never fetches
        elif op == "block":
            self._dispatch_block(int(rec["k"]))
            self._inflight.clear()  # replay never fetches
        elif op == "verify":
            # drafts are plain host ints in the record, so follower device
            # state evolves bit-identically to the liaison's
            self._dispatch_verify(
                np.asarray(rec["drafts"], np.int32),
                np.asarray(rec["dlen"], np.int32),
            )
            self._inflight.clear()  # replay never fetches
        elif op == "verify_tree":
            # the record carries the tree topology, so the follower
            # rebuilds the exact program regardless of its own env
            self._dispatch_verify_tree(
                np.asarray(rec["drafts"], np.int32),
                np.asarray(rec["valid"], bool),
                np.asarray(rec["parents"], np.int32),
            )
            self._inflight.clear()  # replay never fetches
        elif op == "deact":
            self.active = self.active.at[int(rec["slot"])].set(False)
        elif op == "embed":
            tok = jnp.asarray(np.asarray(rec["tok"], np.int32))
            lens = jnp.asarray(np.asarray(rec["lens"], np.int32))
            self._embed_fn(self.params, tok, lens)  # result unused
        elif op == "reset":
            self.reset_device_state()
        else:
            raise ValueError(f"unknown plan op: {op!r}")

    # ------------------------------------------------------------ stepping

    def _ingest(self, slot: int, st: _Slot, tok: int) -> None:
        """Record one sampled token; emit text; finish the slot if done."""
        if st.export_only:
            # disaggregated prefill (ISSUE 7): the first host-visible token
            # proves the whole prompt's KV is written — finish NOW with
            # reason "export" so _finish registers the prompt's full pages
            # in the prefix cache (the export source). The sampled token is
            # deliberately discarded (not detokenized, not streamed): the
            # decode worker re-prefills the prompt tail and samples it
            # itself, which is what keeps the streams bit-identical.
            st.generated.append(tok)
            st.ids.append(tok)
            self._finish(slot, st, "export")
            return
        st.generated.append(tok)
        st.ids.append(tok)
        done_reason = None
        if tok in st.eos_ids:
            st.generated.pop()  # EOS is not part of the visible output
            st.ids.pop()
            done_reason = "stop"
        else:
            st.text += st.detok.delta(self.tokenizer, st.generated)
            for s in st.stop_seqs:  # stop sequences: trim at first match
                i = st.text.find(s)
                if i >= 0:
                    st.text = st.text[:i]
                    done_reason = "stop"
                    break
        if done_reason is None:
            if 0 <= st.num_predict <= len(st.generated):
                done_reason = "length"
            elif st.prompt_len + len(st.generated) >= st.capacity:
                # capacity is allocated in full at admission (alloc never
                # returns partial); growing the page table here would race
                # in-flight decode blocks holding the old table (their
                # writes at grown positions were sentinel-dropped already)
                done_reason = "length"
        if done_reason is not None:
            self._finish(slot, st, done_reason)
            return
        # the token SURVIVED (no finish) — publish it on the resume
        # watermark at the request's cadence (every write copies the full
        # generated list, so per-token would be O(n^2)). Finishing tokens
        # are deliberately excluded: a resume must always have at least
        # one token left to generate, or the replacement worker could
        # overshoot num_predict/EOS.
        cadence = st.req.snapshot_every
        if cadence > 0 and len(st.generated) % cadence == 0:
            st.snapshot = (list(st.generated), st.text)
        # emit finalized text only: hold back anything that may yet turn
        # into a stop sequence (emitted chunks cannot be retracted)
        safe = len(st.text) - st.holdback()
        if safe > st.emitted_len and st.req.on_chunk:
            delta = st.text[st.emitted_len : safe]
            st.emitted_len = safe
            st.req.on_chunk(delta, False, None)

    def _finish(self, slot: int, st: _Slot, reason: str, error: str = "") -> None:
        now = time.perf_counter_ns()
        last_delta = st.text[st.emitted_len :]
        st.emitted_len = len(st.text)
        # final page count (decode growth included) for page-occupancy
        # attribution; the admission-time count is the floor
        with self._alloc_lock:
            try:
                st.pages_held = max(st.pages_held, len(self.alloc.table_row(slot)))
            except Exception:
                pass
        res = GenerationResult(
            id=st.req.id,
            error=error,
            text=st.text,
            token_ids=list(st.generated),
            context=list(st.ids),
            done_reason=reason,
            prompt_eval_count=st.prompt_len,
            cached_tokens=st.cached_tokens,
            prompt_eval_duration_ns=st.t_prefill_ns,
            eval_count=len(st.generated),
            eval_duration_ns=(now - st.t_first_decode) if st.t_first_decode else 0,
            load_duration_ns=self.load_duration_ns,
            total_duration_ns=now - st.t_start,
            spec_proposed=st.spec_proposed,
            spec_accepted=st.spec_accepted,
            decode_device_s=st.device_s,
            kv_page_s=max(st.pages_held, 1)
            * max(time.time() - st.t_admit_wall, 0.0),
        )
        with self.dispatch_lock:
            self.active = self.active.at[slot].set(False)
            if self.plan_sink is not None:  # after-success; see _try_admit
                self.plan_sink({"op": "deact", "slot": slot})
        # Release pages into the prefix-cache reuse LRU, registering full
        # pages of the final context (prompt + generated). The LAST token
        # is excluded: a token's KV is written when it is INPUT to the next
        # decode step, and for the final sampled token that step may not
        # have been dispatched — every earlier position is provably written
        # (its successor was sampled and ingested). An "error" finish may
        # leave poisoned device state, so its pages are never registered
        # (reset_device_state rebuilds the allocator wholesale anyway).
        # Vision requests never register either: their KV encodes spliced
        # pixel embeddings that identical token ids (image-token runs) do
        # not capture, so a token-chain key would collide across images.
        register = reason != "error" and not st.req.images
        with self._alloc_lock:
            self.alloc.free(slot, st.ids[:-1] if register else None)
        self._update_kv_gauges()
        del self._slots[slot]
        self._free_slots.append(slot)
        if self._drafter is not None and hasattr(self._drafter, "reset_slot"):
            # draft-model drafters keep a per-slot KV prefix view; the
            # next request reusing this slot starts from scratch
            self._drafter.reset_slot(slot)
        _FLIGHTREC.record("engine", "finish", model=self.cfg.name,
                          request=st.req.id, slot=slot, reason=reason,
                          tokens=len(st.generated))
        if not self._perf_armed and reason in ("stop", "length"):
            # first naturally completed request ⇒ the prefill/decode
            # programs its shapes needed are compiled — steady state from
            # here; new signatures are flagged (legit new-bucket compiles
            # still happen, bounded by |buckets|, and stay under the
            # storm budget)
            self._perf_armed = True
            self.perf.arm()
        if st.req.on_chunk:
            st.req.on_chunk(last_delta, True, res)

    def _dispatch_block(self, k: int) -> None:
        """Dispatch one fused k-step decode block (no host sync)."""
        with self.dispatch_lock:
            _BATCH_OCCUPANCY.observe(len(self._slots), model=self.cfg.name)
            self._gen += 1
            if self._gen % _FLIGHT_SAMPLE == 0:  # sampled step-loop record
                _FLIGHTREC.record("engine", "block", model=self.cfg.name,
                                  gen=self._gen, k=k,
                                  slots=len(self._slots),
                                  pending=len(self._pending))
            t0 = time.perf_counter()
            (out, self.tokens, self.cache, self.counts, self.window,
             self.wlen, self.sampling) = self._decode_block_fn(
                self.params, self.cache, self.tokens, self.active,
                self.counts, self.window, self.wlen, self.sampling, k=k,
            )
            now = time.perf_counter()
            # dispatch-to-device: trace/lower/enqueue wall time — the call
            # returns before the device finishes; a spike here is usually
            # a recompile (pairs with gridllm_recompiles_total)
            DISPATCH_SECONDS.observe(now - t0, model=self.cfg.name)
            self._inflight.append((self._gen, out, k, now))
            if self.plan_sink is not None:  # after-success; see _try_admit
                self.plan_sink({"op": "block", "k": k})

    def _dispatch_mixed_chunk(self, padded, start: int, length: int,
                              slot: int, row, is_final: bool,
                              embeds) -> None:
        """Dispatch one ragged mixed step (chunk + decode, ISSUE 6). Runs
        under dispatch_lock (called from _dispatch_prefill). The [2, S]
        decode-token block joins _inflight with its own generation —
        fetched later by the normal block drains, no host sync here."""
        self._gen += 1
        t0 = time.perf_counter()
        (out, self.cache, self.counts, self.window, self.wlen, self.tokens,
         self.active, self.sampling) = self._mixed_chunk_fn(
            self.params, padded, self.cache, self.counts, self.window,
            self.wlen, self.tokens, self.active, self.sampling,
            jnp.int32(start), jnp.int32(length), jnp.int32(slot), row,
            jnp.bool_(is_final), embeds=embeds,
        )
        now = time.perf_counter()
        DISPATCH_SECONDS.observe(now - t0, model=self.cfg.name)
        self._inflight.append((self._gen, out, 1, now))

    def _fetch_oldest(self) -> None:
        """Fetch + ingest the oldest in-flight decode/mixed block — the
        ONE copy of the block fetch protocol: step()'s sync path,
        _pump_once's pipelined pop, and the admission-block drains all go
        through here. Observes device pace and per-fused-step duration
        (fetch+ingest wall over the block's step count)."""
        gen, out, blk, t_disp = self._inflight.popleft()
        t0 = time.perf_counter()
        # the ONE declared block-fetch sync point (host-sync-discipline)
        raw = np.asarray(jax.device_get(out))  # sync-ok
        self._observe_device_step(t_disp, blk)
        self._ingest_block(gen, raw)
        _STEP_DURATION.observe(
            (time.perf_counter() - t0) / max(blk, 1), model=self.cfg.name)

    def _dispatch_verify(self, drafts: np.ndarray, dlen: np.ndarray) -> None:
        """Dispatch one speculative verify block: [S, K] host drafts (+
        per-slot valid count) against the device's committed last tokens.
        No host sync — the fetch happens in _step_spec."""
        with self.dispatch_lock:
            _BATCH_OCCUPANCY.observe(len(self._slots), model=self.cfg.name)
            self._gen += 1
            if self._gen % _FLIGHT_SAMPLE == 0:
                _FLIGHTREC.record("engine", "verify", model=self.cfg.name,
                                  gen=self._gen, k=int(drafts.shape[1]),
                                  slots=len(self._slots),
                                  drafted=int(dlen.sum()),
                                  pending=len(self._pending))
            t0 = time.perf_counter()
            (block, n_emit, self.tokens, self.cache, self.counts,
             self.window, self.wlen, self.sampling) = self._verify_fn(
                self.params, self.cache, self.tokens, self.active,
                self.counts, self.window, self.wlen, self.sampling,
                jnp.asarray(drafts, jnp.int32), jnp.asarray(dlen, jnp.int32),
                k1=int(drafts.shape[1]) + 1,  # from the record: follower
            )                                 # replay may differ from env K
            now = time.perf_counter()
            DISPATCH_SECONDS.observe(now - t0, model=self.cfg.name)
            self._inflight.append((self._gen, (block, n_emit), 1, now))
            if self.plan_sink is not None:  # after-success; see _try_admit
                self.plan_sink({"op": "verify", "drafts": drafts.tolist(),
                                "dlen": dlen.tolist()})

    def _dispatch_verify_tree(self, drafts: np.ndarray, valid: np.ndarray,
                              parents: np.ndarray) -> None:
        """Dispatch one TREE verify block (ISSUE 18): [S, N-1] drafted
        node tokens + [S, N] per-slot node validity against the static
        topology `parents`. No host sync — the fetch happens in
        _step_spec_tree. The plan record carries the topology, so a
        multi-host follower replays the identical program without any
        env agreement (mirrors the chain path's k-from-record rule)."""
        with self.dispatch_lock:
            _BATCH_OCCUPANCY.observe(len(self._slots), model=self.cfg.name)
            self._gen += 1
            if self._gen % _FLIGHT_SAMPLE == 0:
                _FLIGHTREC.record("engine", "verify_tree",
                                  model=self.cfg.name, gen=self._gen,
                                  nodes=int(len(parents)),
                                  slots=len(self._slots),
                                  drafted=int(valid[:, 1:].sum()),
                                  pending=len(self._pending))
            fn = self._tree_fn_for(parents)
            t0 = time.perf_counter()
            (block, n_emit, self.tokens, self.cache, self.counts,
             self.window, self.wlen, self.sampling) = fn(
                self.params, self.cache, self.tokens, self.active,
                self.counts, self.window, self.wlen, self.sampling,
                jnp.asarray(drafts, jnp.int32), jnp.asarray(valid, bool),
            )
            now = time.perf_counter()
            DISPATCH_SECONDS.observe(now - t0, model=self.cfg.name)
            self._inflight.append((self._gen, (block, n_emit), 1, now))
            if self.plan_sink is not None:  # after-success; see _try_admit
                self.plan_sink({
                    "op": "verify_tree", "drafts": drafts.tolist(),
                    "valid": valid.tolist(),
                    "parents": [int(p) for p in parents],
                })

    def _step_spec_tree(self, k: int) -> None:
        """One draft-model TREE iteration (ISSUE 18): batched device
        drafting over every live slot, one tree-masked verify dispatch,
        fetch, ragged ingest. Same serial-by-construction shape as
        _step_spec — the next step's drafts depend on this step's
        emitted tokens — but the draft pass itself is one device batch
        instead of per-slot host loops."""
        width = self._tree_width
        parents = tree_topology(k, width)
        n = len(parents)
        s = self.config.max_slots
        drafts = np.zeros((s, n - 1), np.int32) if n > 1 else np.zeros(
            (s, 0), np.int32)
        valid = np.zeros((s, n), bool)
        dlen = np.zeros((s,), np.int32)
        todo: dict[int, list[int]] = {}
        budget: dict[int, int] = {}
        for slot, st in list(self._slots.items()):
            if st.joined_gen > self._gen:
                continue  # first token still device-side
            # don't draft past num_predict (chain-path rule): accepting
            # the whole depth-b chain plus the bonus token lands exactly
            # on the remaining allowance
            b = k if st.num_predict < 0 else max(
                st.num_predict - len(st.generated) - 1, 0)
            todo[slot] = st.ids
            budget[slot] = b
            # every live slot verifies at least the root — a slot the
            # drafter skips (pool overflow / zero budget) still emits its
            # one corrected token, exactly a plain decode step
            valid[slot, 0] = True
        props = self._drafter.draft_batch(todo, k, width) if todo else {}
        # drafter overhead is host+device wall time inside draft_batch,
        # cumulative (bench reads the per-arm delta)
        self.spec_stats["draft_ns"] = int(
            getattr(self._drafter, "draft_ns", 0))
        for slot, (chain, alts) in props.items():
            b = budget[slot]
            depth = min(len(chain), b)
            for i in range(depth):
                drafts[slot, i] = chain[i]
                valid[slot, 1 + i] = True
            if b >= 1 and k >= 1:
                # depth-1 siblings: accepting one emits at most sibling +
                # bonus = 2 tokens, the same bound as a depth-1 chain
                for j, a in enumerate(alts):
                    drafts[slot, k + j] = a
                    valid[slot, k + 1 + j] = True
            # proposed = chain depth, matching the chain drafter's
            # accounting so acceptance rates compare across drafters
            # (siblings are a free second chance, not extra proposals)
            dlen[slot] = depth
        self._dispatch_verify_tree(drafts, valid, parents)
        gen, (block, n_emit), _blk, t_disp = self._inflight.popleft()
        t0 = time.perf_counter()
        raw = np.asarray(jax.device_get(block))  # sync-ok (see _step_spec)
        n_np = np.asarray(jax.device_get(n_emit))  # sync-ok
        self._observe_device_step(t_disp, 1)
        self._ingest_spec(gen, raw, n_np, dlen)
        _STEP_DURATION.observe(time.perf_counter() - t0, model=self.cfg.name)

    def _step_spec(self) -> None:
        """One speculative iteration: draft per slot from host-visible
        history, dispatch the verify block, fetch, ingest the ragged
        accept counts. Serial by construction — the next step's drafts
        depend on this step's emitted tokens, so there is no block
        pipeline to hide the fetch behind; speculation pays that back by
        emitting up to K+1 tokens per fetch."""
        while self._inflight:
            # drain mixed admission blocks first: their decode tokens must
            # be host-visible before drafting (and the verify fetch below
            # assumes the queue head is its own dispatch)
            self._fetch_oldest()
        k = self._spec_k
        if getattr(self._drafter, "tree", False):
            self._step_spec_tree(k)
            return
        drafts = np.zeros((self.config.max_slots, k), np.int32)
        dlen = np.zeros((self.config.max_slots,), np.int32)
        for slot, st in list(self._slots.items()):
            if st.joined_gen > self._gen:
                continue  # first token still device-side — nothing to extend
            prop = self._drafter.draft(st.ids, k)
            if prop and st.num_predict >= 0:
                # don't draft past num_predict: the host would discard the
                # overshoot anyway, and counting it would skew acceptance
                prop = prop[:max(st.num_predict - len(st.generated) - 1, 0)]
            if prop:
                dlen[slot] = len(prop)
                drafts[slot, :len(prop)] = prop
        self._dispatch_verify(drafts, dlen)
        gen, (block, n_emit), _blk, t_disp = self._inflight.popleft()
        t0 = time.perf_counter()
        # the spec path's declared fetch: serial by construction (drafts
        # depend on this step's tokens), so the sync is the design
        raw = np.asarray(jax.device_get(block))  # sync-ok
        n_np = np.asarray(jax.device_get(n_emit))  # sync-ok
        self._observe_device_step(t_disp, 1)
        self._ingest_spec(gen, raw, n_np, dlen)
        _STEP_DURATION.observe(time.perf_counter() - t0, model=self.cfg.name)

    def _ingest_spec(self, gen: int, tok_np: np.ndarray,
                     n_emit: np.ndarray, dlen: np.ndarray) -> None:
        """Ragged-block ingest: per slot, rows 1..n_emit[slot] of the
        fetched [K+2, S] block are real emitted tokens (row 0 is the
        block-input protocol row — a just-admitted slot's prefill sample);
        rows past n_emit are rejected-draft junk and never touch host
        state. Stop sequences / EOS / num_predict run per token inside
        _ingest, so a stop landing mid-span truncates exactly as the
        sequential path would."""
        now = time.perf_counter_ns()
        wall = time.time()
        ingested = 0
        emitted_t = 0  # verify-emitted rows only (row 0 is a prefill sample)
        proposed_t = accepted_t = 0
        for slot, st in list(self._slots.items()):
            if st.joined_gen > gen:
                continue
            first_row = 0 if st.joined_gen == gen else 1
            if first_row == 0:
                st.t_prefill_ns = now - st.t_start
            if not st.t_first_decode:
                st.t_first_decode = now
            st.t_last_ingest = wall
            n = int(n_emit[slot])
            prop = int(dlen[slot])
            acc = max(n - 1, 0)
            st.spec_proposed += prop
            st.spec_accepted += acc
            proposed_t += prop
            accepted_t += acc
            for r in range(first_row, min(n, tok_np.shape[0] - 1) + 1):
                self._ingest(slot, st, int(tok_np[r, slot]))
                ingested += 1
                emitted_t += 1 if r >= 1 else 0
                if slot not in self._slots:
                    break  # finished mid-span; later rows are post-stop junk
        if ingested:
            _TOKENS_TOTAL.inc(ingested, model=self.cfg.name, kind="decode")
        m = self.cfg.name
        dk = getattr(self._drafter, "kind", "ngram") or "ngram"
        if proposed_t:
            _SPEC_PROPOSED.inc(proposed_t, model=m, drafter=dk)
            _SPEC_ACCEPT_RATE.observe(accepted_t / proposed_t, model=m,
                                      drafter=dk)
        if accepted_t:
            _SPEC_ACCEPTED.inc(accepted_t, model=m, drafter=dk)
        if proposed_t - accepted_t:
            _SPEC_REJECTED.inc(proposed_t - accepted_t, model=m, drafter=dk)
        stats = self.spec_stats
        stats["steps"] += 1
        stats["proposed"] += proposed_t
        stats["accepted"] += accepted_t
        # row-0 tokens are prefill samples riding the block protocol, not
        # verify output — only rows >= 1 count toward tokens-per-step
        stats["emitted"] += emitted_t

    def _ingest_block(self, gen: int, tok_np: np.ndarray) -> None:
        """Feed one fetched [k+1, S] token block through per-token
        bookkeeping. Row 0 = block-input tokens: consumed only by slots
        whose joined_gen == gen (their prefill sample); newer slots (slot
        reused after this block was dispatched) are skipped entirely."""
        k = tok_np.shape[0] - 1
        now = time.perf_counter_ns()
        wall = time.time()
        ingested = 0
        for slot, st in list(self._slots.items()):
            if st.joined_gen > gen:
                continue
            first_row = 0 if st.joined_gen == gen else 1
            if first_row == 0:
                # first host-visible token: admission → now is the honest
                # prompt-eval (prefill) latency for this request
                st.t_prefill_ns = now - st.t_start
            if not st.t_first_decode:
                st.t_first_decode = now
            st.t_last_ingest = wall  # decode-progress mark (batch_state)
            for r in range(first_row, k + 1):
                self._ingest(slot, st, int(tok_np[r, slot]))
                ingested += 1
                if slot not in self._slots:
                    break  # finished mid-block; later rows are post-EOS junk
        if ingested:
            _TOKENS_TOTAL.inc(ingested, model=self.cfg.name, kind="decode")

    def _drain_ctl(self) -> None:
        while self._ctl:
            op, req_id = self._ctl.popleft()
            for slot, st in list(self._slots.items()):
                if st.req.id == req_id:
                    self._finish(slot, st, op)
                    break

    def step(self) -> bool:
        """One synchronous engine iteration: admit what fits, one decode
        step for all active slots, fetch + ingest. Exact per-token
        semantics (block size 1, no pipelining) — the test/sync driver.
        The serving path is the runner thread (start()/stop()), which uses
        fused blocks and pipelined dispatch. Returns False when idle."""
        self._drain_ctl()
        while self._try_admit():
            pass
        while self._inflight:
            # ragged mixed admission steps enqueue [2, S] blocks; sync
            # semantics = nothing left in flight before this step's own
            # dispatch
            self._fetch_oldest()
        if not self._slots:
            self._t_prev_fetch = None
            return bool(self._pending)
        if self._spec_k:
            self._step_spec()
            return True
        self._dispatch_block(1)
        self._fetch_oldest()
        return True

    def _observe_device_step(self, t_disp: float, k: int) -> None:
        """Per-step on-device time estimate, pipelined-dispatch aware:
        with another block already in flight when this fetch completed,
        the device never idled between blocks, so consecutive fetch
        completions pace at the device's block time; with the pipeline
        drained, dispatch→fetch wall is the honest (queue-inclusive)
        upper bound. Called right after the device_get returns."""
        now = time.perf_counter()
        prev = self._t_prev_fetch
        self._t_prev_fetch = now
        if prev is not None and self._inflight:
            dev = (now - prev) / max(k, 1)
        else:
            dev = (now - t_disp) / max(k, 1)
        DEVICE_STEP_SECONDS.observe(dev, model=self.cfg.name)
        # usage attribution (ISSUE 16): split the block's device time
        # evenly across the slots that shared the batch (engine thread
        # owns _slots — no lock needed)
        if self._slots:
            share = dev * max(k, 1) / len(self._slots)
            for st in self._slots.values():
                st.device_s += share

    # ------------------------------------------------------------- runner

    def start(self) -> None:
        """Start the dedicated engine thread (the serving driver). Replaces
        round-3's per-step asyncio.to_thread hop (VERDICT r03 #2): one
        thread owns all device dispatch; submit()/cancel() are the only
        cross-thread entry points."""
        if self._runner is not None:
            return
        self._runner_stop.clear()
        self._runner = threading.Thread(
            target=self._run, name=f"engine-{self.cfg.name}", daemon=True
        )
        self._runner.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._runner_stop.set()
        with self._work:
            self._work.notify_all()
        r = self._runner  # local: _run may not touch self._runner (races)
        if r is not None:
            r.join(timeout)
            if not r.is_alive():
                self._runner = None
            # else: keep the reference — start() must NOT spawn a second
            # thread while the old one could still be dispatching

    @property
    def running(self) -> bool:
        return self._runner is not None and self._runner.is_alive()

    def _run(self) -> None:
        fail_streak = 0
        while not self._runner_stop.is_set():
            with self._work:
                while not (self._pending or self._slots or self._ctl
                           or self._runner_stop.is_set()):
                    self._work.wait(timeout=0.5)
            if self._runner_stop.is_set():
                break
            try:
                self._pump_once()
                fail_streak = 0
            except Exception as e:  # noqa: BLE001 — keep serving others
                log.error("engine block failed; aborting in-flight requests",
                          model=self.cfg.name, error=str(e))
                _FLIGHTREC.record("engine", "step_failure",
                                  model=self.cfg.name, error=str(e)[:200],
                                  streak=fail_streak + 1)
                self._inflight.clear()
                self._t_prev_fetch = None
                self.abort_all(f"engine failure: {e}")
                try:
                    self.reset_device_state()
                except Exception as re:  # noqa: BLE001
                    log.error("device state rebuild failed", error=str(re))
                fail_streak += 1
                if fail_streak >= 3:
                    # thread just exits; `running` turns False via
                    # is_alive() and the worker watchdog drops the model.
                    # (Never touch self._runner from this thread — races
                    # stop().)
                    log.error("engine unrecoverable after repeated failures;"
                              " runner exiting", model=self.cfg.name)
                    _FLIGHTREC.record("engine", "runner_dead",
                                      model=self.cfg.name,
                                      error=str(e)[:200])
                    self.abort_all("engine unrecoverable")
                    return

    def _pump_once(self) -> None:
        """One runner iteration: bounded admission, top up the dispatch
        pipeline, fetch + ingest the oldest in-flight block."""
        # engine.step fault site (faults.py): an injected raise takes the
        # runner's step-failure recovery path — abort in-flight requests,
        # rebuild device state, keep serving
        faults.inject("engine.step")
        self._drain_ctl()
        # idle engine admits everything (first tokens as early as possible);
        # a busy engine bounds admission so running streams never stall for
        # a whole arrival burst of prefills
        budget = (
            self.config.admit_per_block if self._slots
            else self.config.max_slots
        )
        admitted = 0
        while admitted < budget and self._try_admit():
            admitted += 1
        if admitted:
            # a prefill ran between decode blocks: the next fetch delta
            # would span it and book prefill wall time as device pace —
            # fall back to dispatch→fetch for the next block instead
            self._t_prev_fetch = None
        if not self._slots:
            self._t_prev_fetch = None
            self._t_ingest_done = None
            return
        if self._spec_k:
            # speculative serving: one verify block per iteration, fetched
            # immediately (the next step's drafts depend on this step's
            # tokens, so the block pipeline can't apply — acceptance > 1
            # token/step is what pays the un-hidden fetch back)
            if self._t_ingest_done is not None:
                HOST_SCHED_SECONDS.observe(
                    time.perf_counter() - self._t_ingest_done,
                    model=self.cfg.name)
            self._step_spec()
            self._t_ingest_done = time.perf_counter()
            return
        k = self.config.decode_block
        # host-scheduling gap since the previous block's ingest finished
        # — control drain, admission (incl. prefill dispatch), stream
        # callbacks — amortized per fused step so it compares 1:1 with
        # gridllm_engine_device_step_seconds (the host-stall alert and
        # dashboard plot them against each other)
        if self._t_ingest_done is not None:
            HOST_SCHED_SECONDS.observe(
                (time.perf_counter() - self._t_ingest_done) / max(k, 1),
                model=self.cfg.name)
        while len(self._inflight) < max(1, self.config.pipeline_depth):
            self._dispatch_block(k)
        # fetch+ingest wall time per fused step (observed inside
        # _fetch_oldest); in steady state the fetch of block N overlaps
        # block N+1's compute, so this is the honest per-step pace the
        # pipeline sustains
        self._fetch_oldest()
        self._t_ingest_done = time.perf_counter()

    # ---------------------------------------------------------- public API

    def generate(self, req: GenerationRequest) -> GenerationResult:
        """Blocking convenience: submit and drive until THIS request is
        done. With the runner active, just waits; otherwise drives step()
        inline (tests / sync callers)."""
        box: list[GenerationResult] = []
        done_evt = threading.Event()
        user_cb = req.on_chunk

        def cb(delta: str, done: bool, res: GenerationResult | None):
            if user_cb:
                user_cb(delta, done, res)
            if done and res is not None:
                box.append(res)
                done_evt.set()

        req.on_chunk = cb
        self.submit(req)
        if self.running:
            done_evt.wait()
            return box[0]
        while not box:
            if not self.step() and not box:
                time.sleep(0.001)
        return box[0]

    # batch-size buckets for the embeddings path: bounded compile count
    # (|_EMBED_BATCH_BUCKETS| × |length buckets| programs max)
    _EMBED_BATCH_BUCKETS = (1, 4, 16, 32)

    def _batch_bucket(self, n: int) -> int:
        for b in self._EMBED_BATCH_BUCKETS:
            if n <= b:
                return b
        return self._EMBED_BATCH_BUCKETS[-1]

    def embed(self, texts: list[str]) -> list[list[float]]:
        """Pooled, L2-normalized embeddings. bert_embed models run the
        bidirectional encoder with their configured pooling (mean/cls);
        decoder families mean-pool final hidden states (padding masked at
        both attention and pooling via seq_lens).

        Batched: texts are grouped by length bucket and run up to
        `embed_batch` per forward (BASELINE config #5 is high-QPS batch
        embeddings — one-text-per-forward left ~B× on the table). Padding
        rows use len=1 so pooling never divides by zero; their outputs are
        discarded."""
        from gridllm_tpu.models.bert_embed import pool

        if (self.mesh is not None and self.mesh.shape.get("pp", 1) > 1
                and not self.embedding_only):
            # hidden_states has no pp schedule; GSPMD would gather the
            # pp-sharded layer stack onto every stage (the memory blow-up
            # pp exists to avoid). Loud failure > silent OOM.
            raise RuntimeError(
                f"{self.cfg.name}: decoder-model embeddings are not "
                "supported under pipeline parallelism — serve embeddings "
                "from a non-pp engine"
            )

        enc = [
            self.tokenizer.encode_for_embedding(t, self.max_context)
            for t in texts
        ]
        out: list[list[float] | None] = [None] * len(texts)
        by_bucket: dict[int, list[int]] = {}
        for i, ids in enumerate(enc):
            by_bucket.setdefault(self._bucket_for(max(len(ids), 1)), []).append(i)
        cap = max(1, self.config.embed_batch)
        for blen, idxs in sorted(by_bucket.items()):
            for start in range(0, len(idxs), cap):
                group = idxs[start : start + cap]
                bsz = min(self._batch_bucket(len(group)), cap)
                tok = np.zeros((bsz, blen), np.int32)
                lens = np.ones((bsz,), np.int32)
                for j, i in enumerate(group):
                    ids = enc[i]
                    tok[j, : len(ids)] = ids
                    lens[j] = max(len(ids), 1)
                # multi-host: the embed forward is a sharded program too —
                # it must enter the slice's serialized plan stream or its
                # collectives deadlock (embed runs on the executor thread,
                # so the shared dispatch_lock is what pins its position
                # relative to the runner's decode blocks)
                with self.dispatch_lock:
                    lens_j = jnp.asarray(lens)
                    h = self._embed_fn(self.params, jnp.asarray(tok), lens_j)
                    if self.plan_sink is not None:  # after-success
                        self.plan_sink({
                            "op": "embed",
                            "tok": tok.tolist(),
                            "lens": lens.tolist(),
                        })
                vecs = np.asarray(pool(h, lens_j, self.cfg.pooling), np.float32)
                for j, i in enumerate(group):
                    out[i] = vecs[j].tolist()
        return out  # type: ignore[return-value]

    def abort_all(self, msg: str) -> int:
        """Fail every pending and active request (driver recovery path:
        the worker pump calls this when step() raises, so waiters get an
        immediate error instead of hanging to the job timeout)."""
        n = 0
        with self._lock:
            pending, self._pending = list(self._pending), deque()
        for r in pending:
            self._fail(r, msg)
            n += 1
        for slot, st in list(self._slots.items()):
            # keep st.text: streamed deltas already sent must stay consistent
            # with the final text field; the failure rides res.error
            self._finish(slot, st, "error", error=msg)
            n += 1
        return n

    def resolve_seed(self) -> int:
        """Draw a sampler seed from the ENGINE-seeded RNG — the same
        stream admission uses for unseeded requests, so pre-resolving a
        seed worker-side (the crash-resume watermark must carry it,
        ISSUE 9) preserves EngineConfig.seed's reproducibility knob."""
        return int(self._rng.getrandbits(31))

    def _request_finish(self, req_id: str, op: str) -> bool:
        """Shared body of cancel()/suspend(): finish a pending or running
        request with done_reason=`op`.

        Thread-safe: pending removal happens here under the lock; a RUNNING
        slot is finished via the control queue at the runner's next block
        boundary (device state must only be touched by the driving thread)."""
        with self._lock:
            for i, r in enumerate(self._pending):
                if r.id == req_id:
                    del self._pending[i]
                    res = GenerationResult(id=req_id, done_reason=op)
                    if r.on_chunk:
                        r.on_chunk("", True, res)
                    return True
        for _slot, st in list(self._slots.items()):
            if st.req.id == req_id:
                self._ctl.append((op, req_id))
                if not self.running:
                    self._drain_ctl()
                else:
                    with self._work:
                        self._work.notify_all()
                return True
        return False

    def cancel(self, req_id: str) -> bool:
        """Cancel a pending or running request (reference analogue: job
        cancellation publish, JobScheduler.ts:530-536 → worker). The
        request's on_chunk gets a final done with done_reason='cancel'."""
        return self._request_finish(req_id, "cancel")

    def suspend(self, req_id: str) -> bool:
        """Suspend a pending or running request for graceful drain
        (ISSUE 9). A running slot finishes at the next block boundary
        with done_reason='suspend' and a GenerationResult carrying
        everything a resume needs (context, generated ids, text); its
        pages register in the prefix cache exactly like a normal finish —
        the export source for the drain migration. A still-pending
        request suspends empty (nothing generated yet)."""
        return self._request_finish(req_id, "suspend")

    def decode_snapshot(self, req_id: str) -> dict[str, Any] | None:
        """Last consistent resume watermark for a running request:
        ``{"tokens": [...generated ids...], "text": "..."}``. Lock-free
        read of the engine thread's atomic snapshot tuple (same contract
        as batch_state); None until the first surviving token lands."""
        for st in list(self._slots.values()):
            if st.req.id == req_id:
                snap = st.snapshot
                if snap is None:
                    return None
                toks, text = snap
                return {"tokens": list(toks), "text": text}
        return None

    # ------------------------------------------- KV-page migration (ISSUE 7)

    @property
    def free_slot_count(self) -> int:
        """Open batch slots — the decode-headroom figure heartbeats carry
        for the scheduler's decode-pool placement."""
        return 0 if self.embedding_only else len(self._free_slots)

    def kv_transfer_supported(self) -> bool:
        """Export/import needs the content-addressed prefix cache (the
        transfer unit IS cached pages) and a process-local, unsharded
        pool: a mesh shards the pool across devices and a multi-host
        plan replay would desync on any out-of-plan pool mutation."""
        return (not self.embedding_only
                and self._prefix_cache_cap != 0
                and self.mesh is None
                and self.plan_sink is None)

    def export_prefix_pages(self, token_ids: list[int]) -> dict[str, Any] | None:
        """Gather the longest cached full-page prefix of `token_ids` as
        host arrays for the migration wire (transfer/wire.py). Returns
        {tokens, k, v, model, kvLayout, quant} with k/v
        [L, n, ps, KVH, D] sliced to the UNPADDED model head dim, or
        None when nothing is cached / transfer is unsupported here.

        The pages are refcount-pinned for the duration of the device
        gather so a concurrent admission can neither evict nor overwrite
        them; the pin is dropped before returning."""
        if not self.kv_transfer_supported():
            return None
        with self._alloc_lock:
            pages, tokens = self.alloc.pin_prefix(token_ids)
        if not pages:
            return None
        try:
            with self.dispatch_lock:
                # dispatch the gather only — it materializes its own
                # device buffers, so the (slow, size-proportional)
                # device→host copy below runs WITHOUT the lock and
                # concurrent decode dispatch never stalls on an export
                idx = jnp.asarray(pages, jnp.int32)
                d = self.cfg.head_dim_
                if self._kv_int8:
                    # int8 pool (ISSUE 11): the wire carries the engine
                    # compute dtype so fp and int8 workers interoperate —
                    # dequantize on export, requantize on install
                    dt = jnp.dtype(self.config.dtype)
                    k_dev = (
                        self.cache.k.data[:, idx][..., :d]
                        .astype(jnp.float32)
                        * self.cache.k.scale[:, idx][..., None, None]
                    ).astype(dt)
                    v_dev = (
                        self.cache.v.data[:, idx][..., :d]
                        .astype(jnp.float32)
                        * self.cache.v.scale[:, idx][..., None, None]
                    ).astype(dt)
                else:
                    k_dev = self.cache.k[:, idx][..., :d]
                    v_dev = self.cache.v[:, idx][..., :d]
            k = np.asarray(k_dev)
            v = np.asarray(v_dev)
        finally:
            with self._alloc_lock:
                self.alloc.unpin_pages(pages)
        dpool = self.cache.k.shape[-1]
        layout = (("ragged" if dpool == d else "ragged-padded")
                  if self._ragged else "legacy")
        return {
            "tokens": [int(t) for t in token_ids[:tokens]],
            "k": k, "v": v,
            "model": self.cfg.name,
            "kvLayout": layout,
            "quant": self.config.quantize,
        }

    def import_prefix_pages(self, token_ids: list[int], k: np.ndarray,
                            v: np.ndarray, meta: dict[str, Any]) -> int:
        """Install migrated KV pages into this engine's pool and register
        them in the content-addressed prefix cache (refcount allocator),
        so the request's decode-side admission shares them via the normal
        match_prefix warm path. Returns the number of tokens installed
        (contiguous from position 0; may be shorter than offered under
        pool pressure — a shorter prefix is still valid). Raises on any
        geometry/dtype mismatch; the sender treats that as a NACK and
        falls back to serving the request locally."""
        if not self.kv_transfer_supported():
            raise ValueError(
                f"{self.cfg.name}: KV import unsupported here (prefix "
                "cache off, sharded pool, or multi-host plan replay)")
        mc, c = self.cfg, self.config
        ps = c.page_size
        kvh, dpool = self.cache.k.shape[3], self.cache.k.shape[4]
        if int(meta["pageSize"]) != ps:
            raise ValueError(
                f"page-size mismatch: wire {meta['pageSize']} vs pool {ps}")
        if (int(meta["numLayers"]) != mc.num_layers
                or int(meta["kvHeads"]) != kvh
                or int(meta["headDim"]) != mc.head_dim_):
            raise ValueError(
                f"pool geometry mismatch: wire L{meta['numLayers']}/"
                f"H{meta['kvHeads']}/D{meta['headDim']} vs "
                f"L{mc.num_layers}/H{kvh}/D{mc.head_dim_}")
        # int8 pools (ISSUE 11) exchange fp pages on the wire (export
        # dequantizes, install requantizes) — the contract dtype is the
        # engine compute dtype, not the pool storage dtype
        wire_dtype = (jnp.dtype(c.dtype) if self._kv_int8
                      else self.cache.k.dtype)
        if jnp.dtype(str(meta["dtype"])) != wire_dtype:
            raise ValueError(
                f"dtype mismatch: wire {meta['dtype']} vs pool "
                f"{wire_dtype}")
        n = min(int(k.shape[1]), len(token_ids) // ps)
        keys = self.alloc.chain_keys(token_ids, n_pages=n)
        # claim pool pages under the allocator lock; claimed pages come
        # back PINNED and UNREGISTERED — the chain key only becomes
        # matchable AFTER the device write lands, so a concurrent
        # admission can never match (and decode over) an unwritten page
        writes: list[tuple[int, int, bytes]] = []  # (page, wire idx, key)
        installed = 0
        with self._alloc_lock:
            for i, key in enumerate(keys):
                if self.alloc.peek_key(key) is not None:
                    # identical content already cached here (possibly
                    # pinned by a live request) — skip the write, keep it
                    installed = i + 1
                    continue
                page = self.alloc.claim_page()
                if page is None:
                    break  # pool exhausted: keep the shorter prefix
                writes.append((page, i, key))
                installed = i + 1
        if writes:
            try:
                self._write_imported_pages(
                    [(p, i) for p, i, _ in writes], k, v, dpool)
                with self._alloc_lock:
                    for page, _i, key in writes:
                        self.alloc.register_claimed(page, key)
            finally:
                with self._alloc_lock:
                    self.alloc.unpin_pages([p for p, _, _ in writes])
        self._update_kv_gauges()
        _FLIGHTREC.record("engine", "kv_import", model=self.cfg.name,
                          pagesInstalled=len(writes),
                          pagesShared=installed - len(writes),
                          tokens=installed * ps)
        return installed * ps

    _IMPORT_PAGE_BLOCK = 8  # pages per jitted install (fixed shape)

    def _write_imported_pages(self, writes: list[tuple[int, int]],
                              k: np.ndarray, v: np.ndarray,
                              dpool: int,
                              k_rowscale: np.ndarray | None = None,
                              v_rowscale: np.ndarray | None = None) -> None:
        """Scatter imported page data into the pool in fixed-size blocks
        (sentinel-padded so ONE compiled program serves any count), with
        buffer donation so the pool is updated in place.

        int8 pools (ISSUE 11): ``k``/``v`` either arrive as int8 with
        per-row scales (``k_rowscale``/``v_rowscale`` [L, n, ps] — a
        host-tier restore of an int8 spill) or as fp pages (a KV
        migration), which requantize per row host-side here."""
        if self._kv_int8 and k_rowscale is None:
            from gridllm_tpu.ops.kvtier import quantize_rows_np

            k, k_rowscale = quantize_rows_np(k)
            v, v_rowscale = quantize_rows_np(v)
        if dpool != k.shape[-1]:  # lane-padded pool: zero-pad the lanes
            pad = [(0, 0)] * (k.ndim - 1) + [(0, dpool - k.shape[-1])]
            k, v = np.pad(k, pad), np.pad(v, pad)
        if self._kv_install_fn is None:
            if self._kv_int8:
                @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
                def install_fn(kd, ksc, vd, vsc, idx, k_new, ks_new,
                               v_new, vs_new):
                    return (kd.at[:, idx].set(k_new, mode="drop"),
                            ksc.at[:, idx].set(ks_new, mode="drop"),
                            vd.at[:, idx].set(v_new, mode="drop"),
                            vsc.at[:, idx].set(vs_new, mode="drop"))
            else:
                @partial(jax.jit, donate_argnums=(0, 1))
                def install_fn(k_pages, v_pages, idx, k_new, v_new):
                    return (k_pages.at[:, idx].set(k_new, mode="drop"),
                            v_pages.at[:, idx].set(v_new, mode="drop"))

            # armable=False: imports legitimately first compile long after
            # the engine arms (the first migration can land any time)
            self._kv_install_fn = self.perf.wrap("kv_install", install_fn,
                                                 armable=False)
        block = self._IMPORT_PAGE_BLOCK
        sentinel = self.config.num_pages  # out of bounds → mode="drop"
        dt = self.cache.k.dtype
        for s0 in range(0, len(writes), block):
            grp = writes[s0:s0 + block]
            idx = np.full((block,), sentinel, np.int32)
            kb = np.zeros((k.shape[0], block) + k.shape[2:], dtype=k.dtype)
            vb = np.zeros_like(kb)
            if self._kv_int8:
                ksb = np.ones((k.shape[0], block, self.config.page_size),
                              np.float32)
                vsb = np.ones_like(ksb)
            for j, (page, src) in enumerate(grp):
                idx[j] = page
                kb[:, j] = k[:, src]
                vb[:, j] = v[:, src]
                if self._kv_int8:
                    ksb[:, j] = k_rowscale[:, src]
                    vsb[:, j] = v_rowscale[:, src]
            with self.dispatch_lock:
                if self._kv_int8:
                    kd, ksc, vd, vsc = self._kv_install_fn(
                        self.cache.k.data, self.cache.k.scale,
                        self.cache.v.data, self.cache.v.scale,
                        jnp.asarray(idx), jnp.asarray(kb, dt),
                        jnp.asarray(ksb), jnp.asarray(vb, dt),
                        jnp.asarray(vsb))
                    new_k = QuantPages(kd, ksc)
                    new_v = QuantPages(vd, vsc)
                else:
                    new_k, new_v = self._kv_install_fn(
                        self.cache.k, self.cache.v, jnp.asarray(idx),
                        jnp.asarray(kb, dt), jnp.asarray(vb, dt))
                self.cache = PagedKVCache(
                    k=new_k, v=new_v, page_table=self.cache.page_table,
                    lengths=self.cache.lengths,
                    page_size=self.cache.page_size)

    # ----------------------------------------- tiered KV cache (ISSUE 11)

    def _spill_page_to_host(self, page: int, key: bytes) -> None:
        """Allocator spill hook: copy one about-to-be-evicted prefix-cache
        page into the host tier (fires under _alloc_lock, from inside the
        allocator's eviction paths). Best-effort — a failure (or the
        ``kvtier.spill`` fault site) just loses the page from the tier
        and the later match degrades to a cold prefill."""
        tier = self.host_tier
        if tier is None or self.plan_sink is not None:
            return
        if key in tier:
            return  # content-addressed: the existing host copy is valid
        if faults.check("kvtier.spill"):
            return
        # one synchronous device→host round trip per NEW page, under the
        # caller's _alloc_lock; re-evictions short-circuit above, so only
        # first-time spills pay it. Batching an alloc()'s whole eviction
        # set into one indexed gather (the export_prefix_pages shape)
        # needs an allocator-side evict-N hook — deliberate future work.
        d = self.cfg.head_dim_
        # TRACED index gather (same pattern as export_prefix_pages): a
        # static python-int slice would compile one XLA program per
        # distinct page id — an eviction storm over a big pool would
        # serialize fresh compiles on the admission path
        idx = jnp.asarray([page], jnp.int32)
        with self.dispatch_lock:
            # dispatch the gather only; the device→host copy below runs
            # without the lock (same discipline as export_prefix_pages)
            if self._kv_int8:
                k_dev = self.cache.k.data[:, idx][..., :d]
                v_dev = self.cache.v.data[:, idx][..., :d]
                ks_dev = self.cache.k.scale[:, idx]
                vs_dev = self.cache.v.scale[:, idx]
            else:
                k_dev = self.cache.k[:, idx][..., :d]
                v_dev = self.cache.v[:, idx][..., :d]
        k = np.asarray(k_dev)                    # [L, 1, ps, KVH, D]
        v = np.asarray(v_dev)
        if self._kv_int8:
            tier.put(key, k, v,
                     k_scale=np.asarray(ks_dev),
                     v_scale=np.asarray(vs_dev),
                     quant="int8-rows")
        else:
            tier.put(key, k, v)

    def _restore_page_from_host(self, key: bytes) -> int | None:
        """Allocator restore hook (consulted by match_prefix under
        _alloc_lock on a chain miss): page one spilled page back into a
        fresh pool page, register it under its chain key at refcount 0,
        and return the page id so the match keeps walking. None = tier
        miss / injected fault / pool pressure / integrity failure — the
        admission degrades to a cold prefill, never a wedged request."""
        tier = self.host_tier
        if tier is None or self.plan_sink is not None:
            return None
        rec = tier.get(key)
        if rec is None:
            return None
        if faults.check("kvtier.restore"):
            tier.note_restore_failure()
            return None
        with self._alloc_lock:
            page = self.alloc.claim_page()
        if page is None:
            tier.note_restore_failure()  # pool pressure: nowhere to land
            return None
        k, v, ks, vs, quant = rec
        try:
            self._install_restored_page(page, k, v, ks, vs, quant)
        except Exception as e:  # noqa: BLE001 — degrade to cold prefill
            log.warning("host-tier restore install failed",
                        model=self.cfg.name, error=str(e))
            tier.note_restore_failure()
            with self._alloc_lock:
                self.alloc.unpin_pages([page])
            return None
        with self._alloc_lock:
            self.alloc.register_claimed(page, key)
            self.alloc.unpin_pages([page])
            out = self.alloc.peek_key(key)
        tier.mark_restored(key)
        return out

    def _install_restored_page(self, page: int, k: np.ndarray,
                               v: np.ndarray, ks: np.ndarray | None,
                               vs: np.ndarray | None,
                               quant: str | None) -> None:
        """Decode one spill record to the pool's dtype/layout and write it
        into ``page`` (the import install program, reused)."""
        from gridllm_tpu.ops.kvtier import dequantize_page

        dpool = self.cache.k.shape[-1]
        if self._kv_int8:
            if quant == "int8-rows":
                # int8 spill of an int8 pool: rows + scales land verbatim
                # (ks/vs [L, 1, ps])
                self._write_imported_pages(
                    [(page, 0)], k, v, dpool,
                    k_rowscale=np.asarray(ks, np.float32),
                    v_rowscale=np.asarray(vs, np.float32))
                return
            if quant == "int8-page":
                k, v = dequantize_page(k, ks), dequantize_page(v, vs)
            self._write_imported_pages(
                [(page, 0)], np.asarray(k, np.float32),
                np.asarray(v, np.float32), dpool)
            return
        if quant == "int8-page":
            k, v = dequantize_page(k, ks), dequantize_page(v, vs)
        elif quant == "int8-rows":
            k = np.asarray(k, np.float32) * ks[..., None, None]
            v = np.asarray(v, np.float32) * vs[..., None, None]
        self._write_imported_pages([(page, 0)], k, v, dpool)

    def park_to_host(self, token_ids: list[int]) -> int:
        """Suspend-to-host (ISSUE 11): move the cached full-page prefix
        of ``token_ids`` into the host tier and FREE its HBM pages, so a
        suspended decode stops occupying device memory entirely. The
        later resume admission restores the pages through the normal
        match_prefix warm path. Pages still shared with a live request
        are copied but NOT freed — a pinned shared page never leaves HBM
        mid-decode. Returns the number of tokens whose pages now live in
        the host tier (contiguous from position 0)."""
        tier = self.host_tier
        if tier is None or self.plan_sink is not None or len(token_ids) < 2:
            return 0
        with self._alloc_lock:
            pages, _covered = self.alloc.pin_prefix(token_ids)
        if not pages:
            return 0
        keys = self.alloc.chain_keys(token_ids, n_pages=len(pages))
        parked = 0
        try:
            for pg, key in zip(pages, keys):
                self._spill_page_to_host(pg, key)
                if key in tier:
                    parked += 1
                else:
                    break  # keep the parked prefix contiguous
        finally:
            with self._alloc_lock:
                self.alloc.unpin_pages(pages)
                self.alloc.evict_cached(
                    [pg for pg, key in zip(pages, keys) if key in tier])
        self._update_kv_gauges()
        _FLIGHTREC.record("engine", "kv_park", model=self.cfg.name,
                          pages=parked,
                          tokens=parked * self.config.page_size)
        return parked * self.config.page_size

    @property
    def active_requests(self) -> int:
        return len(self._slots)

    @property
    def queued_requests(self) -> int:
        return len(self._pending)

    def batch_state(self) -> dict[str, Any]:
        """Point-in-time batch snapshot for hang diagnoses and flight
        recorder dumps (obs/flightrec.py engine probes): which request
        holds which slot, how far it got, and how long since its last
        host-visible token. Reads mutable state without the dispatch lock
        — a wedged runner holding that lock is exactly when this must
        still answer; a torn read is a cosmetic risk, a blocked dump a
        fatal one."""
        now_ns = time.perf_counter_ns()
        wall = time.time()
        slots = {}
        for slot, st in list(self._slots.items()):
            slots[str(slot)] = {
                "request": st.req.id,
                "phase": "decode" if st.t_first_decode else "prefill",
                "promptTokens": st.prompt_len,
                "generated": len(st.generated),
                "ageS": round((now_ns - st.t_start) / 1e9, 3),
                "sinceLastTokenS": (
                    round(wall - st.t_last_ingest, 3)
                    if st.t_last_ingest else None),
            }
        return {
            "model": self.cfg.name,
            "running": self.running,
            "embeddingOnly": self.embedding_only,
            "slots": slots,
            "pending": len(self._pending),
            "inflightBlocks": len(self._inflight),
            "dispatchGen": self._gen,
            "freeSlots": len(self._free_slots),
            "kvPagesFree": self.alloc.free_pages
            if not self.embedding_only else None,
            "kvPagesCached": self.alloc.cached_pages
            if not self.embedding_only else None,
            "prefixCache": {
                "hits": self.alloc.hits, "misses": self.alloc.misses,
                "evictions": self.alloc.evictions,
                "cowCopies": self.alloc.cow_copies,
            } if not self.embedding_only else None,
            "hostTier": (self.host_tier.stats()
                         if not self.embedding_only
                         and self.host_tier is not None else None),
            "specDecode": {
                "k": self._spec_k,
                "drafter": getattr(self._drafter, "kind", "ngram"),
                "treeWidth": (self._tree_width
                              if getattr(self._drafter, "tree", False)
                              else 1),
                **self.spec_stats,
            } if self._spec_k else None,
            "jit": self.perf.state(),
        }

    def memory_arrays(self) -> dict[str, Any]:
        """Live device buffers + allocator math for the memory probe
        (obs/perf.py memory_snapshot): weight and KV-pool arrays by
        identity (the snapshot classifies jax.live_arrays() against
        them), plus JSON-safe page-pool accounting. Reads mutable state
        without the dispatch lock, same contract as batch_state()."""
        weights = [a for a in jax.tree_util.tree_leaves(self.params)
                   if hasattr(a, "nbytes")]
        out: dict[str, Any] = {"weights": weights, "kv": [], "alloc": None}
        if self.embedding_only:
            return out
        cache = self.cache
        if isinstance(cache.k, QuantPages):
            out["kv"] = [cache.k.data, cache.k.scale, cache.v.data,
                         cache.v.scale, cache.page_table, cache.lengths]
        else:
            out["kv"] = [cache.k, cache.v, cache.page_table, cache.lengths]
        c, mc = self.config, self.cfg
        kv_bytes = cache.k.nbytes + cache.v.nbytes
        bpp = kv_bytes / max(c.num_pages, 1)
        used = c.num_pages - self.alloc.free_pages - self.alloc.cached_pages
        live_tokens = sum(len(st.ids) for st in list(self._slots.values()))
        dpool = cache.k.shape[-1]
        capacity_tokens = used * c.page_size
        out["alloc"] = {
            "numPages": c.num_pages,
            "pageSize": c.page_size,
            "pagesUsed": used,
            "pagesCached": self.alloc.cached_pages,
            "pagesFree": self.alloc.free_pages,
            "bytesPerPage": int(bpp),
            "usedBytes": int(used * bpp),
            "cachedBytes": int(self.alloc.cached_pages * bpp),
            "freeBytes": int(self.alloc.free_pages * bpp),
            # lane padding multiplies KV bytes for d<128 models under the
            # kernel path (_pool_head_dim) — this is that overhead's share.
            # Under the ragged flat-lane layout (kvLayout "ragged") the
            # pool stays UNPADDED, so this reads 0 — the KV-bytes win of
            # ISSUE 6, visible directly here
            "lanePadOverheadBytes": int(
                kv_bytes * (1 - mc.head_dim_ / dpool)) if dpool else 0,
            # "ragged" = unified attention on an unpadded pool (the zero-
            # overhead case the README documents); "ragged-padded" =
            # ragged attention but the shape can't go flat-lane (e.g.
            # KVH=1, d=64), so the pool still pays the pad
            "kvLayout": (
                ("ragged" if dpool == mc.head_dim_ else "ragged-padded")
                if self._ragged else "legacy"),
            "liveTokens": live_tokens,
            # internal fragmentation of the live allocation: capacity
            # reserved at admission (num_predict headroom + tail pages)
            # not yet holding tokens. Clamped at 0: prefix-cache sharing
            # counts a shared page ONCE in pagesUsed while every sharer's
            # tokens land in liveTokens, so the ratio can exceed 1 in the
            # warm steady state — that is sharing, not fragmentation.
            "fragmentation": (
                max(0.0, round(1 - live_tokens / capacity_tokens, 4))
                if capacity_tokens else 0.0),
            # tiered KV cache (ISSUE 11): int8 residency + host-tier
            # occupancy/flow, itemized per tier in /admin/memory
            "kvInt8": self._kv_int8,
            "hostTier": (self.host_tier.stats()
                         if self.host_tier is not None else None),
        }
        return out
