"""Host-side image preprocessing for vision models (CLIP pipeline).

Mirrors HF CLIPImageProcessor's default llava-1.5 pipeline exactly
(tests/test_llava.py checks against it): RGB convert → resize the SHORT
side to `image_size` (bicubic) → center crop `image_size`² → scale 1/255
→ normalize with the CLIP mean/std. Deterministic: multi-host followers
re-run it on the raw base64 payload from the liaison's plan record and
get bit-identical pixel arrays.

The reference shipped base64 images straight to Ollama
(client/src/services/OllamaService.ts:197-226); this is the native
replacement's host half — the device half is models/llava.py.
"""

from __future__ import annotations

import base64
import io

import numpy as np

# CLIP normalization constants (OPENAI_CLIP_MEAN/STD)
_MEAN = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
_STD = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)


def preprocess_images(images_b64: list[str], image_size: int) -> np.ndarray:
    """base64 (or raw-bytes) images → [N, 3, S, S] float32 pixel values."""
    from PIL import Image

    out = []
    for item in images_b64:
        raw = base64.b64decode(item) if isinstance(item, str) else bytes(item)
        img = Image.open(io.BytesIO(raw)).convert("RGB")
        w, h = img.size
        # shortest-edge resize (CLIPImageProcessor {"shortest_edge": S});
        # the long side TRUNCATES (transformers get_resize_output_image_size
        # uses int(), not round()) — bit-parity matters: multi-host
        # followers re-run this on the raw payload
        if w <= h:
            nw, nh = image_size, max(1, int(h * image_size / w))
        else:
            nw, nh = max(1, int(w * image_size / h)), image_size
        img = img.resize((nw, nh), Image.Resampling.BICUBIC)
        # center crop S×S (matches transformers' center_crop rounding)
        left = (nw - image_size) // 2
        top = (nh - image_size) // 2
        img = img.crop((left, top, left + image_size, top + image_size))
        arr = np.asarray(img, np.float32) / 255.0        # [S, S, 3]
        arr = (arr - _MEAN) / _STD
        out.append(arr.transpose(2, 0, 1))               # [3, S, S]
    return np.stack(out)
