"""Tokenizer abstraction.

The reference never tokenizes (Ollama does, externally). The engine needs
one, with two backends:

- `HFTokenizer`: wraps a *local* transformers tokenizer directory (the deploy
  story ships tokenizer.json next to the safetensors; nothing is downloaded).
- `ByteTokenizer`: self-contained byte-level fallback (ids 0..255 = bytes,
  + BOS/EOS) used by tests and the synthetic bench path so the full engine
  runs with zero external artifacts.

Incremental streaming uses `DetokState`: decoding token-by-token must not
emit partial UTF-8 sequences (a multi-byte char split across tokens), so
text is withheld while it ends in the replacement char.
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, Sequence


class Tokenizer(Protocol):
    bos_id: int | None
    eos_ids: frozenset[int]
    vocab_size: int

    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...
    def encode_for_embedding(
        self, text: str, max_len: int | None = None
    ) -> list[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...


@dataclasses.dataclass
class DetokState:
    """Incremental detokenization cursor over a growing id list."""

    emitted_chars: int = 0

    def delta(self, tok: Tokenizer, ids: Sequence[int]) -> str:
        """Text newly finalized by the latest ids. Holds back trailing bytes
        that decode to U+FFFD (possible split multi-byte char)."""
        text = tok.decode(ids)
        safe_end = len(text)
        while safe_end > 0 and text[safe_end - 1] == "�":
            safe_end -= 1
        if safe_end <= self.emitted_chars:
            return ""
        out = text[self.emitted_chars : safe_end]
        self.emitted_chars = safe_end
        return out


class ByteTokenizer:
    """Bytes → ids 0..255; BOS=256, EOS=257. vocab_size=258 fits every tiny
    test config (rounded up to 256 there via modulo guard at encode)."""

    def __init__(self, vocab_size: int = 258):
        self.vocab_size = max(vocab_size, 258)
        self.bos_id: int | None = 256
        self.eos_ids = frozenset({257})

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] + ids) if add_bos and self.bos_id is not None else ids

    def encode_for_embedding(self, text: str, max_len: int | None = None) -> list[int]:
        ids = self.encode(text, add_bos=True)
        return ids[:max_len] if max_len is not None else ids

    def decode(self, ids: Sequence[int]) -> str:
        data = bytes(i for i in ids if 0 <= i < 256)
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """Local-directory transformers tokenizer (no network)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.bos_id = self._tok.bos_token_id
        eos = self._tok.eos_token_id
        ids = set(eos if isinstance(eos, list) else [eos] if eos is not None else [])
        # llama3 chat also stops on <|eot_id|>
        eot = self._tok.convert_tokens_to_ids("<|eot_id|>")
        if isinstance(eot, int) and eot >= 0:
            ids.add(eot)
        self.eos_ids = frozenset(ids)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        if add_bos and self.bos_id is not None:
            ids = [self.bos_id] + ids
        return ids

    def encode_for_embedding(self, text: str, max_len: int | None = None) -> list[int]:
        """Full special-token template — BERT-family tokenizers wrap with
        [CLS]...[SEP], which cls-pooling (models/bert_embed.pool) relies on
        reading at position 0. Truncation happens INSIDE the tokenizer so
        the trailing [SEP] survives (slicing after the fact would cut it,
        diverging from the HF/sentence-transformers pipeline)."""
        if max_len is not None:
            return self._tok.encode(
                text, add_special_tokens=True, truncation=True,
                max_length=max_len,
            )
        return self._tok.encode(text, add_special_tokens=True)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def get_tokenizer(spec: str | None, vocab_size: int = 258) -> Tokenizer:
    """spec: None/"byte" → ByteTokenizer; anything else → local HF dir."""
    if spec is None or spec == "byte":
        return ByteTokenizer(vocab_size)
    return HFTokenizer(spec)
