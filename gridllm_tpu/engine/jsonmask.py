"""Grammar-constrained JSON decoding: a pushdown automaton over JSON
syntax drives a per-step vocabulary mask (VERDICT r04 #3).

**EXPERIMENTAL — NOT INTEGRATED.** Nothing imports this module today:
the sampler (ops/sampling.py) has NO ``allowed``-mask hook, and the
worker's ``format:"json"`` path (worker/prompting.py) enforces JSON via
instruction injection + post-extraction only. Until an engine-side
per-step mask hook exists, the hard-parse guarantee this module could
provide is NOT delivered — do not assume constrained decoding is active.
The module is kept import-clean (a collection-level test enforces it) as
the grammar groundwork for that future hook.

Ollama guarantees `format:"json"` output parses by masking logits with a
llama.cpp GBNF grammar; the reference inherited that guarantee via
passthrough (client/src/services/OllamaService.ts:197-226). This module
is the TPU-native analogue: the PDA runs on the host (it is inherently
sequential in the sampled tokens), producing a boolean [V] mask that a
future device-sampler mask hook would consume before each constrained
step. Masks are cached by PDA *state signature* — a
token can pop at most as many containers as it has closing characters,
so validity depends only on the mode, the literal/number sub-state, and
the top max_pops stack entries; signatures repeat heavily across steps
and requests, so each unique one is simulated over the vocab once.

Design notes:
- Full JSON grammar (RFC 8259): objects/arrays to arbitrary depth,
  strings with \\u escapes, strict numbers (no leading zeros), literals.
- Token-level: a token is allowed iff EVERY character keeps the PDA
  valid. EOS is allowed only when the root value is complete; at
  COMPLETE the mask is {EOS} alone, so constrained generations always
  terminate instead of trailing whitespace forever.
- Tokens whose text is empty (special tokens) are never allowed — they
  make no parsing progress and would permit non-terminating output.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# modes (plain ints — simulated in tight Python loops)
VAL = 0          # expecting a value
ARR_FIRST = 1    # after '[': value or ']'
OBJ_FIRST = 2    # after '{': key or '}'
OBJ_KEY = 3      # after ',' in object: key only
KEY_STR = 4      # inside a key string
KEY_ESC = 5
KEY_U1, KEY_U2, KEY_U3, KEY_U4 = 6, 7, 8, 9
AFTER_KEY = 10   # expecting ':'
STR = 11         # inside a value string
STR_ESC = 12
STR_U1, STR_U2, STR_U3, STR_U4 = 13, 14, 15, 16
AFTER_VAL = 17   # expecting ',' or the container's closer
NUM_SIGN = 18    # after '-'
NUM_ZERO = 19    # after leading '0'
NUM_INT = 20
NUM_DOT = 21
NUM_FRAC = 22
NUM_E = 23
NUM_ESIGN = 24
NUM_EXP = 25
LIT = 26         # inside true/false/null (lit = remaining chars)
COMPLETE = 27    # root value done

_WS = " \t\n\r"
_HEX = set("0123456789abcdefABCDEF")
_ESCAPABLE = set('"\\/bfnrt')
# number modes where the value may legally end at the next delimiter
_NUM_END = (NUM_ZERO, NUM_INT, NUM_FRAC, NUM_EXP)


@dataclasses.dataclass(frozen=True)
class JsonState:
    mode: int = VAL
    stack: tuple = ()      # '{' / '[' entries, innermost last
    lit: str = ""          # remaining literal chars in LIT mode

    def signature(self, max_pops: int):
        """Hashable key capturing exactly what token validity depends on:
        a token with max_pops closing characters can inspect at most the
        top max_pops stack entries plus whether deeper entries exist."""
        depth = len(self.stack)
        return (
            self.mode, self.lit, self.stack[-max_pops:],
            depth if depth <= max_pops else -1,
        )


def _close(stack) -> JsonState:
    """A value just finished at the current nesting."""
    return JsonState(AFTER_VAL if stack else COMPLETE, stack)


def advance_char(st: JsonState, ch: str) -> JsonState | None:
    """One character through the PDA; None = invalid."""
    m, stack = st.mode, st.stack

    if m in (VAL, ARR_FIRST):
        if ch in _WS:
            return st
        if ch == "{":
            return JsonState(OBJ_FIRST, stack + ("{",))
        if ch == "[":
            return JsonState(ARR_FIRST, stack + ("[",))
        if ch == '"':
            return JsonState(STR, stack)
        if ch == "-":
            return JsonState(NUM_SIGN, stack)
        if ch == "0":
            return JsonState(NUM_ZERO, stack)
        if ch in "123456789":
            return JsonState(NUM_INT, stack)
        if ch == "t":
            return JsonState(LIT, stack, "rue")
        if ch == "f":
            return JsonState(LIT, stack, "alse")
        if ch == "n":
            return JsonState(LIT, stack, "ull")
        if m == ARR_FIRST and ch == "]":
            return _close(stack[:-1])
        return None

    if m == OBJ_FIRST:
        if ch in _WS:
            return st
        if ch == '"':
            return JsonState(KEY_STR, stack)
        if ch == "}":
            return _close(stack[:-1])
        return None

    if m == OBJ_KEY:
        if ch in _WS:
            return st
        if ch == '"':
            return JsonState(KEY_STR, stack)
        return None

    if m in (KEY_STR, STR):
        key = m == KEY_STR
        if ch == '"':
            return JsonState(AFTER_KEY, stack) if key else _close(stack)
        if ch == "\\":
            return JsonState(KEY_ESC if key else STR_ESC, stack)
        if ord(ch) < 0x20:
            return None  # raw control chars are invalid in strings
        return st

    if m in (KEY_ESC, STR_ESC):
        key = m == KEY_ESC
        if ch in _ESCAPABLE:
            return JsonState(KEY_STR if key else STR, stack)
        if ch == "u":
            return JsonState(KEY_U1 if key else STR_U1, stack)
        return None

    if m in (KEY_U1, KEY_U2, KEY_U3, KEY_U4, STR_U1, STR_U2, STR_U3, STR_U4):
        if ch not in _HEX:
            return None
        if m in (KEY_U4, STR_U4):
            return JsonState(KEY_STR if m == KEY_U4 else STR, stack)
        return JsonState(m + 1, stack)

    if m == AFTER_KEY:
        if ch in _WS:
            return st
        if ch == ":":
            return JsonState(VAL, stack)
        return None

    if m == AFTER_VAL:
        if ch in _WS:
            return st
        if ch == ",":
            if not stack:
                return None
            return JsonState(OBJ_KEY if stack[-1] == "{" else VAL, stack)
        if ch == "}" and stack and stack[-1] == "{":
            return _close(stack[:-1])
        if ch == "]" and stack and stack[-1] == "[":
            return _close(stack[:-1])
        return None

    if m in _NUM_END:
        # digits / continuations first, else the number ends and ch is
        # re-processed as a delimiter at AFTER_VAL/COMPLETE
        if m == NUM_ZERO:
            if ch == ".":
                return JsonState(NUM_DOT, stack)
            if ch in "eE":
                return JsonState(NUM_E, stack)
        elif m == NUM_INT:
            if ch.isdigit():
                return st
            if ch == ".":
                return JsonState(NUM_DOT, stack)
            if ch in "eE":
                return JsonState(NUM_E, stack)
        elif m == NUM_FRAC:
            if ch.isdigit():
                return st
            if ch in "eE":
                return JsonState(NUM_E, stack)
        elif m == NUM_EXP and ch.isdigit():
            return st
        return advance_char(_close(stack), ch)

    if m == NUM_SIGN:
        if ch == "0":
            return JsonState(NUM_ZERO, stack)
        if ch in "123456789":
            return JsonState(NUM_INT, stack)
        return None

    if m == NUM_DOT:
        return JsonState(NUM_FRAC, stack) if ch.isdigit() else None

    if m == NUM_E:
        if ch in "+-":
            return JsonState(NUM_ESIGN, stack)
        return JsonState(NUM_EXP, stack) if ch.isdigit() else None

    if m == NUM_ESIGN:
        return JsonState(NUM_EXP, stack) if ch.isdigit() else None

    if m == LIT:
        if st.lit and ch == st.lit[0]:
            rest = st.lit[1:]
            return JsonState(LIT, stack, rest) if rest else _close(stack)
        return None

    if m == COMPLETE:
        return st if ch in _WS else None

    raise AssertionError(f"unknown mode {m}")


def advance_text(st: JsonState, text: str) -> JsonState | None:
    for ch in text:
        st = advance_char(st, ch)
        if st is None:
            return None
    return st


def _at_complete(st: JsonState) -> bool:
    """EOS-eligible: the root value is syntactically complete (incl. a
    top-level number that can end at end-of-output)."""
    if st.mode == COMPLETE:
        return True
    return st.mode in _NUM_END and not st.stack


class JsonMaskCache:
    """Per-tokenizer vocabulary masks for JSON-constrained sampling.

    token_texts[i] is the decoded text of vocab id i ("" for special /
    undecodable tokens — never allowed). Masks are np.bool_[V], cached by
    state signature; a cache entry is computed by simulating every
    non-empty token's characters through the PDA once (~0.5 s for a 128k
    vocab — amortized across all steps and requests that reach the same
    signature)."""

    def __init__(self, token_texts: list[str], eos_ids) -> None:
        self.texts = token_texts
        self.eos_ids = sorted(set(int(e) for e in eos_ids))
        self.vocab = len(token_texts)
        # a token can pop at most count('}')+count(']') container levels
        self.max_pops = max(
            (t.count("}") + t.count("]") for t in token_texts if t),
            default=1,
        )
        self._cache: dict = {}
        # EOS ids are excluded even if they decode to text ("</s>"):
        # sampling one ENDS generation, it never appends its surface form
        eos_set = set(self.eos_ids)
        self._candidates = [
            (i, t) for i, t in enumerate(token_texts)
            if t and i not in eos_set
        ]

    def mask(self, st: JsonState) -> np.ndarray:
        sig = st.signature(self.max_pops)
        got = self._cache.get(sig)
        if got is not None:
            return got
        m = np.zeros((self.vocab,), np.bool_)
        if st.mode == COMPLETE:
            # terminate deterministically: EOS is the only continuation
            for e in self.eos_ids:
                if 0 <= e < self.vocab:
                    m[e] = True
            self._cache[sig] = m
            return m
        if _at_complete(st):
            # a top-level number may either continue or end here
            for e in self.eos_ids:
                if 0 <= e < self.vocab:
                    m[e] = True
        for i, text in self._candidates:
            s = st
            ok = True
            for ch in text:
                s = advance_char(s, ch)
                if s is None:
                    ok = False
                    break
            if ok:
                m[i] = True
        if len(self._cache) > 512:  # bound the per-engine footprint
            self._cache.clear()
        self._cache[sig] = m
        return m


def build_token_texts(tokenizer, vocab_size: int) -> list[str]:
    """Decoded per-id texts for mask simulation. Ids that decode to ""
    or fail are disallowed (special tokens); multi-byte UTF-8 fragments
    decode to replacement chars, which the PDA treats as string-interior
    characters — the only place they can legally appear."""
    texts: list[str] = []
    for i in range(vocab_size):
        try:
            t = tokenizer.decode([i])
        except Exception:  # noqa: BLE001 — any undecodable id: disallow
            t = ""
        texts.append(t or "")
    return texts


__all__ = [
    "JsonState", "JsonMaskCache", "advance_char", "advance_text",
    "build_token_texts", "COMPLETE",
]
