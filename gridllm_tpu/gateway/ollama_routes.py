"""Ollama-compatible API at ``/ollama`` (and also mounted bare at ``/api``).

Reference analogue: server/src/routes/ollama.ts (714 LoC). Endpoints:
- POST /api/generate  (:161-319) — incl. empty-prompt load/unload semantics
  (:177-214), stream default TRUE (:51), NDJSON streaming
- POST /api/chat      (:322-504) — FIXED vs reference (SURVEY.md §2.8):
  structured messages are carried end-to-end with requestType "chat" instead
  of being flattened into a prompt
- GET  /api/tags      (:507-571) — cross-worker aggregation with
  gridllm_metadata.num_workers_with_model
- POST /api/embed     (:574-643), POST /api/embeddings legacy (:646-711)
Plus endpoints the reference README claims but never implemented
(README.md:149, 207-211; SURVEY.md §2.2): /api/version, /api/ps, /api/show,
and real model management — /api/pull (cluster-wide load-on-demand from
each worker's checkpoint root, with streamed progress), /api/delete,
/api/copy. /api/push stays 501 (no remote registry to push to).

Validation mirrors the Joi schemas (ollama.ts:17-117): prompt ≤ 100 kB,
model required.
"""

from __future__ import annotations

import asyncio
import re
import time
import uuid
from typing import Any

from aiohttp import web

from gridllm_tpu.gateway.convert import (
    start_ndjson,
    to_ollama_chat,
    to_ollama_generate,
    write_ndjson,
)
from gridllm_tpu.gateway.common import (
    guarded_stream,
    prefix_key,
    response_dict,
    submit,
    tenant_of,
)
from gridllm_tpu.gateway.errors import ApiError
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import InferenceRequest, StreamChunk, iso_now

log = get_logger("gateway.ollama")

MAX_PROMPT = 100 * 1024  # Joi max (ollama.ts:19)


def _require_model_name(body: dict) -> str:
    model = body.get("model")
    if not model or not isinstance(model, str):
        raise ApiError("Validation error: \"model\" is required", 400)
    return model


def _require_model(body: dict, registry: WorkerRegistry) -> str:
    model = _require_model_name(body)
    if not registry.get_workers_with_model(model):
        raise ApiError(
            f"Model '{model}' is not available on any worker", 404, "MODEL_NOT_FOUND")
    return model


def _validate_prompt(body: dict) -> str | None:
    prompt = body.get("prompt")
    if prompt is not None:
        if not isinstance(prompt, str):
            raise ApiError("Validation error: \"prompt\" must be a string", 400)
        if len(prompt) > MAX_PROMPT:
            raise ApiError(
                f"Validation error: \"prompt\" length must be less than or equal to "
                f"{MAX_PROMPT} characters long", 400)
    return prompt


_GO_DURATION_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_GO_UNITS = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
             "s": 1.0, "m": 60.0, "h": 3600.0}


def _parse_keep_alive(v: Any) -> float | None:
    """Ollama keep_alive → seconds. Numbers are seconds; strings take Go
    durations incl. compound forms ("1h30m", "500ms"); negative → keep
    forever (None); default 5m when unset or unparseable."""
    if v is None:
        return 300.0
    if isinstance(v, (int, float)):
        return None if v < 0 else float(v)
    s = str(v).strip()
    neg = s.startswith("-")
    if neg:
        s = s[1:]
    parts = _GO_DURATION_RE.findall(s)
    if parts and _GO_DURATION_RE.sub("", s) == "":
        sec = sum(float(n) * _GO_UNITS[u] for n, u in parts)
        return None if neg else sec
    try:
        sec = float(s)
        return None if neg or sec < 0 else sec
    except ValueError:
        return 300.0


def build_routes(registry: WorkerRegistry, scheduler: JobScheduler,
                 version: str, default_timeout_ms: int = 300_000,
                 admin=None) -> list[web.RouteDef]:
    from gridllm_tpu.gateway.admin import get_admin

    routes: list[web.RouteDef] = []
    DEFAULT_TIMEOUT_MS = default_timeout_ms
    madmin = get_admin(registry, admin, default_timeout_ms)
    # keep_alive bookkeeping: /api/ps reports expires_at from the last
    # request's keep_alive; keep_alive=0 + empty prompt REALLY unloads
    # (worker admin broadcast) and the next request for the model
    # auto-loads it back (_require_servable) — full Ollama residency
    # semantics. Workers without management (multi-host slices) decline
    # unloads and stay resident.
    model_expiry = madmin.model_expiry

    def _touch_keep_alive(model: str, keep_alive: Any) -> None:
        madmin.touch_keep_alive(model, _parse_keep_alive(keep_alive))

    async def _require_servable(body: dict) -> str:
        """Ollama load-on-demand (gateway/admin.py): load the model on
        request when no worker serves it; 404 only when none can."""
        model = _require_model_name(body)
        if await madmin.ensure_servable(model):
            return model
        raise ApiError(
            f"Model '{model}' is not available on any worker", 404,
            "MODEL_NOT_FOUND")

    # ---------------- /api/generate ----------------
    async def generate(request: web.Request) -> web.StreamResponse:
        body = await request.json()
        prompt = _validate_prompt(body)
        stream = body.get("stream", True)  # Ollama default (ollama.ts:51)

        # empty prompt → load/unload semantics (ollama.ts:177-214)
        if not prompt or not prompt.strip():
            ka = body.get("keep_alive")
            # NOT isinstance bool: JSON false == 0 in Python, and a client
            # sending keep_alive:false must not nuke the weights
            if ka == 0 and not isinstance(ka, bool):
                # REAL unload (Ollama drops the weights on keep_alive=0);
                # must NOT go through load-on-demand first — unloading an
                # unloaded model is a no-op, not a load. Workers without
                # management (multi-host groups) decline and stay loaded.
                model = _require_model_name(body)
                await _admin_broadcast("unload_model", {"model": model}, 30.0)
                payload: dict[str, Any] = {
                    "model": model, "created_at": iso_now(), "response": "",
                    "done": True, "done_reason": "unload"}
            else:
                # load/warmup semantics: an empty prompt loads the model
                # and its keep_alive sets the residency window
                model = await _require_servable(body)
                _touch_keep_alive(model, body.get("keep_alive"))
                payload = {
                    "model": model, "created_at": iso_now(), "response": "",
                    "done": True}
            if stream:
                resp = await start_ndjson(request)
                await write_ndjson(resp, payload)
                await resp.write_eof()
                return resp
            return web.json_response(payload)

        model = await _require_servable(body)
        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, prompt=prompt, stream=stream,
            options=body.get("options") or {},
            images=body.get("images"),
            timeout=DEFAULT_TIMEOUT_MS,
            metadata={
                "ollamaEndpoint": "/api/generate",
                "requestType": "inference",
                "tenant": tenant_of(request),
                "suffix": body.get("suffix"),
                "think": body.get("think"),
                "format": body.get("format"),
                "system": body.get("system"),
                "template": body.get("template"),
                "raw": body.get("raw"),
                "keep_alive": body.get("keep_alive"),
                "context": body.get("context"),
                # stable prefix identity (system prompt + leading prompt
                # text) for the scheduler's prefix-affinity routing
                "prefixKey": prefix_key(model, body.get("system"),
                                        (prompt or "")[:512]),
                "submittedAt": iso_now(),
            },
        )
        _touch_keep_alive(model, body.get("keep_alive"))
        log.job("ollama generate submitted", req.id, model=model, stream=stream)

        if not stream:
            result = await submit(req, scheduler)
            # keep_alive measures IDLE time: restart the window when the
            # request COMPLETES (the submit-time touch alone would let the
            # sweeper expire a model mid-generation)
            _touch_keep_alive(model, body.get("keep_alive"))
            return web.json_response(
                to_ollama_generate(response_dict(result), model))

        resp = await start_ndjson(request)

        async def on_chunk(chunk: StreamChunk) -> None:
            await write_ndjson(resp, to_ollama_generate(
                chunk.model_dump(exclude_none=True), model))

        async def run() -> None:
            result = await scheduler.submit_streaming_job(req, on_chunk)
            _touch_keep_alive(model, body.get("keep_alive"))  # idle clock
            if result.success:
                await write_ndjson(resp, to_ollama_generate(response_dict(result), model))
            else:
                await on_error(result.error or "Inference failed")

        async def on_error(message: str) -> None:
            await write_ndjson(resp, {
                "model": model, "created_at": iso_now(), "response": "",
                "done": True, "error": message})

        return await guarded_stream(resp, run, on_error)

    # ---------------- /api/chat ----------------
    async def chat(request: web.Request) -> web.StreamResponse:
        body = await request.json()
        model = await _require_servable(body)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise ApiError("Validation error: \"messages\" is required", 400)
        stream = body.get("stream", True)

        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, stream=stream,
            messages=messages,
            tools=body.get("tools"),
            format=body.get("format"),
            options=body.get("options") or {},
            timeout=DEFAULT_TIMEOUT_MS,
            metadata={
                "ollamaEndpoint": "/api/chat",
                "requestType": "chat",   # fix: reference never set this (§2.8)
                "tenant": tenant_of(request),
                "think": body.get("think"),
                "keep_alive": body.get("keep_alive"),
                # system prompt + leading messages identify the reusable
                # conversation prefix (prefix-affinity routing)
                "prefixKey": prefix_key(model, messages[:2]),
                "submittedAt": iso_now(),
            },
        )
        _touch_keep_alive(model, body.get("keep_alive"))
        log.job("ollama chat submitted", req.id, model=model,
                stream=stream, messages=len(messages))

        if not stream:
            result = await submit(req, scheduler)
            _touch_keep_alive(model, body.get("keep_alive"))  # idle clock
            return web.json_response(to_ollama_chat(response_dict(result), model))

        resp = await start_ndjson(request)

        async def on_chunk(chunk: StreamChunk) -> None:
            d = chunk.model_dump(exclude_none=True)
            if "message" not in d:
                d["message"] = {"role": "assistant", "content": d.get("response", "")}
            await write_ndjson(resp, to_ollama_chat(d, model))

        async def run() -> None:
            result = await scheduler.submit_streaming_job(req, on_chunk)
            _touch_keep_alive(model, body.get("keep_alive"))  # idle clock
            if result.success:
                await write_ndjson(resp, to_ollama_chat(response_dict(result), model))
            else:
                await on_error(result.error or "Inference failed")

        async def on_error(message: str) -> None:
            await write_ndjson(resp, {
                "model": model, "created_at": iso_now(),
                "message": {"role": "assistant", "content": ""},
                "done": True, "error": message})

        return await guarded_stream(resp, run, on_error)

    # ---------------- /api/tags ----------------
    async def tags(request: web.Request) -> web.Response:
        models_map: dict[str, dict] = {}
        count: dict[str, int] = {}
        for worker in registry.get_all_workers():
            for m in worker.capabilities.availableModels:
                count[m.name] = count.get(m.name, 0) + 1
                if m.name not in models_map:
                    models_map[m.name] = {
                        "name": m.name,
                        "model": m.model or m.name,
                        "modified_at": m.modified_at or iso_now(),
                        "size": m.size or 0,
                        "digest": m.digest or "",
                        "details": m.details or {
                            "parent_model": "", "format": "safetensors",
                            "family": "unknown", "families": ["unknown"],
                            "parameter_size": "Unknown",
                            "quantization_level": "Unknown",
                        },
                        "gridllm_metadata": {"num_workers_with_model": 0},
                    }
        for name, entry in models_map.items():
            entry["gridllm_metadata"]["num_workers_with_model"] = count[name]
        models = sorted(models_map.values(), key=lambda m: m["name"])
        return web.json_response({"models": models})

    # ---------------- /api/embed (+ legacy /api/embeddings) ----------------
    async def embed(request: web.Request) -> web.Response:
        body = await request.json()
        model = await _require_servable(body)
        input_val = body.get("input")
        if input_val is None or (isinstance(input_val, list) and not input_val):
            raise ApiError("Validation error: \"input\" is required", 400)
        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, input=input_val,
            truncate=body.get("truncate"),
            options=body.get("options") or {},
            timeout=DEFAULT_TIMEOUT_MS,
            metadata={"ollamaEndpoint": "/api/embed",
                      "requestType": "embedding",
                      "tenant": tenant_of(request), "submittedAt": iso_now()},
        )
        result = await submit(req, scheduler)
        d = response_dict(result)
        return web.json_response({
            "model": model,
            "embeddings": d.get("embeddings") or [],
            "total_duration": d.get("total_duration") or 0,
            "load_duration": d.get("load_duration") or 0,
            "prompt_eval_count": d.get("prompt_eval_count") or 0,
        })

    async def embeddings_legacy(request: web.Request) -> web.Response:
        """Single-embedding legacy shape (ollama.ts:646-711)."""
        body = await request.json()
        model = await _require_servable(body)
        prompt = body.get("prompt")
        if prompt is None:
            raise ApiError("Validation error: \"prompt\" is required", 400)
        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, input=prompt,
            options=body.get("options") or {},
            timeout=DEFAULT_TIMEOUT_MS,
            metadata={"ollamaEndpoint": "/api/embeddings",
                      "requestType": "embedding",
                      "tenant": tenant_of(request), "submittedAt": iso_now()},
        )
        result = await submit(req, scheduler)
        d = response_dict(result)
        embeddings = d.get("embeddings") or []
        return web.json_response({
            "embedding": embeddings[0] if embeddings else (d.get("embedding") or [])})

    # ---------------- parity endpoints beyond the reference ----------------
    version_str = version

    async def api_version(request: web.Request) -> web.Response:
        return web.json_response({"version": version_str})

    async def ps(request: web.Request) -> web.Response:
        """Running models across workers (real Ollama /api/ps shape)."""
        seen: dict[str, dict] = {}
        for worker in registry.get_online_workers():
            for m in worker.capabilities.availableModels:
                mkey = madmin.canonical(m.name)
                if mkey in model_expiry:
                    exp = model_expiry[mkey]
                    expires = (
                        "never" if exp is None else
                        time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(exp))
                    )
                else:
                    expires = ""
                entry = seen.setdefault(m.name, {
                    "name": m.name, "model": m.model or m.name,
                    "size": m.size or 0, "digest": m.digest or "",
                    "details": m.details or {},
                    "expires_at": expires,
                    "size_vram": 0,
                    "gridllm_metadata": {"workers": []},
                })
                entry["gridllm_metadata"]["workers"].append(worker.workerId)
        return web.json_response({"models": sorted(seen.values(), key=lambda m: m["name"])})

    async def show(request: web.Request) -> web.Response:
        body = await request.json()
        model = _require_model(body, registry)
        for worker in registry.get_all_workers():
            for m in worker.capabilities.availableModels:
                if m.name == model:
                    details = m.details or {}
                    if details.get("family") == "bert_embed":
                        caps = ["embedding"]  # Ollama's shape for embed-only
                    else:
                        caps = ["completion"]
                    if details.get("vision") or "clip" in (
                        details.get("families") or []
                    ):
                        caps.append("vision")
                    return web.json_response({
                        "modelfile": "", "parameters": "", "template": "",
                        "details": details,
                        "model_info": {"general.name": model,
                                       "general.size": m.size or 0},
                        "capabilities": caps,
                    })
        raise ApiError(f"Model '{model}' not found", 404, "MODEL_NOT_FOUND")

    # ------------- model management (/api/pull, /api/delete, /api/copy) --
    #
    # Cluster semantics: the op broadcasts to every online worker over the
    # bus admin channel (worker/service.py _on_admin); "pull" means
    # load-on-demand from each worker's local checkpoint root (there is no
    # remote registry in this deployment — the reference's pullModel/
    # deleteModel were dead client-side stubs, OllamaService.ts:286-331).

    async def _admin_broadcast(
        op: str, payload: dict, timeout_s: float,
        on_result=None,
    ) -> list[dict]:
        return await madmin.broadcast(op, payload, timeout_s, on_result)

    def _mgmt_model(body: dict) -> str:
        model = body.get("model") or body.get("name")
        if not model or not isinstance(model, str):
            raise ApiError("Validation error: \"model\" is required", 400)
        return model

    async def pull(request: web.Request) -> web.StreamResponse:
        body = await request.json()
        model = _mgmt_model(body)
        stream = body.get("stream", True)
        if not registry.get_online_workers():
            raise ApiError("no workers online", 503, "NO_WORKERS")
        timeout_s = DEFAULT_TIMEOUT_MS / 1000.0
        if stream:
            resp = await start_ndjson(request)
            await write_ndjson(resp, {"status": "pulling manifest"})

            async def progress(rec: dict) -> None:
                await write_ndjson(resp, {
                    "status": f"{rec.get('detail') or 'done'} "
                              f"on {rec.get('workerId')}"
                })

            results = await _admin_broadcast(
                "load_model", {"model": model}, timeout_s, progress)
            ok = any(r.get("ok") for r in results)
            if ok:
                await write_ndjson(resp, {"status": "verifying sha256 digest"})
                await write_ndjson(resp, {"status": "success"})
            else:
                detail = "; ".join(
                    str(r.get("detail")) for r in results) or "no worker replied"
                await write_ndjson(resp, {"error": f"pull failed: {detail}"})
            await resp.write_eof()
            return resp
        results = await _admin_broadcast("load_model", {"model": model}, timeout_s)
        if any(r.get("ok") for r in results):
            return web.json_response({"status": "success"})
        detail = "; ".join(str(r.get("detail")) for r in results) or "no worker replied"
        raise ApiError(f"pull failed: {detail}", 500, "PULL_FAILED")

    async def delete_model(request: web.Request) -> web.Response:
        body = await request.json()
        model = _mgmt_model(body)
        results = await _admin_broadcast("unload_model", {"model": model}, 30.0)
        if any(r.get("ok") for r in results):
            model_expiry.pop(madmin.canonical(model), None)
            return web.json_response({})  # Ollama: 200 empty on success
        raise ApiError(f"Model '{model}' not found", 404, "MODEL_NOT_FOUND")

    async def copy_model(request: web.Request) -> web.Response:
        body = await request.json()
        src, dst = body.get("source"), body.get("destination")
        if not src or not dst:
            raise ApiError(
                "Validation error: \"source\" and \"destination\" are required",
                400)
        results = await _admin_broadcast(
            "copy_model", {"source": src, "destination": dst}, 30.0)
        if any(r.get("ok") for r in results):
            return web.json_response({})
        raise ApiError(f"Model '{src}' not found", 404, "MODEL_NOT_FOUND")

    async def not_supported(request: web.Request) -> web.Response:
        raise ApiError(
            "There is no remote model registry in GridLLM-TPU; "
            f"{request.path} is not supported by the gateway", 501,
            "NOT_SUPPORTED")

    routes.append(web.post("/api/generate", generate))
    routes.append(web.post("/api/chat", chat))
    routes.append(web.get("/api/tags", tags))
    routes.append(web.post("/api/embed", embed))
    routes.append(web.post("/api/embeddings", embeddings_legacy))
    routes.append(web.get("/api/version", api_version))
    routes.append(web.get("/api/ps", ps))
    routes.append(web.post("/api/show", show))
    routes.append(web.post("/api/pull", pull))
    routes.append(web.post("/api/copy", copy_model))
    routes.append(web.delete("/api/delete", delete_model))
    routes.append(web.post("/api/push", not_supported))
    return routes

