"""OpenAI-compatible API at ``/v1``.

Reference analogue: server/src/routes/openai.ts (877 LoC):
- POST /v1/completions (:363-578): SSE streaming with `[DONE]` sentinel
  (:526), echo and stream_options handling (:470-523)
- POST /v1/chat/completions (:581-819): multimodal content→text+images
  (:205-243), OpenAI→Ollama option mapping (:606-642) incl.
  response_format→format (:637-642), requestType "chat" + structured
  messages in metadata (:644-669)
- GET /v1/models (:822-874)

OpenAI-style error envelope: {"error": {"message", "type", "code"}}.
"""

from __future__ import annotations

import time
import uuid
from typing import Any

from aiohttp import web

from gridllm_tpu.gateway.convert import (
    start_sse,
    to_openai_chat,
    to_openai_completion,
    write_sse,
)
from gridllm_tpu.gateway.common import (
    guarded_stream,
    prefix_key,
    response_dict,
    submit,
    tenant_of,
)
from gridllm_tpu.gateway.errors import OpenAIApiError
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import InferenceRequest, StreamChunk, iso_now

log = get_logger("gateway.openai")


def convert_messages(messages: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """OpenAI multimodal content arrays → Ollama text+images messages
    (reference: openai.ts:205-243)."""
    out = []
    for msg in messages:
        content = msg.get("content")
        if isinstance(content, list):
            text_parts: list[str] = []
            images: list[str] = []
            for part in content:
                if part.get("type") == "text":
                    text_parts.append(part.get("text", ""))
                elif part.get("type") == "image_url":
                    url = (part.get("image_url") or {}).get("url", "")
                    # data URLs carry base64 payloads Ollama-style
                    if url.startswith("data:") and "," in url:
                        images.append(url.split(",", 1)[1])
                    else:
                        images.append(url)
            converted: dict[str, Any] = {
                "role": msg.get("role", "user"), "content": "\n".join(text_parts)}
            if images:
                converted["images"] = images
        else:
            converted = {"role": msg.get("role", "user"), "content": content or ""}
        for key in ("name", "tool_calls", "tool_call_id"):
            if key in msg:
                converted[key] = msg[key]
        out.append(converted)
    return out


def map_options(body: dict[str, Any]) -> dict[str, Any]:
    """OpenAI params → engine options (reference: openai.ts:606-642)."""
    opts: dict[str, Any] = {}
    if body.get("temperature", 1) != 1:
        opts["temperature"] = body["temperature"]
    if body.get("top_p", 1) != 1:
        opts["top_p"] = body["top_p"]
    max_tokens = body.get("max_completion_tokens") or body.get("max_tokens")
    if max_tokens is not None:
        opts["num_predict"] = max_tokens
    if body.get("seed") is not None:
        opts["seed"] = body["seed"]
    if body.get("stop") is not None:
        stop = body["stop"]
        opts["stop"] = stop if isinstance(stop, list) else [stop]
    if body.get("frequency_penalty"):
        opts["frequency_penalty"] = body["frequency_penalty"]
    if body.get("presence_penalty"):
        opts["presence_penalty"] = body["presence_penalty"]
    rf = body.get("response_format") or {}
    if rf.get("type") == "json_object":
        opts["format"] = "json"
    elif rf.get("type") == "json_schema":
        opts["format"] = (rf.get("json_schema") or {}).get("schema")
    return opts


def build_routes(registry: WorkerRegistry, scheduler: JobScheduler,
                 default_timeout_ms: int = 300_000,
                 admin=None) -> list[web.RouteDef]:
    from gridllm_tpu.gateway.admin import get_admin

    DEFAULT_TIMEOUT_MS = default_timeout_ms
    madmin = get_admin(registry, admin, default_timeout_ms)

    async def _require_model(body: dict) -> str:
        model = body.get("model")
        if not model or not isinstance(model, str):
            raise OpenAIApiError("you must provide a model parameter", 400,
                                 "invalid_request_error")
        # same load-on-demand residency semantics as the Ollama surface
        # (gateway/admin.py): a cold model gets a cluster load before 404
        if not await madmin.ensure_servable(model):
            raise OpenAIApiError(
                f"The model '{model}' does not exist or is not available",
                404, "invalid_request_error", "model_not_found")
        return model

    async def _submit_touch(req, scheduler_, model, **kw):
        result = await submit(req, scheduler_, **kw)
        _touch(model)
        return result

    def _touch(model: str) -> None:
        # the OpenAI API has no keep_alive knob; Ollama applies its 5m
        # default per request — requests here must restart the idle clock
        # too or the cross-surface keep_alive sweeper would unload a model
        # that only /v1 clients are using
        madmin.touch_keep_alive(model, 300.0)

    # ---------------- /v1/chat/completions ----------------
    async def chat_completions(request: web.Request) -> web.StreamResponse:
        body = await request.json()
        model = await _require_model(body)
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise OpenAIApiError("'messages' is a required property", 400,
                                 "invalid_request_error")
        stream = bool(body.get("stream", False))
        ollama_messages = convert_messages(messages)

        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, stream=stream,
            messages=ollama_messages,
            tools=body.get("tools"),
            options=map_options(body),
            timeout=DEFAULT_TIMEOUT_MS,
            metadata={
                "openaiEndpoint": "/v1/chat/completions",
                "requestType": "chat",
                "tenant": tenant_of(request),
                "ollamaEndpoint": "/api/chat",
                "originalRequest": {
                    "n": body.get("n"), "logprobs": body.get("logprobs"),
                    "tools": body.get("tools"),
                    "tool_choice": body.get("tool_choice"),
                    "user": body.get("user"),
                },
                "prefixKey": prefix_key(model, ollama_messages[:2]),
                "submittedAt": iso_now(),
            },
        )
        log.job("openai chat completions submitted", req.id,
                model=model, stream=stream)

        if not stream:
            result = await _submit_touch(req, scheduler, model,
                                         timeout_code="server_error",
                      failure_code="server_error", error_cls=OpenAIApiError)
            return web.json_response(
                to_openai_chat(response_dict(result), model, req.id))

        resp = await start_sse(request)
        created = int(time.time())
        sent_any = False

        async def on_chunk(chunk: StreamChunk) -> None:
            nonlocal sent_any
            delta_content = (chunk.message or {}).get("content") or chunk.response or ""
            openai_chunk: dict[str, Any] = {
                "id": f"chatcmpl-{req.id}",
                "object": "chat.completion.chunk",
                "created": created,
                "model": model,
                "system_fingerprint": "fp_gridllm_tpu",
                "choices": [{
                    "index": 0,
                    "delta": (
                        {"role": "assistant", "content": delta_content}
                        if not sent_any else {"content": delta_content}),
                    "logprobs": None,
                    "finish_reason": None,
                }],
            }
            sent_any = True
            await write_sse(resp, openai_chunk)

        async def run() -> None:
            result = await scheduler.submit_streaming_job(req, on_chunk)
            _touch(model)
            if not result.success:
                await on_error(result.error or "Inference failed")
                return
            d = response_dict(result)
            final_chunk: dict[str, Any] = {
                "id": f"chatcmpl-{req.id}",
                "object": "chat.completion.chunk",
                "created": created,
                "model": model,
                "system_fingerprint": "fp_gridllm_tpu",
                "choices": [{"index": 0, "delta": {}, "logprobs": None,
                             "finish_reason": _chunk_finish_reason(d)}],
            }
            if (body.get("stream_options") or {}).get("include_usage"):
                p = d.get("prompt_eval_count") or 0
                c = d.get("eval_count") or 0
                final_chunk["usage"] = {
                    "prompt_tokens": p, "completion_tokens": c, "total_tokens": p + c}
            await write_sse(resp, final_chunk)
            await write_sse(resp, "[DONE]")

        async def on_error(message: str) -> None:
            await write_sse(resp, {"error": {"message": message,
                                             "type": "server_error"}})
            await write_sse(resp, "[DONE]")

        return await guarded_stream(resp, run, on_error)

    # ---------------- /v1/completions ----------------
    async def completions(request: web.Request) -> web.StreamResponse:
        body = await request.json()
        model = await _require_model(body)
        prompt = body.get("prompt")
        if isinstance(prompt, list):
            prompt = "".join(str(p) for p in prompt)
        if not isinstance(prompt, str) or not prompt:
            raise OpenAIApiError("'prompt' is a required property", 400,
                                 "invalid_request_error")
        stream = bool(body.get("stream", False))
        echo = bool(body.get("echo", False))

        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, prompt=prompt, stream=stream,
            options=map_options(body),
            timeout=DEFAULT_TIMEOUT_MS,
            metadata={
                "openaiEndpoint": "/v1/completions",
                "requestType": "inference",
                "tenant": tenant_of(request),
                "ollamaEndpoint": "/api/generate",
                "prefixKey": prefix_key(model, prompt[:512]),
                "submittedAt": iso_now(),
            },
        )
        log.job("openai completions submitted", req.id, model=model, stream=stream)

        if not stream:
            result = await _submit_touch(req, scheduler, model,
                                         timeout_code="server_error",
                      failure_code="server_error", error_cls=OpenAIApiError)
            return web.json_response(to_openai_completion(
                response_dict(result), model, req.id, prompt, echo))

        resp = await start_sse(request)
        created = int(time.time())
        first = True

        async def on_chunk(chunk: StreamChunk) -> None:
            nonlocal first
            text = chunk.response or ""
            if first and echo:
                text = prompt + text
            first = False
            await write_sse(resp, {
                "id": f"cmpl-{req.id}", "object": "text_completion",
                "created": created, "model": model,
                "system_fingerprint": "fp_gridllm_tpu",
                "choices": [{"text": text, "index": 0, "logprobs": None,
                             "finish_reason": None}],
            })

        async def run() -> None:
            result = await scheduler.submit_streaming_job(req, on_chunk)
            _touch(model)
            if not result.success:
                await on_error(result.error or "Inference failed")
                return
            d = response_dict(result)
            final: dict[str, Any] = {
                "id": f"cmpl-{req.id}", "object": "text_completion",
                "created": created, "model": model,
                "system_fingerprint": "fp_gridllm_tpu",
                "choices": [{"text": "", "index": 0, "logprobs": None,
                             "finish_reason": _chunk_finish_reason(d)}],
            }
            if (body.get("stream_options") or {}).get("include_usage"):
                p = d.get("prompt_eval_count") or 0
                c = d.get("eval_count") or 0
                final["usage"] = {
                    "prompt_tokens": p, "completion_tokens": c, "total_tokens": p + c}
            await write_sse(resp, final)
            await write_sse(resp, "[DONE]")

        async def on_error(message: str) -> None:
            await write_sse(resp, {"error": {"message": message,
                                             "type": "server_error"}})
            await write_sse(resp, "[DONE]")

        return await guarded_stream(resp, run, on_error)

    # ---------------- /v1/models ----------------
    async def models(request: web.Request) -> web.Response:
        models_map: dict[str, dict] = {}
        for worker in registry.get_all_workers():
            for m in worker.capabilities.availableModels:
                if m.name not in models_map:
                    # exactly Ollama's facade field set {id, object,
                    # created, owned_by} — extra legacy-OpenAI keys
                    # (permission/root/parent) break shape parity
                    models_map[m.name] = {
                        "id": m.name,
                        "object": "model",
                        "created": int(time.time()),
                        "owned_by": "gridllm",
                    }
        data = sorted(models_map.values(), key=lambda m: m["id"])
        return web.json_response({"object": "list", "data": data})

    return [
        web.post("/v1/chat/completions", chat_completions),
        web.post("/v1/completions", completions),
        web.get("/v1/models", models),
    ]


def _chunk_finish_reason(d: dict[str, Any]) -> str:
    if d.get("done_reason") == "length":
        return "length"
    if (d.get("message") or {}).get("tool_calls"):
        return "tool_calls"
    return "stop"
