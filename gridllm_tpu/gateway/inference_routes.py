"""Native inference API at ``/inference``.

Reference analogue: server/src/routes/inference.ts (289 LoC):
- POST /inference            (:35-125)  validate + submit_and_wait
- GET  /inference/models     (:195-250) per-model worker counts
- GET  /inference/queue      (:253-286) queue stats
- GET  /inference/{id}/status (:128-167) queued position / processing
- DELETE /inference/{id}     (:170-192) cancel
"""

from __future__ import annotations

import uuid

from aiohttp import web

from gridllm_tpu.gateway.common import prefix_key, tenant_of
from gridllm_tpu.gateway.common import submit as submit_job
from gridllm_tpu.gateway.errors import ApiError
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.types import InferenceRequest, Priority, iso_now


def build_routes(registry: WorkerRegistry, scheduler: JobScheduler) -> list[web.RouteDef]:

    async def submit(request: web.Request) -> web.Response:
        body = await request.json()
        model = body.get("model")
        prompt = body.get("prompt")
        if not model:
            raise ApiError("Validation error: \"model\" is required", 400)
        if not prompt:
            raise ApiError("Validation error: \"prompt\" is required", 400)
        if not registry.get_workers_with_model(model):
            raise ApiError(f"Model '{model}' is not available on any worker",
                           404, "MODEL_NOT_FOUND")
        priority = body.get("priority", "medium")
        if priority not in ("high", "medium", "low"):
            raise ApiError("Validation error: \"priority\" must be one of "
                           "[high, medium, low]", 400)
        req = InferenceRequest(
            id=str(uuid.uuid4()), model=model, prompt=prompt,
            stream=False,
            options=body.get("options") or {},
            priority=Priority(priority),
            timeout=body.get("timeout") or 300_000,
            metadata={"endpoint": "/inference", "requestType": "inference",
                      "tenant": tenant_of(request),
                      "prefixKey": prefix_key(model, str(prompt)[:512]),
                      "submittedAt": iso_now()},
        )
        result = await submit_job(req, scheduler)
        d = result.response.model_dump(exclude_none=True) if result.response else {}
        return web.json_response({
            "id": req.id,
            "model": model,
            "response": d.get("response", ""),
            "done": True,
            "processingTimeMs": result.processingTimeMs,
            "worker": result.workerId,
            **{k: d[k] for k in ("total_duration", "eval_count", "eval_duration",
                                 "prompt_eval_count") if k in d},
        })

    async def status(request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        position = scheduler.get_queue_position(job_id)
        if position is not None:
            return web.json_response({
                "id": job_id, "status": "queued", "queuePosition": position + 1,
                "queueLength": scheduler.get_stats()["queuedJobs"]})
        for assignment in scheduler.get_active_jobs():
            if assignment.jobId == job_id:
                return web.json_response({
                    "id": job_id, "status": "processing",
                    "workerId": assignment.workerId,
                    "assignedAt": assignment.assignedAt})
        raise ApiError(f"Job '{job_id}' not found", 404, "JOB_NOT_FOUND")

    async def cancel(request: web.Request) -> web.Response:
        job_id = request.match_info["job_id"]
        if await scheduler.cancel_job(job_id):
            return web.json_response({"id": job_id, "status": "cancelled"})
        raise ApiError(f"Job '{job_id}' not found", 404, "JOB_NOT_FOUND")

    async def models(request: web.Request) -> web.Response:
        out = []
        for m in registry.get_all_available_models():
            name = m.get("name")
            out.append({
                "name": name,
                "workersAvailable": len(registry.get_available_workers_by_model(name)),
                "workersTotal": len(registry.get_workers_with_model(name)),
            })
        return web.json_response({"models": sorted(out, key=lambda x: x["name"])})

    async def queue(request: web.Request) -> web.Response:
        stats = scheduler.get_stats()
        counts = registry.get_worker_count()
        return web.json_response({
            "queue": {
                "length": stats["queuedJobs"],
                "activeJobs": stats["activeJobs"],
                "totalProcessed": stats["totalJobsProcessed"],
                "totalFailed": stats["totalJobsFailed"],
                "totalTimedOut": stats["totalJobsTimedOut"],
                "totalCancelled": stats["totalJobsCancelled"],
                "totalRetried": stats["totalJobsRetried"],
                "totalOrphaned": stats["totalJobsOrphaned"],
            },
            "workers": counts,
        })

    return [
        web.post("/inference", submit),
        web.get("/inference/models", models),
        web.get("/inference/queue", queue),
        web.get("/inference/{job_id}/status", status),
        web.delete("/inference/{job_id}", cancel),
    ]
