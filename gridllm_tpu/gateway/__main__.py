"""``python -m gridllm_tpu.gateway`` — same as the ``gridllm-server``
console script, for PYTHONPATH-only (uninstalled) deployments."""

from gridllm_tpu.gateway.app import main

main()
