"""Observability endpoints + HTTP metrics middleware (ISSUE 1 + 2).

- ``GET /metrics``: Prometheus text exposition. Renders the scheduler's
  per-instance registry (gateway/scheduler/worker-liveness/SLO series) plus
  the process-global default registry (bus, and — in single-process
  deployments like bench.py — engine/kernel series).
- ``GET /admin/trace/{request_id}``: the stitched gateway+worker span
  timeline recorded by obs/tracer.py.
- ``GET /admin/slo``: per-class SLO attainment, burn rates, and goodput
  from obs/slo.py — the same state the ``gridllm_slo_*`` gauges render.
- ``GET /admin/dump``: the flight-recorder post-mortem artifact
  (obs/flightrec.py): event rings, active traces, SLO snapshot, registry
  and engine state, plus any retained auto dumps from hang/crash detection.
- ``metrics_middleware``: request count by route/method/status and
  end-to-end latency histogram by route. Route labels use the matched
  route's canonical pattern (``/inference/{job_id}/status``), never the raw
  path, so label cardinality stays bounded. Server-fault responses (5xx)
  also land in the gateway flight-recorder ring.
"""

from __future__ import annotations

import asyncio
import time

from aiohttp import web

from gridllm_tpu.obs import (
    PROMETHEUS_CONTENT_TYPE,
    build_dump,
    default_flight_recorder,
    default_registry,
    render_registries,
)
from gridllm_tpu.scheduler import JobScheduler


def metrics_middleware(scheduler: JobScheduler):
    requests_total = scheduler.metrics.counter(
        "gridllm_gateway_requests_total",
        "HTTP requests handled by the gateway, by route/method/status.",
        ("route", "method", "status"),
    )
    duration = scheduler.metrics.histogram(
        "gridllm_gateway_request_duration_seconds",
        "End-to-end HTTP request latency (including streaming bodies), "
        "by route.",
        ("route",),
    )

    def route_of(request: web.Request) -> str:
        info = request.match_info
        resource = info.route.resource if info.route is not None else None
        canonical = getattr(resource, "canonical", None)
        return canonical or "unmatched"

    @web.middleware
    async def middleware(request: web.Request, handler):
        if request.path == "/metrics":
            return await handler(request)  # don't count scrapes
        t0 = time.monotonic()
        status = 500
        try:
            response = await handler(request)
            status = response.status
            return response
        except web.HTTPException as e:
            status = e.status
            raise
        except asyncio.CancelledError:
            # client closed the connection mid-stream — not a server fault;
            # 499 per the nginx convention so disconnects don't pollute the
            # 5xx error rate
            status = 499
            raise
        finally:
            route = route_of(request)
            requests_total.inc(route=route, method=request.method,
                               status=str(status))
            duration.observe(time.monotonic() - t0, route=route)
            if status >= 500:  # server faults only — the ring is for
                default_flight_recorder().record(  # post-mortems, not access logs
                    "gateway", "server_error", route=route,
                    method=request.method, status=status)

    return middleware


def build_routes(scheduler: JobScheduler) -> list[web.RouteDef]:

    async def metrics(request: web.Request) -> web.Response:
        text = render_registries(scheduler.metrics, default_registry())
        return web.Response(text=text,
                            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE})

    async def trace(request: web.Request) -> web.Response:
        request_id = request.match_info["request_id"]
        spans = scheduler.tracer.export(request_id)
        if spans is None:
            from gridllm_tpu.gateway.errors import ApiError

            raise ApiError(f"No trace recorded for request '{request_id}'",
                           404, "TRACE_NOT_FOUND")
        return web.json_response({
            "requestId": request_id,
            "spans": spans,
            "sources": sorted({s["source"] for s in spans}),
        })

    async def slo(request: web.Request) -> web.Response:
        return web.json_response(scheduler.slo.snapshot())

    async def dump(request: web.Request) -> web.Response:
        return web.json_response(build_dump(scheduler, reason="on_demand"))

    return [
        web.get("/metrics", metrics),
        web.get("/admin/trace/{request_id}", trace),
        web.get("/admin/slo", slo),
        web.get("/admin/dump", dump),
    ]
