"""Observability endpoints + HTTP metrics middleware (ISSUE 1 + 2).

- ``GET /metrics``: Prometheus text exposition. Renders the scheduler's
  per-instance registry (gateway/scheduler/worker-liveness/SLO series) plus
  the process-global default registry (bus, and — in single-process
  deployments like bench.py — engine/kernel series).
- ``GET /admin/trace/{request_id}``: the stitched gateway+worker span
  timeline recorded by obs/tracer.py.
- ``GET /admin/slo``: per-class SLO attainment, burn rates, and goodput
  from obs/slo.py — the same state the ``gridllm_slo_*`` gauges render.
- ``GET /admin/capacity``: per-model demand/utilization/headroom and the
  derived scale hint from obs/capacity.py (plus the per-tenant usage
  ledger), fleet-merged across shards on scaled control planes — the
  same state the ``gridllm_capacity_*`` gauges render.
- ``GET /admin/dump``: the flight-recorder post-mortem artifact
  (obs/flightrec.py): event rings, active traces, SLO snapshot, registry
  and engine state, plus any retained auto dumps from hang/crash detection.
- ``GET /admin/memory``: per-device weights/KV/workspace breakdown with
  headroom + fragmentation (obs/perf.py). Covers THIS process's devices:
  in single-process stacks (bench, tests) that includes the engines; in a
  split deployment the worker health port serves the engine-side view.
- ``POST /admin/profile?seconds=N``: start an on-demand jax.profiler
  capture into the bounded artifact dir; returns the path immediately.
  409 while a capture is already running.
- ``metrics_middleware``: request count by route/method/status and
  end-to-end latency histogram by route. Route labels use the matched
  route's canonical pattern (``/inference/{job_id}/status``), never the raw
  path, so label cardinality stays bounded. Server-fault responses (5xx)
  also land in the gateway flight-recorder ring.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid

from aiohttp import web

from gridllm_tpu.bus.base import CH_OBS_DUMP, obs_dump_reply_channel
from gridllm_tpu.obs import (
    PROMETHEUS_CONTENT_TYPE,
    build_dump,
    default_flight_recorder,
    default_registry,
    render_registries,
    stamp_key,
    timeline_emitter,
)
from gridllm_tpu.scheduler import JobScheduler

# how long /admin/dump?fleet=1 waits for member replies before reporting
# the silent ones as missing (never silently merged, never hung)
FLEET_DUMP_TIMEOUT_S = 2.0


def metrics_middleware(scheduler: JobScheduler):
    requests_total = scheduler.metrics.counter(
        "gridllm_gateway_requests_total",
        "HTTP requests handled by the gateway, by route/method/status.",
        ("route", "method", "status"),
    )
    duration = scheduler.metrics.histogram(
        "gridllm_gateway_request_duration_seconds",
        "End-to-end HTTP request latency (including streaming bodies), "
        "by route.",
        ("route",),
    )

    def route_of(request: web.Request) -> str:
        info = request.match_info
        resource = info.route.resource if info.route is not None else None
        canonical = getattr(resource, "canonical", None)
        return canonical or "unmatched"

    @web.middleware
    async def middleware(request: web.Request, handler):
        if request.path == "/metrics":
            return await handler(request)  # don't count scrapes
        t0 = time.monotonic()
        status = 500
        try:
            response = await handler(request)
            status = response.status
            return response
        except web.HTTPException as e:
            status = e.status
            raise
        except asyncio.CancelledError:
            # client closed the connection mid-stream — not a server fault;
            # 499 per the nginx convention so disconnects don't pollute the
            # 5xx error rate
            status = 499
            raise
        finally:
            route = route_of(request)
            requests_total.inc(route=route, method=request.method,
                               status=str(status))
            duration.observe(time.monotonic() - t0, route=route)
            if status >= 500:  # server faults only — the ring is for
                default_flight_recorder().record(  # post-mortems, not access logs
                    "gateway", "server_error", route=route,
                    method=request.method, status=status)

    return middleware


def build_routes(scheduler: JobScheduler,
                 fleet=None, timeline=None,
                 incidents=None) -> list[web.RouteDef]:
    """``fleet`` (controlplane/status.py FleetView, ISSUE 15) is present
    on scaled-control-plane gateway replicas: /admin/slo and /admin/dump
    then attach the fleet-wide aggregation — keyed by member/shard
    identity, never silently summed — so any replica answers for the
    whole control plane. /metrics serves the same view through the
    FleetView's collector gauges (gridllm_shard_*).

    ``timeline`` / ``incidents`` (obs/timeline.py TimelineStore +
    obs/forensics.py IncidentCollector, ISSUE 17) arm the
    /admin/timeline/{request_id} and /admin/incidents forensic surfaces;
    None (timeline disabled) serves 503 so a disarmed member is
    distinguishable from an empty timeline."""

    async def _flush_local_timeline() -> None:
        # serving a forensic read flushes THIS process's pending events
        # first, so single-process fleets (tests, bench) read their own
        # just-emitted history without waiting a flush interval
        pub = timeline_emitter()
        if pub is not None:
            for _ in range(8):
                if await pub.flush_once() == 0:
                    break
        drain = getattr(scheduler.bus, "flush", None)
        if drain is not None:
            try:
                await drain()
            except Exception:  # noqa: BLE001 — reads stay best-effort
                pass

    async def timeline_slice(request: web.Request) -> web.Response:
        if timeline is None:
            raise web.HTTPServiceUnavailable(
                text="timeline disabled (GRIDLLM_TIMELINE=0)")
        request_id = request.match_info["request_id"]
        await _flush_local_timeline()
        events = timeline.slice(request_id)
        spans = scheduler.tracer.export(request_id) or []
        if not events and not spans:
            from gridllm_tpu.gateway.errors import ApiError

            raise ApiError(
                f"No timeline recorded for request '{request_id}'",
                404, "TIMELINE_NOT_FOUND")
        return web.json_response({
            "requestId": request_id,
            "events": events,  # HLC (causal) order, fleet-stitched
            "spans": spans,    # tracer wall-clock intervals, merged in
            "members": sorted({str(ev.get("member") or "?")
                               for ev in events}),
        })

    async def timeline_window(request: web.Request) -> web.Response:
        if timeline is None:
            raise web.HTTPServiceUnavailable(
                text="timeline disabled (GRIDLLM_TIMELINE=0)")
        await _flush_local_timeline()
        events = sorted(timeline.events(), key=stamp_key)
        try:
            limit = int(request.query.get("limit", "256"))
        except ValueError:
            limit = 256
        if limit > 0:
            events = events[-limit:]
        return web.json_response({
            "events": events,  # HLC (causal) order, fleet-merged
            "members": sorted({str(ev.get("member") or "?")
                               for ev in events}),
        })

    async def incident_reports(request: web.Request) -> web.Response:
        if incidents is None:
            raise web.HTTPServiceUnavailable(
                text="timeline disabled (GRIDLLM_TIMELINE=0)")
        await _flush_local_timeline()
        return web.json_response({
            "member": scheduler.identity(),
            "incidents": incidents.reports(),
        })

    async def _collect_fleet_dumps() -> dict:
        """Broadcast a dump op and gather per-member replies through the
        bus (every StatusPublisher answers); silent members are listed
        as missing rather than merged away."""
        op_id = uuid.uuid4().hex[:12]
        expected = set(fleet.members())
        replies: dict[str, object] = {}
        done = asyncio.Event()

        async def on_reply(_ch: str, raw: str) -> None:
            try:
                data = json.loads(raw)
                member = str(data["member"])
            except Exception:
                return
            replies[member] = data.get("dump")
            if expected <= set(replies):
                done.set()

        sub = await scheduler.bus.subscribe(
            obs_dump_reply_channel(op_id), on_reply)
        try:
            await scheduler.bus.publish(CH_OBS_DUMP, json.dumps({
                "opId": op_id, "requester": scheduler.identity().get(
                    "member")}))
            try:
                await asyncio.wait_for(done.wait(), FLEET_DUMP_TIMEOUT_S)
            except asyncio.TimeoutError:
                pass
        finally:
            await sub.unsubscribe()
        return {
            "requested": sorted(expected),
            "missing": sorted(expected - set(replies)),
            "members": replies,
        }

    async def metrics(request: web.Request) -> web.Response:
        text = render_registries(scheduler.metrics, default_registry())
        return web.Response(text=text,
                            headers={"Content-Type": PROMETHEUS_CONTENT_TYPE})

    async def trace(request: web.Request) -> web.Response:
        request_id = request.match_info["request_id"]
        spans = scheduler.tracer.export(request_id)
        if spans is None:
            from gridllm_tpu.gateway.errors import ApiError

            raise ApiError(f"No trace recorded for request '{request_id}'",
                           404, "TRACE_NOT_FOUND")
        return web.json_response({
            "requestId": request_id,
            "spans": spans,
            "sources": sorted({s["source"] for s in spans}),
        })

    async def slo(request: web.Request) -> web.Response:
        snap = scheduler.slo.snapshot()
        # shard identity label (ISSUE 15 satellite): the snapshot always
        # says WHOSE judgments these are, so sharded deployments cannot
        # silently aggregate per-member numbers into one unlabeled view
        snap["shard"] = scheduler.identity()
        if fleet is not None:
            snap["fleet"] = fleet.merged_slo()
        return web.json_response(snap)

    async def capacity(request: web.Request) -> web.Response:
        # fleet capacity & demand (ISSUE 16): this member's per-model
        # snapshot plus — on scaled control planes — the cross-shard
        # merge, so any replica serves the same fleet-wide view the
        # future autoscaler consumes
        snap = scheduler.capacity.snapshot()
        snap["shard"] = scheduler.identity()
        snap["usage"] = scheduler.usage.snapshot()
        if fleet is not None:
            snap["fleet"] = fleet.merged_capacity()
        return web.json_response(snap)

    async def health_fleet(request: web.Request) -> web.Response:
        # active fleet health (ISSUE 19): this member's worker health
        # verdicts + canary prober summary, plus — on scaled control
        # planes — every member's view keyed by identity, so any replica
        # answers "which workers are degraded/quarantined and why"
        snap = {
            "shard": scheduler.identity(),
            "health": (scheduler.health.snapshot()
                       if getattr(scheduler, "health", None) is not None
                       else None),
            "canary": (scheduler.prober.summary()
                       if getattr(scheduler, "prober", None) is not None
                       else None),
        }
        if fleet is not None:
            snap["fleet"] = fleet.merged_health()
        return web.json_response(snap)

    async def dump(request: web.Request) -> web.Response:
        artifact = build_dump(scheduler, reason="on_demand")
        if fleet is not None:
            artifact["controlPlane"] = {
                "member": scheduler.identity(),
                "members": fleet.members(),
                "stats": fleet.merged_stats(),
            }
            if request.query.get("fleet"):
                # fleet-merged dump (ISSUE 17): every live member's own
                # artifact, keyed by member identity — one call captures
                # the whole control plane post-incident
                artifact["fleet"] = await _collect_fleet_dumps()
        return web.json_response(artifact)

    async def memory(request: web.Request) -> web.Response:
        from gridllm_tpu.obs import memory_snapshot

        # to_thread: the live_arrays walk is synchronous work that grows
        # with the number of live buffers
        return web.json_response(await asyncio.to_thread(memory_snapshot))

    async def profile(request: web.Request) -> web.Response:
        return await start_profile_capture(request)

    return [
        web.get("/metrics", metrics),
        web.get("/admin/trace/{request_id}", trace),
        web.get("/admin/timeline", timeline_window),
        web.get("/admin/timeline/{request_id}", timeline_slice),
        web.get("/admin/incidents", incident_reports),
        web.get("/admin/slo", slo),
        web.get("/admin/capacity", capacity),
        web.get("/admin/health/fleet", health_fleet),
        web.get("/admin/dump", dump),
        web.get("/admin/memory", memory),
        web.post("/admin/profile", profile),
    ]


async def start_profile_capture(request: web.Request) -> web.Response:
    """``POST /admin/profile?seconds=N``: start a background jax.profiler
    capture; the response carries the artifact path so the caller can
    fetch/open it after `seconds`. Validation, the busy conflict, and
    the engine-less-process refusal live in obs/perf.py — the worker
    health port serves the same helper without importing gateway code."""
    from gridllm_tpu.obs.perf import handle_profile_request

    # to_thread: starting a capture prunes old artifact dirs and calls
    # start_trace — blocking filesystem/profiler work that must not
    # stall the event loop serving streams and health checks
    status, payload = await asyncio.to_thread(
        handle_profile_request, request.query.get("seconds"))
    return web.json_response(payload, status=status)
