"""Per-IP fixed-window rate limiter.

Reference analogue: client/src/middleware/rateLimiter.ts (130 LoC) — built
but never mounted (SURVEY.md §2.4); here it is actually applied, with the
same semantics: fixed window, X-RateLimit-* headers, health-path bypass,
429 with Retry-After on exceed. Config keys match the reference's env
(RATE_LIMIT_WINDOW_MS / RATE_LIMIT_MAX_REQUESTS).
"""

from __future__ import annotations

import time

from aiohttp import web

from gridllm_tpu.utils.config import GatewayConfig

# /metrics joins the health bypass: a Prometheus scrape cadence (every
# 10-15 s) would otherwise eat the client budget of whatever shares the
# scraper's IP (and throttling a scrape blinds the dashboard exactly when
# traffic spikes)
_BYPASS_PREFIXES = ("/health", "/live", "/ready", "/metrics")


def rate_limit_middleware(config: GatewayConfig):
    window_s = config.rate_limit_window_ms / 1000
    limit = config.rate_limit_max_requests
    buckets: dict[str, tuple[float, int]] = {}  # ip → (window start, count)

    @web.middleware
    async def middleware(request: web.Request, handler):
        if not config.rate_limit_enabled or request.path.startswith(_BYPASS_PREFIXES):
            return await handler(request)
        ip = request.remote or "unknown"
        now = time.monotonic()
        start, count = buckets.get(ip, (now, 0))
        if now - start >= window_s:
            start, count = now, 0
        count += 1
        buckets[ip] = (start, count)
        if len(buckets) > 10_000:  # bound memory under IP churn
            cutoff = now - window_s
            for k in [k for k, (s, _) in buckets.items() if s < cutoff]:
                del buckets[k]
        remaining = max(0, limit - count)
        reset_s = int(start + window_s - now) + 1
        if count > limit:
            return web.json_response(
                {"error": {"message": "Too many requests", "code": "RATE_LIMITED"}},
                status=429,
                headers={
                    "Retry-After": str(reset_s),
                    "X-RateLimit-Limit": str(limit),
                    "X-RateLimit-Remaining": "0",
                    "X-RateLimit-Reset": str(reset_s),
                })
        response = await handler(request)
        response.headers["X-RateLimit-Limit"] = str(limit)
        response.headers["X-RateLimit-Remaining"] = str(remaining)
        response.headers["X-RateLimit-Reset"] = str(reset_s)
        return response

    return middleware
