"""Per-IP fixed-window rate limiter.

Reference analogue: client/src/middleware/rateLimiter.ts (130 LoC) — built
but never mounted (SURVEY.md §2.4); here it is actually applied, with the
same semantics: fixed window, X-RateLimit-* headers, health-path bypass,
429 with Retry-After on exceed. Config keys match the reference's env
(RATE_LIMIT_WINDOW_MS / RATE_LIMIT_MAX_REQUESTS).

Multi-replica deployments (ISSUE 15): bucket state was per-process, so N
gateway replicas silently multiplied every limit by N. The scope is now
explicit (``GRIDLLM_RATELIMIT_SCOPE``):

- ``replica`` (default): the original per-process buckets. The limit is
  PER REPLICA by documented contract — size it as limit/N, or use it
  deliberately when replicas sit behind per-replica DNS.
- ``fleet``: bucket state lives in the shared bus (one TTL'd KV record
  per client IP, read-modify-write per counted request), so the limit
  holds fleet-wide regardless of which replica serves the request.
  Concurrent replicas may momentarily lose an increment to the
  read-modify-write race — the limiter is a throttle, not a ledger —
  and a bus failure degrades to the local bucket rather than letting
  traffic through uncounted.

Either scope counts throttled requests in
``gridllm_ratelimit_rejections_total{scope}``.
"""

from __future__ import annotations

import json
import time

from aiohttp import web

from gridllm_tpu.bus.base import MessageBus
from gridllm_tpu.obs import MetricsRegistry
from gridllm_tpu.utils.config import GatewayConfig

# /metrics joins the health bypass: a Prometheus scrape cadence (every
# 10-15 s) would otherwise eat the client budget of whatever shares the
# scraper's IP (and throttling a scrape blinds the dashboard exactly when
# traffic spikes)
_BYPASS_PREFIXES = ("/health", "/live", "/ready", "/metrics")


def _ratelimit_key(ip: str) -> str:
    """Bus KV key holding one client's fleet-scope window record."""
    return f"ratelimit:{ip}"


def rate_limit_middleware(config: GatewayConfig,
                          bus: MessageBus | None = None,
                          metrics: MetricsRegistry | None = None):
    window_s = config.rate_limit_window_ms / 1000
    limit = config.rate_limit_max_requests
    scope = config.rate_limit_scope if bus is not None else "replica"
    buckets: dict[str, tuple[float, int]] = {}  # ip → (window start, count)
    rejections = None
    if metrics is not None:
        rejections = metrics.counter(
            "gridllm_ratelimit_rejections_total",
            "Requests throttled with HTTP 429, by bucket scope (replica "
            "= per-process buckets, so N gateway replicas multiply the "
            "configured limit by N; fleet = bus-shared buckets).",
            ("scope",))

    def local_count(ip: str, now: float) -> tuple[int, float]:
        """(count after this request, window start) from the per-process
        buckets — the replica scope, and the fleet scope's degraded path."""
        start, count = buckets.get(ip, (now, 0))
        if now - start >= window_s:
            start, count = now, 0
        count += 1
        buckets[ip] = (start, count)
        if len(buckets) > 10_000:  # bound memory under IP churn
            cutoff = now - window_s
            for k in [k for k, (s, _) in buckets.items() if s < cutoff]:
                del buckets[k]
        return count, start

    async def fleet_count(ip: str, now: float) -> tuple[int, float]:
        """Bus-shared window record: read-modify-write with the window
        TTL, so abandoned client records expire on their own."""
        key = _ratelimit_key(ip)
        raw = await bus.get(key)
        start, count = now, 0
        if raw:
            try:
                rec = json.loads(raw)
                start = float(rec.get("start", now))
                count = int(rec.get("count", 0))
            except (TypeError, ValueError):
                start, count = now, 0
        if now - start >= window_s:
            start, count = now, 0
        count += 1
        await bus.set_with_expiry(
            key, json.dumps({"start": start, "count": count}), window_s)
        return count, start

    @web.middleware
    async def middleware(request: web.Request, handler):
        if not config.rate_limit_enabled or request.path.startswith(_BYPASS_PREFIXES):
            return await handler(request)
        ip = request.remote or "unknown"
        # wall clock, not monotonic: fleet-scope window starts are shared
        # across processes, and monotonic clocks don't agree between them
        now = time.time()
        if scope == "fleet":
            try:
                count, start = await fleet_count(ip, now)
            except Exception:  # noqa: BLE001 — degraded bus: local bucket
                count, start = local_count(ip, now)
        else:
            count, start = local_count(ip, now)
        remaining = max(0, limit - count)
        reset_s = int(start + window_s - now) + 1
        if count > limit:
            if rejections is not None:
                rejections.inc(scope=scope)
            return web.json_response(
                {"error": {"message": "Too many requests", "code": "RATE_LIMITED"}},
                status=429,
                headers={
                    "Retry-After": str(reset_s),
                    "X-RateLimit-Limit": str(limit),
                    "X-RateLimit-Remaining": "0",
                    "X-RateLimit-Reset": str(reset_s),
                })
        response = await handler(request)
        response.headers["X-RateLimit-Limit"] = str(limit)
        response.headers["X-RateLimit-Remaining"] = str(remaining)
        response.headers["X-RateLimit-Reset"] = str(reset_s)
        return response

    return middleware
