"""Response conversion + streaming framing helpers.

Reference analogues:
- convertToOllamaResponse (server/src/routes/ollama.ts:137-158): zero-filled
  timing fields, `thinking` only when present
- convertOllamaChatToOpenAI / convertToOpenAICompletionsResponse
  (server/src/routes/openai.ts:246-355): usage from prompt_eval_count /
  eval_count, finish_reason mapping, optional system_fingerprint passthrough
- NDJSON framing (ollama.ts:131-134) and SSE framing (openai.ts:357-360)
"""

from __future__ import annotations

import json
import time
from typing import Any

from aiohttp import web

from gridllm_tpu.utils.types import iso_now


# -- Ollama ----------------------------------------------------------------

def to_ollama_generate(response: dict[str, Any], model: str) -> dict[str, Any]:
    out = {
        "model": model,
        "created_at": response.get("created_at") or iso_now(),
        "response": response.get("response") or "",
        "done": response.get("done") or False,
        "context": response.get("context") or [],
        "total_duration": response.get("total_duration") or 0,
        "load_duration": response.get("load_duration") or 0,
        "prompt_eval_count": response.get("prompt_eval_count") or 0,
        "prompt_eval_duration": response.get("prompt_eval_duration") or 0,
        "eval_count": response.get("eval_count") or 0,
        "eval_duration": response.get("eval_duration") or 0,
    }
    if response.get("done_reason"):
        out["done_reason"] = response["done_reason"]
    if response.get("thinking"):
        out["thinking"] = response["thinking"]
    return out


def to_ollama_chat(response: dict[str, Any], model: str) -> dict[str, Any]:
    message = response.get("message") or {
        "role": "assistant", "content": response.get("response") or ""}
    out = {
        "model": model,
        "created_at": response.get("created_at") or iso_now(),
        "message": message,
        "done": response.get("done") or False,
        "total_duration": response.get("total_duration") or 0,
        "load_duration": response.get("load_duration") or 0,
        "prompt_eval_count": response.get("prompt_eval_count") or 0,
        "prompt_eval_duration": response.get("prompt_eval_duration") or 0,
        "eval_count": response.get("eval_count") or 0,
        "eval_duration": response.get("eval_duration") or 0,
    }
    if response.get("done_reason"):
        out["done_reason"] = response["done_reason"]
    return out


# -- OpenAI ----------------------------------------------------------------

def _finish_reason(response: dict[str, Any]) -> str:
    done_reason = response.get("done_reason")
    if done_reason == "stop":
        return "stop"
    if done_reason == "length":
        return "length"
    message = response.get("message") or {}
    if message.get("tool_calls"):
        return "tool_calls"
    if response.get("eval_count") == 0:
        return "length"
    return "stop"


def _usage(response: dict[str, Any]) -> dict[str, int]:
    p = response.get("prompt_eval_count") or 0
    c = response.get("eval_count") or 0
    return {"prompt_tokens": p, "completion_tokens": c, "total_tokens": p + c}


def to_openai_chat(response: dict[str, Any], model: str, request_id: str) -> dict[str, Any]:
    message = response.get("message") or {
        "role": "assistant", "content": response.get("response")}
    choice: dict[str, Any] = {
        "index": 0,
        "message": {"role": "assistant", "content": message.get("content")},
        "logprobs": None,
        "finish_reason": _finish_reason(response),
    }
    if message.get("tool_calls"):
        # Ollama shape (arguments: object) → OpenAI shape (id/type +
        # arguments as a JSON string), matching the reference's facade
        choice["message"]["tool_calls"] = [
            {
                "id": f"call_{request_id[:8]}_{i}",
                "type": "function",
                "function": {
                    "name": (tc.get("function") or {}).get("name", ""),
                    "arguments": json.dumps(
                        (tc.get("function") or {}).get("arguments", {})
                    ),
                },
            }
            for i, tc in enumerate(message["tool_calls"])
        ]
    out: dict[str, Any] = {
        "id": f"chatcmpl-{request_id}",
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
        "usage": _usage(response),
        # Ollama's facade always stamps system_fingerprint ("fp_ollama");
        # drop-in clients see the same key here (reference passes it
        # through end-to-end, openai.ts:298-301)
        "system_fingerprint": response.get("system_fingerprint")
        or "fp_gridllm_tpu",
    }
    return out


def to_openai_completion(response: dict[str, Any], model: str, request_id: str,
                         prompt: str = "", echo: bool = False) -> dict[str, Any]:
    text = response.get("response") or ""
    out: dict[str, Any] = {
        "id": f"cmpl-{request_id}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{
            "text": (prompt + text) if echo else text,
            "index": 0,
            "logprobs": None,
            "finish_reason": _finish_reason(response),
        }],
        "usage": _usage(response),
        "system_fingerprint": response.get("system_fingerprint")
        or "fp_gridllm_tpu",
    }
    return out


# -- streaming framing -----------------------------------------------------

async def start_ndjson(request: web.Request) -> web.StreamResponse:
    """Ollama streams NDJSON with Content-Type application/json + chunked
    transfer (reference: ollama.ts:248-250)."""
    resp = web.StreamResponse(status=200, headers={
        "Content-Type": "application/x-ndjson"})
    resp.enable_chunked_encoding()
    await resp.prepare(request)
    return resp


async def write_ndjson(resp: web.StreamResponse, data: dict[str, Any]) -> None:
    await resp.write((json.dumps(data) + "\n").encode())


async def start_sse(request: web.Request) -> web.StreamResponse:
    """reference: openai.ts:686-690."""
    resp = web.StreamResponse(status=200, headers={
        "Content-Type": "text/event-stream",
        "Cache-Control": "no-cache",
        "Connection": "keep-alive",
        "Access-Control-Allow-Origin": "*",
    })
    resp.enable_chunked_encoding()
    await resp.prepare(request)
    return resp


async def write_sse(resp: web.StreamResponse, data: dict[str, Any] | str) -> None:
    payload = data if isinstance(data, str) else json.dumps(data)
    await resp.write(f"data: {payload}\n\n".encode())
