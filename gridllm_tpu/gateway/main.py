"""Console-script entry for the gateway server (``gridllm-server``)."""

from gridllm_tpu.gateway.app import main

if __name__ == "__main__":  # pragma: no cover
    main()
