"""Shared route plumbing: submit helpers and streaming error guards."""

from __future__ import annotations

import hashlib
import json as _json
from typing import Any, Awaitable, Callable

from aiohttp import web

from gridllm_tpu.gateway.errors import ApiError
from gridllm_tpu.obs import resolve_tenant
from gridllm_tpu.scheduler import JobScheduler
from gridllm_tpu.scheduler.scheduler import JobTimeoutError
from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import InferenceRequest, JobResult

log = get_logger("gateway.common")


def tenant_of(request: web.Request) -> str:
    """Tenant id for usage attribution (ISSUE 16): the configured
    GRIDLLM_TENANT_HEADER value, else a truncated hash of the
    Authorization bearer, else 'anonymous'. Stamped into every
    request's metadata at the gateway — the one ingress point — so
    traces, flight-recorder events, and the shard usage ledger all
    agree on who a request belongs to."""
    return resolve_tenant(request.headers)


def _truncate_part(v: Any, limit: int = 1024) -> Any:
    """Bound a structured prefix-key part BEFORE serialization — a 500 KB
    system message must not be json.dumps'd in full on the request hot
    path just to keep its first kilobyte."""
    if isinstance(v, str):
        return v[:limit]
    if isinstance(v, dict):
        return {k: _truncate_part(x, limit) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_truncate_part(x, limit) for x in v[:8]]
    return v


def prefix_key(model: str, *parts: Any) -> str:
    """Stable content key for a request's reusable prompt prefix (ISSUE 3).

    Hash of the model plus the rendered system prompt / leading message
    content (first ~1 KiB per string) — enough to identify the shared
    prefix of templated and multi-turn workloads WITHOUT the scheduler
    ever seeing token ids. Stamped as metadata.prefixKey by the inference
    routes; workers heartbeat the keys they recently served and worker
    selection scores the overlap (prefix-affinity routing)."""
    h = hashlib.blake2b(digest_size=8)
    h.update(model.encode("utf-8", "replace"))
    for p in parts:
        h.update(b"\x1f")
        if p is None:
            continue
        if not isinstance(p, str):
            p = _json.dumps(_truncate_part(p), sort_keys=True, default=str)
        h.update(p[:1024].encode("utf-8", "replace"))
    return h.hexdigest()


async def submit(req: InferenceRequest, scheduler: JobScheduler,
                 timeout_code: str = "JOB_TIMEOUT",
                 failure_code: str = "INFERENCE_FAILED",
                 error_cls: type[ApiError] = ApiError) -> JobResult:
    """submit_and_wait with HTTP error translation (timeout→504, failure→500)."""
    try:
        result = await scheduler.submit_and_wait(req)
    except JobTimeoutError as e:
        raise error_cls(str(e), 504, timeout_code) from None
    if not result.success:
        if result.error and result.error.startswith("deadline_exceeded"):
            # queued past its class deadline and shed (ISSUE 9): the
            # structured 504 tells the client to back off, not retry hot
            raise error_cls("Request deadline exceeded while queued", 504,
                            "DEADLINE_EXCEEDED")
        raise error_cls(result.error or "Inference failed", 500, failure_code)
    return result


def response_dict(result: JobResult) -> dict[str, Any]:
    return result.response.model_dump(exclude_none=True) if result.response else {}


async def guarded_stream(resp: web.StreamResponse,
                         run: Callable[[], Awaitable[None]],
                         on_error: Callable[[str], Awaitable[None]]) -> web.StreamResponse:
    """Run a streaming body after the response is prepared. Any failure is
    delivered as an in-stream error frame (a second JSON response can't be
    started once headers are out); client disconnects end the stream quietly."""
    try:
        await run()
    except JobTimeoutError as e:
        try:
            await on_error(str(e))
        except (ConnectionResetError, ConnectionError):
            pass
    except (ConnectionResetError, ConnectionError):
        log.info("client disconnected mid-stream")
    except Exception as e:
        log.error("streaming handler failed", error=str(e))
        try:
            await on_error("Internal error during streaming")
        except (ConnectionResetError, ConnectionError):
            pass
    try:
        await resp.write_eof()
    except (ConnectionResetError, ConnectionError):
        pass
    return resp
