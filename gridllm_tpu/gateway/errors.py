"""HTTP error machinery.

Reference analogue: server/src/middleware/errorHandler.ts — createError with
(message, status, code, details), JSON error envelope
``{"error": {"message", "code", "details"}, "timestamp", "path", "method"}``
(details only in development), and a 404 envelope with code NOT_FOUND.
"""

from __future__ import annotations

import json
from typing import Any

from aiohttp import web
from pydantic import ValidationError as PydanticValidationError

from gridllm_tpu.utils.logging import get_logger
from gridllm_tpu.utils.types import iso_now

log = get_logger("gateway.errors")

APP_ENV: web.AppKey[str] = web.AppKey("env", str)


class ApiError(Exception):
    def __init__(self, message: str, status: int = 500,
                 code: str | None = None, details: Any = None):
        super().__init__(message)
        self.message = message
        self.status = status
        self.code = code
        self.details = details


class OpenAIApiError(ApiError):
    """Rendered in the OpenAI error envelope
    ``{"error": {"message", "type", "code"}}`` for /v1 routes."""

    def __init__(self, message: str, status: int = 500,
                 etype: str = "invalid_request_error", code: str | None = None):
        super().__init__(message, status, code)
        self.etype = etype


def error_body(request: web.Request, message: str, code: str | None = None,
               details: Any = None, dev: bool = False) -> dict:
    err: dict[str, Any] = {"message": message}
    if code is not None:
        err["code"] = code
    if dev and details is not None:
        err["details"] = details
    return {
        "error": err,
        "timestamp": iso_now(),
        "path": request.path,
        "method": request.method,
    }


@web.middleware
async def error_middleware(request: web.Request, handler):
    dev = request.app.get(APP_ENV, "development") == "development"
    try:
        return await handler(request)
    except OpenAIApiError as e:
        log.error("request error", path=request.path, status=e.status,
                  message=e.message, code=e.code)
        return web.json_response(
            {"error": {"message": e.message, "type": e.etype, "code": e.code}},
            status=e.status)
    except ApiError as e:
        log.error("request error", path=request.path, status=e.status,
                  message=e.message, code=e.code)
        return web.json_response(
            error_body(request, e.message, e.code, e.details, dev), status=e.status)
    except web.HTTPNotFound:
        return web.json_response(
            error_body(request, "Route not found", "NOT_FOUND"), status=404)
    except web.HTTPException:
        raise
    except json.JSONDecodeError:
        return web.json_response(
            error_body(request, "Invalid JSON body", "BAD_JSON"), status=400)
    except PydanticValidationError as e:
        # malformed request fields surface as 400, not 500
        first = e.errors()[0] if e.errors() else {}
        loc = ".".join(str(p) for p in first.get("loc", ()))
        msg = f"Validation error: \"{loc}\" {first.get('msg', 'is invalid')}"
        return web.json_response(
            error_body(request, msg, "VALIDATION_ERROR"), status=400)
    except Exception as e:  # unexpected
        log.error("unhandled request error", path=request.path, error=str(e))
        return web.json_response(
            error_body(request, "Internal Server Error", details=str(e), dev=dev),
            status=500)
