"""Health/observability endpoints.

Reference analogue: server/src/routes/health.ts (172 LoC): /health basic,
/health/live, /health/ready (503 when bus/registry/scheduler not ready),
/health/system (workers/jobs/memory/CPU), /health/workers, /health/jobs.
"""

from __future__ import annotations

import os
import time

from aiohttp import web

from gridllm_tpu.bus.base import MessageBus
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry

_START = time.time()


def build_routes(bus: MessageBus, registry: WorkerRegistry,
                 scheduler: JobScheduler, version: str,
                 fleet=None) -> list[web.RouteDef]:

    async def health(request: web.Request) -> web.Response:
        return web.json_response({
            "status": "healthy",
            "timestamp": time.time(),
            "uptime": time.time() - _START,
            "version": version,
        })

    async def live(request: web.Request) -> web.Response:
        return web.json_response({"status": "alive"})

    async def ready(request: web.Request) -> web.Response:
        bus_ok = await bus.is_healthy()
        checks = {
            "redis": bus_ok,
            "workerRegistry": registry is not None,
            "jobScheduler": scheduler is not None,
        }
        ok = all(checks.values())
        return web.json_response(
            {"status": "ready" if ok else "not_ready", "checks": checks},
            status=200 if ok else 503)

    async def system(request: web.Request) -> web.Response:
        try:
            import resource

            max_rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        except Exception:
            max_rss_kb = 0
        la1, la5, la15 = os.getloadavg()
        stats = scheduler.get_stats()
        return web.json_response({
            "status": "healthy",
            "workers": registry.get_worker_count(),
            "jobs": stats,
            "system": {
                "maxRssMB": round(max_rss_kb / 1024, 1),
                "loadAvg": [la1, la5, la15],
                "cpuCount": os.cpu_count(),
                "uptime": time.time() - _START,
            },
        })

    async def workers(request: web.Request) -> web.Response:
        detail = []
        for w in registry.get_all_workers():
            detail.append({
                "workerId": w.workerId,
                "status": w.status,
                "healthState": w.healthState,
                "role": w.role,
                "decodeSlotsFree": w.decodeSlotsFree,
                "currentJobs": w.currentJobs,
                "totalJobsProcessed": w.totalJobsProcessed,
                "lastHeartbeat": w.lastHeartbeat,
                "connectionHealth": w.connectionHealth,
                "models": w.model_names(),
                "maxConcurrentTasks": w.capabilities.maxConcurrentTasks,
                "performanceTier": w.capabilities.performanceTier,
                "topology": (w.capabilities.topology.model_dump()
                             if w.capabilities.topology else None),
            })
        body = {"workers": detail,
                "counts": registry.get_worker_count(),
                "roles": registry.role_counts()}
        if fleet is not None:
            # scaled control plane (ISSUE 15): the worker table above is
            # already fleet-wide (heartbeats fan out to every member);
            # attach the control-plane members so one call shows both
            # planes regardless of which replica answered
            body["controlPlane"] = {"member": scheduler.identity(),
                                    "members": fleet.members(),
                                    "numShards": fleet.num_shards()}
        return web.json_response(body)

    async def jobs(request: web.Request) -> web.Response:
        return web.json_response({
            "queue": [
                {"id": r.id, "model": r.model, "priority": r.priority.value,
                 "requestType": r.request_type}
                for r in scheduler.get_job_queue()
            ],
            "active": [
                {"jobId": a.jobId, "workerId": a.workerId,
                 "model": a.request.model, "assignedAt": a.assignedAt}
                for a in scheduler.get_active_jobs()
            ],
            "stats": scheduler.get_stats(),
        })

    return [
        web.get("/health", health),
        web.get("/health/live", live),
        web.get("/health/ready", ready),
        web.get("/health/system", system),
        web.get("/health/workers", workers),
        web.get("/health/jobs", jobs),
    ]
