from gridllm_tpu.gateway.app import GatewayServer, create_app

__all__ = ["GatewayServer", "create_app"]
