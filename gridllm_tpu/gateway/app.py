"""Gateway server assembly.

Reference analogue: server/src/index.ts (GridLLMServer, 330 LoC): middleware
stack, route mounting (/ollama, /v1, /inference, /health, root summary),
event→log wiring (:119-212), graceful shutdown (:272-301), 60 s status log
loop (:249-265). The reference also configured a rate limiter but never
mounted it (SURVEY.md §2.4) — here it is actually mounted.
"""

from __future__ import annotations

import asyncio
import time

from aiohttp import web

import gridllm_tpu
from gridllm_tpu.bus import create_bus
from gridllm_tpu.bus.base import MessageBus
from gridllm_tpu.gateway import (
    health_routes,
    inference_routes,
    obs_routes,
    ollama_routes,
    openai_routes,
)
from gridllm_tpu.gateway.errors import APP_ENV, error_middleware
from gridllm_tpu.gateway.ratelimit import rate_limit_middleware
from gridllm_tpu.scheduler import JobScheduler, WorkerRegistry
from gridllm_tpu.utils.config import Config, load_config
from gridllm_tpu.utils.logging import get_logger

log = get_logger("gateway.app")


def create_app(bus: MessageBus, registry: WorkerRegistry, scheduler: JobScheduler,
               config: Config | None = None, fleet=None,
               timeline=None, incidents=None) -> web.Application:
    """``fleet`` (ISSUE 15): a FleetView on scaled-control-plane gateway
    replicas — the admin/health surfaces then answer fleet-wide.
    ``timeline``/``incidents`` (ISSUE 17): this member's TimelineStore +
    IncidentCollector behind /admin/timeline and /admin/incidents."""
    config = config or load_config()
    version = gridllm_tpu.__version__
    app = web.Application(
        # metrics outermost: it must observe the FINAL status, including
        # error-middleware translations and 429s from the rate limiter
        middlewares=[obs_routes.metrics_middleware(scheduler),
                     error_middleware,
                     rate_limit_middleware(config.gateway, bus=bus,
                                           metrics=scheduler.metrics)],
        client_max_size=config.gateway.max_body_bytes,
    )
    app[APP_ENV] = config.env

    # /ollama/api/* is the canonical mount (reference mounts at /ollama);
    # the same handlers are also mounted bare at /api/* so native Ollama
    # SDKs pointed straight at the gateway work unchanged.
    timeout_ms = config.gateway.default_request_timeout_ms
    # ONE ModelAdmin across surfaces: concurrent cold-model requests from
    # the Ollama and OpenAI APIs coalesce behind the same load broadcast
    from gridllm_tpu.gateway.admin import ModelAdmin

    admin = ModelAdmin(registry, timeout_ms)
    admin.active_models = lambda: {
        a.request.model for a in scheduler.get_active_jobs()
    } | {a.request.model for a in scheduler.job_queue}
    if config.gateway.enforce_keep_alive:
        # Ollama-exact idle residency (opt-in; see GatewayConfig)
        async def _start_sweeper(_app):
            admin.start_keep_alive_sweeper()

        async def _stop_sweeper(_app):
            await admin.stop_keep_alive_sweeper()

        app.on_startup.append(_start_sweeper)
        app.on_cleanup.append(_stop_sweeper)
    ollama = ollama_routes.build_routes(registry, scheduler, version,
                                        timeout_ms, admin=admin)
    app.add_routes([web.RouteDef(r.method, f"/ollama{r.path}", r.handler, r.kwargs)
                    for r in ollama])
    app.add_routes(ollama)
    app.add_routes(openai_routes.build_routes(registry, scheduler, timeout_ms,
                                              admin=admin))
    app.add_routes(inference_routes.build_routes(registry, scheduler))
    app.add_routes(health_routes.build_routes(bus, registry, scheduler,
                                              version, fleet=fleet))
    app.add_routes(obs_routes.build_routes(scheduler, fleet=fleet,
                                           timeline=timeline,
                                           incidents=incidents))

    async def root(request: web.Request) -> web.Response:
        """Root summary (reference: server/src/index.ts:86-109)."""
        stats = scheduler.get_stats()
        return web.json_response({
            "name": "GridLLM-TPU Server",
            "version": version,
            "status": "running",
            "workers": registry.get_worker_count(),
            "jobs": stats,
            "endpoints": {
                "ollama": "/ollama/api/*",
                "openai": "/v1/*",
                "inference": "/inference",
                "health": "/health",
            },
        })

    app.add_routes([web.get("/", root)])
    return app


class GatewayServer:
    """Full server lifecycle: bus + registry + scheduler + HTTP.

    Control-plane modes (ISSUE 15, ``GRIDLLM_CONTROLPLANE``):

    - ``local`` (default): the scheduler lives in this process — the
      single-box layout, bit-identical to the pre-ISSUE-15 server.
    - ``gateway``: this process is one of N stateless replicas. The
      scheduler is a GatewaySubmitter (submissions fan out to the
      scheduler shards on ``ctrl:submit``; results/streams arrive on
      the durable per-job channels), the registry runs in observer mode
      (shards own the worker-death verdicts), and a FleetView +
      StatusPublisher serve the fleet-wide admin/health surface.
    """

    def __init__(self, config: Config | None = None, bus: MessageBus | None = None):
        self.config = config or load_config()
        from gridllm_tpu.obs import default_flight_recorder

        default_flight_recorder().set_capacity(
            self.config.obs.flightrec_capacity)
        self.bus = bus or create_bus(self.config.bus.url,
                                     key_prefix=self.config.bus.key_prefix,
                                     password=self.config.bus.password,
                                     db=self.config.bus.db,
                                     endpoints=self.config.bus.endpoints)
        cp = self.config.controlplane
        self.fleet = None
        self._status_pub = None
        if cp.mode == "gateway":
            from gridllm_tpu.controlplane.client import GatewaySubmitter
            from gridllm_tpu.controlplane.status import (
                FleetView,
                StatusPublisher,
            )

            self.registry = WorkerRegistry(self.bus, self.config.scheduler,
                                           observer=True)
            self.scheduler = GatewaySubmitter(
                self.bus, self.registry, self.config.scheduler,
                slo_config=self.config.obs.slo,
                member_id=cp.member_id)
            self.fleet = FleetView(
                self.bus, self.scheduler.metrics,
                stale_after_ms=3 * cp.status_interval_ms)
            self._status_pub = StatusPublisher(
                self.bus, self.scheduler, "gateway",
                self.scheduler.member_id, cp.status_interval_ms)
        else:
            self.registry = WorkerRegistry(self.bus, self.config.scheduler)
            self.scheduler = JobScheduler(
                self.bus, self.registry, self.config.scheduler,
                slo_config=self.config.obs.slo,
                watchdog_config=self.config.obs.watchdog)
        # fleet timeline & incident forensics (ISSUE 17): every gateway —
        # local or replica — arms the event publisher plus a store +
        # collector, so any member answers /admin/timeline + /admin/incidents
        self.timeline_store = None
        self.incidents = None
        self._timeline_pub = None
        tl = self.config.obs.timeline
        if tl.enabled:
            from gridllm_tpu.obs import (
                IncidentCollector,
                TimelinePublisher,
                TimelineStore,
            )

            member = self.scheduler.identity().get("member") or "local"
            self._timeline_pub = TimelinePublisher(
                member, queue_capacity=tl.queue_capacity,
                flush_ms=tl.flush_ms, batch_max=tl.batch_max)
            self.timeline_store = TimelineStore(
                capacity=tl.store_capacity,
                max_requests=tl.store_requests)
            self.incidents = IncidentCollector(
                self.timeline_store, member=member,
                window_ms=tl.incident_window_ms,
                max_incidents=tl.max_incidents)
        self.app = create_app(self.bus, self.registry, self.scheduler,
                              self.config, fleet=self.fleet,
                              timeline=self.timeline_store,
                              incidents=self.incidents)
        self._runner: web.AppRunner | None = None
        self._status_task: asyncio.Task | None = None
        self._wire_events()

    def _wire_events(self) -> None:
        """Event→log wiring (reference: server/src/index.ts:119-212)."""
        self.registry.on("worker_registered",
                         lambda info: log.worker("registered", info.workerId,
                                                 models=info.model_names()))
        self.registry.on("worker_removed",
                         lambda wid, info, reason: log.worker("removed", wid, reason=reason))
        self.scheduler.on("job_queued", lambda r: log.job("queued", r.id, model=r.model))
        self.scheduler.on("job_completed",
                          lambda res: log.job("completed", res.jobId,
                                              ms=round(res.processingTimeMs, 1)))
        self.scheduler.on("job_failed", lambda res: log.job("failed", res.jobId,
                                                            error=res.error))
        self.scheduler.on("job_orphaned", lambda r: log.job("orphaned", r.id))

    async def start(self, port: int | None = None) -> int:
        await self.bus.connect()
        if self._timeline_pub is not None:
            # armed before scheduler/registry init so their lifecycle
            # events are on the fleet timeline from the first moment
            self._timeline_pub.install()
            await self._timeline_pub.start(self.bus)
            await self.timeline_store.attach(self.bus)
        await self.registry.initialize()
        await self.scheduler.initialize()
        if self.fleet is not None:
            await self.fleet.start()
        if self._status_pub is not None:
            await self._status_pub.start()
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.gateway.host,
                           port if port is not None else self.config.gateway.port)
        await site.start()
        bound = self._runner.addresses[0][1] if self._runner.addresses else 0
        self._status_task = asyncio.create_task(self._status_loop())
        log.info("gateway started", host=self.config.gateway.host, port=bound)
        return bound

    async def _status_loop(self) -> None:
        """60 s performance snapshot (reference: server/src/index.ts:249-265)."""
        while True:
            await asyncio.sleep(60)
            log.performance("status", workers=self.registry.get_worker_count(),
                            jobs=self.scheduler.get_stats())

    async def shutdown(self) -> None:
        log.info("gateway shutting down")
        if self._status_task:
            self._status_task.cancel()
            self._status_task = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        if self._status_pub is not None:
            await self._status_pub.stop()
        if self.fleet is not None:
            await self.fleet.stop()
        await self.scheduler.shutdown()
        await self.registry.shutdown()
        if self._timeline_pub is not None:
            await self._timeline_pub.stop()
        if self.timeline_store is not None:
            await self.timeline_store.detach()
        await self.bus.disconnect()


def main() -> None:  # pragma: no cover
    """CLI entry: ``gridllm-server`` / ``python -m gridllm_tpu.gateway.app``."""
    import signal

    async def run() -> None:
        server = GatewayServer()
        await server.start()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        await server.shutdown()

    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
