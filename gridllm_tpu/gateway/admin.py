"""Cluster model administration from the gateway: the admin broadcast
protocol (worker/service.py _on_admin) and Ollama residency semantics
(load-on-demand), shared by every API surface (ollama/openai routes).

One instance per app (gateway/app.py) so concurrent cold-model requests
coalesce across surfaces."""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from typing import Callable

from gridllm_tpu.bus.base import CH_WORKER_ADMIN, admin_result_channel
from gridllm_tpu.scheduler import WorkerRegistry
from gridllm_tpu.utils.logging import get_logger

log = get_logger("gateway.admin")


class ModelAdmin:
    # retry spacing for models whose every sweep reply said "model
    # management disabled" (multi-host groups can't unload)
    SWEEP_BACKOFF_S = 300.0

    def __init__(self, registry: WorkerRegistry,
                 default_timeout_ms: int = 300_000) -> None:
        self.registry = registry
        self.default_timeout_s = default_timeout_ms / 1000.0
        # in-flight load-on-demand broadcasts, coalesced per model: N
        # concurrent requests for a cold model must not fire N cluster
        # broadcasts + N propagation polls. DETACHED tasks, not futures
        # tied to a requesting handler: a leader client disconnecting must
        # not cancel a load other requests (on either surface) wait on.
        self._load_tasks: dict[str, asyncio.Task] = {}
        # short negative cache: a model the cluster just failed to load is
        # not re-broadcast for every retry (typo storms would otherwise
        # queue behind real loads on the workers' serialized admin lock)
        self._fail_at: dict[str, float] = {}
        self.fail_ttl_s = 30.0
        # advertised residency: model → wall-clock expiry of its last
        # request's keep_alive window (None = keep forever). /api/ps
        # reports it; the opt-in sweeper (enforce_keep_alive) REALLY
        # unloads when it passes — Ollama's idle-unload behavior.
        self.model_expiry: dict[str, float | None] = {}
        self._sweeper: asyncio.Task | None = None
        # set by app.py: () -> set of model names with jobs in flight —
        # the sweeper must never unload under an active request (the
        # keep_alive clock measures IDLE time, and gateway handlers
        # re-touch expiry at completion; this probe is the belt to that
        # suspender for queued/retrying jobs the gateway can't see)
        self.active_models = None

    @staticmethod
    def canonical(model: str) -> str:
        """The ':latest' alias normalized away — expiry/busy bookkeeping
        must use ONE name per model, like the workers' _resolve_name."""
        return model[: -len(":latest")] if model.endswith(":latest") else model

    def touch_keep_alive(self, model: str, seconds: float | None) -> None:
        """Restart the idle window: None = keep forever."""
        self.model_expiry[self.canonical(model)] = (
            None if seconds is None else time.time() + seconds
        )

    def servable_now(self, model: str) -> bool:
        """Alias-aware registry check: workers resolve the ':latest' tag
        both ways (worker/service.py _resolve_name), so the gateway
        lookup must too or alias-named requests could never observe the
        load they just triggered."""
        reg = self.registry
        if reg.get_workers_with_model(model):
            return True
        if model.endswith(":latest") and reg.get_workers_with_model(
            model[: -len(":latest")]
        ):
            return True
        return (":" not in model
                and bool(reg.get_workers_with_model(f"{model}:latest")))

    async def broadcast(
        self, op: str, payload: dict, timeout_s: float,
        on_result: Callable | None = None,
    ) -> list[dict]:
        """One admin op to every worker; collects their results. Workers
        ack instantly then work (worker/service.py), so a missing ack
        within the grace window means nobody speaks the protocol."""
        bus = self.registry.bus
        rid = uuid.uuid4().hex
        expect = max(len(self.registry.get_online_workers()), 1)
        results: list[dict] = []
        acks = 0
        done = asyncio.Event()

        async def handler(_ch: str, raw: str) -> None:
            nonlocal acks
            rec = json.loads(raw)
            if rec.get("ack"):
                acks += 1
                return
            results.append(rec)
            # count/done BEFORE the progress callback: a raising on_result
            # (e.g. streamed-pull client disconnect mid-write) must not
            # leave the broadcast waiting out its whole timeout
            if len(results) >= expect:
                done.set()
            if on_result is not None:
                await on_result(rec)

        sub = await bus.subscribe(admin_result_channel(rid), handler)
        try:
            await asyncio.sleep(0.05)  # pub/sub delivery is async (broker)
            await bus.publish(CH_WORKER_ADMIN,
                              json.dumps({"op": op, "id": rid, **payload}))
            try:
                await asyncio.wait_for(done.wait(), min(5.0, timeout_s))
            except asyncio.TimeoutError:
                if acks or results:
                    try:
                        await asyncio.wait_for(done.wait(),
                                               max(timeout_s - 5.0, 0.0))
                    except asyncio.TimeoutError:
                        pass
        finally:
            # also on cancellation (client disconnect mid-load): the
            # admin:result subscription must never outlive the broadcast
            await sub.unsubscribe()
        return results

    async def _load(self, model: str) -> bool:
        results = await self.broadcast(
            "load_model", {"model": model}, self.default_timeout_s)
        if any(r.get("ok") for r in results):
            for _ in range(100):  # registration propagation
                if self.servable_now(model):
                    return True
                await asyncio.sleep(0.1)
        return self.servable_now(model)

    async def ensure_servable(self, model: str) -> bool:
        """Ollama load-on-demand: if no worker serves `model`, ask the
        cluster to load it (the other half of keep_alive=0 actually
        unloading — Ollama reloads transparently on the next request).
        Returns whether the model is servable afterwards."""
        if self.servable_now(model):
            return True
        if not self.registry.get_online_workers():
            return False
        last_fail = self._fail_at.get(model)
        if last_fail is not None:
            if time.monotonic() - last_fail < self.fail_ttl_s:
                return False
            self._fail_at.pop(model, None)
        task = self._load_tasks.get(model)
        if task is None:
            task = asyncio.create_task(self._load(model))
            self._load_tasks[model] = task
            task.add_done_callback(
                lambda t, m=model: self._load_tasks.pop(m, None))
        try:
            # shield: a waiter's cancellation (client disconnect) must not
            # cancel the shared load, nor poison the other waiters
            ok = await asyncio.shield(task)
        except asyncio.CancelledError:
            raise  # THIS request was cancelled; the load continues
        except Exception:
            ok = False
        if not ok:
            self._fail_at[model] = time.monotonic()
        return ok


    # -------------------------------------------- keep_alive enforcement

    def start_keep_alive_sweeper(self, interval_s: float = 10.0) -> None:
        """Opt-in Ollama idle-unload (gateway.enforce_keep_alive): when a
        model's keep_alive window passes with no new requests, broadcast a
        real unload. The next request auto-loads it back."""
        if self._sweeper is None:
            self._sweeper = asyncio.create_task(
                self._sweep_loop(interval_s))

    async def stop_keep_alive_sweeper(self) -> None:
        if self._sweeper is not None:
            self._sweeper.cancel()
            try:
                await self._sweeper
            except asyncio.CancelledError:
                pass
            self._sweeper = None

    async def _sweep_loop(self, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            now = time.time()
            busy = set()
            if self.active_models is not None:
                try:
                    busy = {self.canonical(m) for m in self.active_models()}
                except Exception:  # noqa: BLE001
                    busy = set()
            for model, exp in list(self.model_expiry.items()):
                if exp is None or now < exp or self.canonical(model) in busy:
                    continue
                try:
                    # if_idle: the WORKER declines when any request is in
                    # flight or queued on the engine — closes the window
                    # between this gateway-side busy check and the unload
                    # landing (a sweep must never abort work; an explicit
                    # /api/delete still force-unloads)
                    results = await self.broadcast(
                        "unload_model", {"model": model, "if_idle": True},
                        30.0)
                except Exception:  # noqa: BLE001 — sweep must keep running
                    continue
                if any(r.get("ok") for r in results):
                    self.model_expiry.pop(model, None)
                elif (
                    results
                    and any("model management disabled"
                            in str(r.get("detail", "")) for r in results)
                    and all(
                        "model management disabled" in str(r.get("detail", ""))
                        or "not loaded" in str(r.get("detail", ""))
                        for r in results
                    )
                    # don't clobber a keep_alive touch (possibly None =
                    # keep forever) that landed during the 30s broadcast
                    and self.model_expiry.get(model) == exp
                ):
                    # Every REPLYING worker that HOLDS the model is a
                    # multi-host group member (admin ops permanently
                    # disabled; workers without the model answer "not
                    # loaded here") — back the retry off instead of
                    # re-broadcasting cluster-wide every sweep. Backoff,
                    # not permanent disable: the result set can be partial
                    # (a single-host worker offline or past the timeout),
                    # so the conclusion stays revisitable. /api/ps keeps
                    # reporting it resident.
                    log.info("keep_alive: only non-evictable (multi-host "
                             "group) replies for model, backing off",
                             model=model, backoff_s=self.SWEEP_BACKOFF_S)
                    self.model_expiry[model] = now + self.SWEEP_BACKOFF_S
                # otherwise declined/failed: keep the expiry so /api/ps
                # stays honest and the next sweep retries


def get_admin(registry: WorkerRegistry, admin: "ModelAdmin | None",
              default_timeout_ms: int) -> ModelAdmin:
    """build_routes helper: use the app-shared instance when provided."""
    return admin if admin is not None else ModelAdmin(
        registry, default_timeout_ms)


__all__ = ["ModelAdmin", "get_admin"]
