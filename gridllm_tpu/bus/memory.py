"""In-memory bus: single-process deployments and the unit-test fake.

SURVEY.md §4 calls for "an in-memory fake bus" so scheduler-policy tests need
no Redis/TPU. This is also a real deployment mode: gateway + scheduler +
worker in one process (the minimum end-to-end slice, SURVEY.md §7 step 4).

Delivery semantics mirror Redis pub/sub: fire-and-forget from the publisher's
point of view, asynchronous, strictly ordered per subscriber (HandlerPump).
``flush()`` drains in-flight deliveries (tests).
"""

from __future__ import annotations

import fnmatch
import time

from gridllm_tpu.bus.base import (
    Handler,
    HandlerPump,
    MessageBus,
    Subscription,
    record_publish,
)


class InMemoryBus(MessageBus):
    def __init__(self, key_prefix: str = "GridLLM:"):
        super().__init__(key_prefix)
        self._kv: dict[str, str] = {}
        self._expiry: dict[str, float] = {}          # key → monotonic deadline
        self._hashes: dict[str, dict[str, str]] = {}
        self._subs: dict[str, list[HandlerPump]] = {}   # channel → pumps
        self._psubs: dict[str, list[HandlerPump]] = {}  # pattern → pumps
        self._connected = False

    # -- lifecycle ----------------------------------------------------------
    async def connect(self) -> None:
        self._connected = True

    async def disconnect(self) -> None:
        self._connected = False
        for registry in (self._subs, self._psubs):
            for pumps in registry.values():
                for p in pumps:
                    p.stop()
            registry.clear()

    async def is_healthy(self) -> bool:
        return self._connected

    # -- KV -----------------------------------------------------------------
    def _expired(self, key: str) -> bool:
        dl = self._expiry.get(key)
        if dl is not None and time.monotonic() >= dl:
            self._kv.pop(key, None)
            self._hashes.pop(key, None)
            self._expiry.pop(key, None)
            return True
        return False

    async def get(self, key: str) -> str | None:
        key = self._k(key)
        if self._expired(key):
            return None
        return self._kv.get(key)

    async def set(self, key: str, value: str) -> None:
        key = self._k(key)
        self._kv[key] = value
        self._expiry.pop(key, None)

    async def set_with_expiry(self, key: str, value: str, ttl_s: float) -> None:
        key = self._k(key)
        self._kv[key] = value
        self._expiry[key] = time.monotonic() + ttl_s

    async def delete(self, key: str) -> None:
        key = self._k(key)
        self._kv.pop(key, None)
        self._hashes.pop(key, None)
        self._expiry.pop(key, None)

    async def ttl(self, key: str) -> int:
        key = self._k(key)
        if self._expired(key) or (key not in self._kv and key not in self._hashes):
            return -2
        dl = self._expiry.get(key)
        if dl is None:
            return -1
        return max(0, int(dl - time.monotonic()))

    # -- hash ---------------------------------------------------------------
    async def hget(self, key: str, field: str) -> str | None:
        return self._hashes.get(self._k(key), {}).get(field)

    async def hset(self, key: str, field: str, value: str) -> None:
        self._hashes.setdefault(self._k(key), {})[field] = value

    async def hgetall(self, key: str) -> dict[str, str]:
        return dict(self._hashes.get(self._k(key), {}))

    async def hdel(self, key: str, field: str) -> None:
        self._hashes.get(self._k(key), {}).pop(field, None)

    # -- pub/sub ------------------------------------------------------------
    async def publish(self, channel: str, message: str) -> int:
        # HLC-framed by record_publish (ISSUE 17); pumps strip + merge
        message = record_publish(channel, message) or message
        pumps: list[HandlerPump] = list(self._subs.get(channel, []))
        for pattern, phs in self._psubs.items():
            if fnmatch.fnmatchcase(channel, pattern):
                pumps.extend(phs)
        for p in pumps:
            p.push(channel, message)
        return len(pumps)

    async def subscribe(self, channel: str, handler: Handler) -> Subscription:
        pump = HandlerPump(handler)
        self._subs.setdefault(channel, []).append(pump)

        async def _unsub() -> None:
            lst = self._subs.get(channel, [])
            if pump in lst:
                lst.remove(pump)
            pump.stop()
            if not lst:
                self._subs.pop(channel, None)

        return Subscription(_unsub, channel)

    async def psubscribe(self, pattern: str, handler: Handler) -> Subscription:
        pump = HandlerPump(handler)
        self._psubs.setdefault(pattern, []).append(pump)

        async def _unsub() -> None:
            lst = self._psubs.get(pattern, [])
            if pump in lst:
                lst.remove(pump)
            pump.stop()
            if not lst:
                self._psubs.pop(pattern, None)

        return Subscription(_unsub, pattern)

    # -- test helper --------------------------------------------------------
    async def flush(self) -> None:
        """Await all in-flight deliveries (and any they trigger)."""
        for _ in range(50):
            pumps = [p for lst in (*self._subs.values(), *self._psubs.values()) for p in lst]
            for p in pumps:
                await p.drain()  # waits for queued AND in-flight handler calls
            # handlers may have published more, possibly to new subscriptions
            pumps = [p for lst in (*self._subs.values(), *self._psubs.values()) for p in lst]
            if all(p.queue.empty() and p.queue._unfinished_tasks == 0 for p in pumps):  # type: ignore[attr-defined]
                break
