"""RESP wire-protocol bus client (asyncio, no third-party deps).

Speaks RESP2 to any compatible broker: a real Redis 7 (the reference's bus,
docker-compose.yml service `redis`) or the bundled `gridbusd` broker
(gridllm_tpu/bus/broker.py). Mirrors the reference's 3-connection pattern —
main KV / subscriber / publisher — because a RESP connection in subscribe
mode cannot issue normal commands (server/src/services/RedisService.ts:19-53,
client/src/services/RedisConnectionManager.ts:36-92).

Failure handling (ISSUE 10 — the bus-HA client half):
- ``endpoints`` is an ORDERED broker list (primary first, warm standbys
  after — ``GRIDLLM_BUS_ENDPOINTS``). Every (re)connect walks the list
  from the top: the first usable broker wins, a reachable REPLICA is
  promoted (``FAILOVER``) only after every earlier endpoint failed, and
  a resurrected stale primary is fenced off (``FENCE`` with the newer
  epoch demotes it) instead of split-braining the KV state. Endpoint
  switches count in ``gridllm_bus_failovers_total``.
- main/publisher connections reconnect lazily inside ``command`` (one retry
  per call) — a broker restart or failover does not permanently poison
  KV/publish.
- the subscriber connection reconnects with NEVER-GIVE-UP capped
  exponential backoff with full jitter (a transient outage must never
  permanently kill the push loop), re-issues all subscriptions, and
  RESUMEs every durable channel from its last-seen seq — the broker
  replays the gap and the per-channel dedupe below drops overlap, so
  consumer-observed delivery is exactly-once across a broker bounce.
  While down, ``gridllm_bus_subscriber_down``/
  ``gridllm_bus_partition_seconds`` expose the partition and
  ``partition_state()`` feeds the registry/scheduler liveness holds.
  On loss it fires ``on_disconnect`` so the worker can publish
  `worker:disconnected` best-effort, mirroring
  RedisConnectionManager.ts:158-179.
- deliveries are strictly ordered per handler (HandlerPump).
- against real Redis (no EPOCH/RESUME commands) the HA layer disables
  itself after the first handshake and everything behaves as before.
"""

from __future__ import annotations

import asyncio
import random
import time
import weakref
from collections import OrderedDict
from typing import Awaitable, Callable

from gridllm_tpu.bus.base import (
    Handler,
    HandlerPump,
    MessageBus,
    Subscription,
    channel_class,
    durable_channel,
    record_publish,
    split_seq,
)
from gridllm_tpu.obs import metrics as obs
from gridllm_tpu.obs.flightrec import default_flight_recorder
from gridllm_tpu.utils.logging import get_logger

log = get_logger("bus.resp")

# -- bus-HA instruments (process-global registry) ---------------------------
_FAILOVERS = obs.default_registry().counter(
    "gridllm_bus_failovers_total",
    "Client-observed broker failovers: a bus connection re-established "
    "to a DIFFERENT endpoint in the ordered GRIDLLM_BUS_ENDPOINTS list.",
)
_REPLAYED = obs.default_registry().counter(
    "gridllm_bus_replayed_messages_total",
    "Messages replayed from the broker's durable-channel ring after a "
    "subscriber reconnect (RESUME), by channel class.",
    ("channel",),
)
_SUB_DOWN = obs.default_registry().gauge(
    "gridllm_bus_subscriber_down",
    "1 while this process's bus subscriber connection is down (push "
    "deliveries suspended; liveness verdicts are held).",
)
_PARTITION_SECONDS = obs.default_registry().gauge(
    "gridllm_bus_partition_seconds",
    "Seconds the current bus-session partition has lasted in this "
    "process; 0 while the subscriber session is healthy.",
)

_BUSES: "weakref.WeakSet[RespBus]" = weakref.WeakSet()


def _collect_bus_health() -> None:
    """Scrape-time collector: partition gauges from every live RespBus."""
    now = time.monotonic()
    down = 0
    longest = 0.0
    for bus in list(_BUSES):
        st = bus.partition_state()
        if st.get("degraded") and st.get("since") is not None:
            down = 1
            longest = max(longest, now - float(st["since"]))
    _SUB_DOWN.set(down)
    _PARTITION_SECONDS.set(longest)


obs.default_registry().add_collector("bus_partition", _collect_bus_health)


def encode_command(*args: str | bytes | int | float) -> bytes:
    """RESP array-of-bulk-strings command encoding."""
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode()
        out.append(f"${len(b)}\r\n".encode())
        out.append(b)
        out.append(b"\r\n")
    return b"".join(out)


class RespProtocolError(Exception):
    pass


async def read_reply(reader: asyncio.StreamReader):
    """Parse one RESP2 reply (simple/error/int/bulk/array, recursively)."""
    line = await reader.readuntil(b"\r\n")
    kind, rest = line[:1], line[1:-2]
    if kind == b"+":
        return rest.decode()
    if kind == b"-":
        raise RespProtocolError(rest.decode())
    if kind == b":":
        return int(rest)
    if kind == b"$":
        n = int(rest)
        if n == -1:
            return None
        data = await reader.readexactly(n + 2)
        return data[:-2].decode("utf-8", errors="replace")
    if kind == b"*":
        n = int(rest)
        if n == -1:
            return None
        return [await read_reply(reader) for _ in range(n)]
    raise RespProtocolError(f"bad RESP type byte: {line!r}")


_CONN_ERRORS = (ConnectionError, asyncio.IncompleteReadError, OSError, EOFError)


class _Conn:
    """One RESP connection with serialized request/reply and lazy reconnect.
    The actual socket + handshake comes from ``connector`` (RespBus owns
    endpoint selection, failover, and fencing)."""

    def __init__(self, name: str,
                 connector: Callable[[], Awaitable[
                     tuple[asyncio.StreamReader, asyncio.StreamWriter]]]):
        self.name = name
        self._connector = connector
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self._lock = asyncio.Lock()

    async def connect(self) -> None:
        async with self._lock:
            await self._connect_locked()

    async def _connect_locked(self) -> None:
        await self._close_locked()
        self.reader, self.writer = await self._connector()

    async def close(self) -> None:
        async with self._lock:
            await self._close_locked()

    async def _close_locked(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except Exception:
                pass
        self.reader = self.writer = None

    def _abandon(self) -> None:
        """Synchronous transport drop for the cancellation path: no
        awaits, so a pending CancelledError cannot re-fire inside the
        cleanup itself."""
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:  # noqa: BLE001
                pass
        self.reader = self.writer = None

    async def command(self, *args: str | bytes | int | float):
        async with self._lock:
            for attempt in range(2):
                try:
                    if self.writer is None:
                        await self._connect_locked()
                    assert self.reader is not None and self.writer is not None
                    self.writer.write(encode_command(*args))
                    await self.writer.drain()
                    return await read_reply(self.reader)
                except asyncio.CancelledError:
                    # Cancelled mid-exchange (caller timeout, task
                    # teardown, a handler unsubscribing its own pump):
                    # the command may already be written and its reply in
                    # flight. Abandon the transport so the NEXT command
                    # reconnects cleanly instead of reading the orphaned
                    # reply as its own — a reply-stream desync poisons
                    # every subsequent command on the connection.
                    self._abandon()
                    raise
                except _CONN_ERRORS:
                    await self._close_locked()
                    if attempt == 1:
                        raise
                    log.warning("connection lost, retrying once",
                                conn=self.name, command=str(args[0]))

    async def send_only(self, *args: str | bytes | int | float) -> None:
        """Write a command without reading its reply. Used on the subscriber
        connection while the push-message pump owns the read side (the pump
        consumes and ignores subscribe/unsubscribe acks)."""
        async with self._lock:
            if self.writer is None:
                raise ConnectionError(f"{self.name}: not connected")
            self.writer.write(encode_command(*args))
            await self.writer.drain()


class RespBus(MessageBus):
    # cap on the per-channel last-seen-seq map (exactly-once dedupe
    # state); oldest channels age out LRU-style
    MAX_SEQ_TRACKED = 8192
    CONNECT_TIMEOUT_S = 2.0

    def __init__(self, host: str = "localhost", port: int = 6379,
                 key_prefix: str = "GridLLM:", password: str | None = None,
                 db: int = 0, reconnect_max_attempts: int = 10,
                 endpoints: list[tuple[str, int]] | None = None):
        super().__init__(key_prefix)
        self.host, self.port = host, port
        self.password, self.db = password, db
        # HISTORICAL name: the subscriber loop no longer gives up (ISSUE
        # 10 — a transient outage permanently killed the push loop); past
        # this many consecutive failures it logs loudly and keeps trying.
        self.reconnect_max_attempts = reconnect_max_attempts
        # ordered endpoint list, primary first (GRIDLLM_BUS_ENDPOINTS);
        # the single (host, port) is the degenerate one-entry list
        self.endpoints: list[tuple[str, int]] = (
            list(endpoints) if endpoints else [(host, port)])
        self._active_ep: int | None = None   # index serving this process
        self._epoch = 0                      # highest fencing epoch seen
        self._ha: bool | None = None         # broker speaks EPOCH/RESUME?
        self._main = _Conn("main", lambda: self._open_connection("main"))
        self._pub = _Conn("publisher",
                          lambda: self._open_connection("publisher"))
        self._sub = _Conn("subscriber",
                          lambda: self._open_connection("subscriber"))
        self._subs: dict[str, list[HandlerPump]] = {}
        self._psubs: dict[str, list[HandlerPump]] = {}
        # per-channel last-seen seq on durable channels: the dedupe half
        # of exactly-once (the broker's RESUME replay is the other half)
        self._last_seq: OrderedDict[str, int] = OrderedDict()
        self._reader_task: asyncio.Task | None = None
        self._closed = False
        # partition-aware liveness (ISSUE 10): monotonic marks of the
        # current subscriber-session outage and the last recovery
        self._down_since: float | None = None
        self._last_rejoin: float | None = None
        # Set by the worker runtime to publish `worker:disconnected` fast-path
        self.on_disconnect: Callable[[], Awaitable[None]] | None = None
        _BUSES.add(self)

    # -- endpoint selection / fencing handshake -----------------------------
    async def _open_connection(
        self, conn_name: str
    ) -> tuple[asyncio.StreamReader, asyncio.StreamWriter]:
        """Walk the endpoint list from the top and return the first USABLE
        broker connection, fully handshaken (AUTH/SELECT, then the HA
        epoch/fence exchange). List order is the election authority:
        reaching a replica means every preferred endpoint already failed
        this pass, so promoting it is safe-by-construction (no quorum —
        the operator's ordering is the quorum)."""
        last_err: Exception | None = None
        for idx, (host, port) in enumerate(self.endpoints):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, port),
                    self.CONNECT_TIMEOUT_S)
            except (OSError, asyncio.TimeoutError) as e:
                last_err = e if isinstance(e, OSError) else \
                    ConnectionError(f"connect timeout to {host}:{port}")
                continue
            try:
                for cmd in ([("AUTH", self.password)] if self.password
                            else []) + \
                           ([("SELECT", self.db)] if self.db else []):
                    writer.write(encode_command(*cmd))
                    await writer.drain()
                    await read_reply(reader)
                if await self._ha_handshake(reader, writer):
                    if self._active_ep is not None and idx != self._active_ep:
                        _FAILOVERS.inc()
                        default_flight_recorder().record(
                            "bus", "failover", conn=conn_name,
                            endpoint=f"{host}:{port}", epoch=self._epoch)
                        log.warning("bus failover", conn=conn_name,
                                    endpoint=f"{host}:{port}",
                                    epoch=self._epoch)
                    self._active_ep = idx
                    return reader, writer
                last_err = ConnectionError(
                    f"{host}:{port} not usable (stale or unfenceable)")
            except _CONN_ERRORS as e:
                last_err = e
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
        raise last_err or ConnectionError("no usable bus endpoint")

    async def _ha_handshake(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> bool:
        """EPOCH/FENCE/FAILOVER exchange on a fresh connection. True when
        the broker is usable as the current primary. Against a broker
        without the HA commands (real Redis) the layer memoizes itself
        off and every endpoint is usable as-is."""
        if self._ha is False:
            return True

        async def ask(*args):
            writer.write(encode_command(*args))
            await writer.drain()
            return await read_reply(reader)

        try:
            got = await ask("EPOCH")
        except RespProtocolError:
            # plain Redis: no EPOCH — no fencing, no resume, no promote
            self._ha = False
            return True
        self._ha = True
        if not isinstance(got, list) or len(got) != 2:
            return False
        role, broker_epoch = str(got[0]), int(got[1])
        if role == "stale":
            return False
        if role == "replica":
            # every earlier endpoint failed this pass — promote. A
            # standby that never synced refuses (-NOTSYNCED): promoting
            # an empty broker during a bring-up race (this client booted
            # before the primary) would split-brain, so keep walking /
            # retrying until the real primary arrives.
            try:
                new_epoch = max(self._epoch, broker_epoch) + 1
                promoted = await ask("FAILOVER", new_epoch)
                self._epoch = max(self._epoch, int(promoted))
                await ask("FENCE", self._epoch)
            except RespProtocolError as e:
                log.warning("standby refused promotion", error=str(e))
                return False
            return True
        # primary: fence at the max of both epochs — a FENCE carrying a
        # NEWER epoch than the broker's demotes a resurrected stale
        # primary (raises -STALE) and we move on down the list
        fence_at = max(self._epoch, broker_epoch)
        try:
            await ask("FENCE", fence_at)
        except RespProtocolError as e:
            log.warning("stale primary fenced off", error=str(e),
                        epoch=fence_at)
            return False
        self._epoch = fence_at
        return True

    # -- lifecycle ----------------------------------------------------------
    async def connect(self) -> None:
        """Connect all three links; brief retry so a worker starting alongside
        the broker (compose-style bring-up) doesn't die on the race."""
        self._closed = False
        for conn in (self._main, self._pub, self._sub):
            delay = 0.3
            for attempt in range(5):
                try:
                    await conn.connect()
                    break
                # the full connection-error family, not just OSError: a
                # broker that accepts the TCP handshake and then hangs up
                # mid-handshake (dying broker, broker.accept fault site)
                # surfaces as IncompleteReadError/EOFError
                except _CONN_ERRORS:
                    if attempt == 4:
                        raise
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 3.0)
        self._reader_task = asyncio.create_task(self._sub_reader_loop())
        # Re-establish any subscriptions that predate a reconnect
        # (pump owns the read side now → write-only)
        await self._reissue_subscriptions()

    async def _reissue_subscriptions(self) -> None:
        for channel in list(self._subs):
            if self._ha and channel in self._last_seq:
                # RESUME subscribes AND replays the outage gap atomically
                # broker-side, so replayed frames always precede the
                # first live one — the seq dedupe drops any overlap
                await self._sub.send_only("RESUME", channel,
                                          self._last_seq[channel])
            else:
                await self._sub.send_only("SUBSCRIBE", channel)
        for pattern in list(self._psubs):
            await self._sub.send_only("PSUBSCRIBE", pattern)

    async def disconnect(self) -> None:
        self._closed = True
        # a deliberate close is not a partition: don't leave the gauges
        # (and any liveness holds) pinned on a bus that no longer exists
        self._down_since = None
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        for registry in (self._subs, self._psubs):
            for pumps in registry.values():
                for p in pumps:
                    p.stop()
            registry.clear()
        self._last_seq.clear()
        for conn in (self._main, self._pub, self._sub):
            await conn.close()

    async def is_healthy(self) -> bool:
        try:
            return (await self._main.command("PING")) == "PONG"
        except Exception:
            return False

    def partition_state(self) -> dict:
        """Partition-aware liveness feed (bus/base.py liveness_suspended):
        degraded while the subscriber session is down — this process is
        DEAF, so missing heartbeats say nothing about the fleet."""
        return {"degraded": self._down_since is not None,
                "since": self._down_since,
                "lastRejoin": self._last_rejoin}

    def _mark_partition(self) -> None:
        if self._down_since is None:
            self._down_since = time.monotonic()
            _SUB_DOWN.set(1)
            default_flight_recorder().record(
                "bus", "subscriber_down", endpoint=self._active_ep)

    def _mark_rejoin(self) -> None:
        if self._down_since is not None:
            outage_s = time.monotonic() - self._down_since
            self._down_since = None
            self._last_rejoin = time.monotonic()
            _SUB_DOWN.set(0)
            _PARTITION_SECONDS.set(0)
            default_flight_recorder().record(
                "bus", "subscriber_reconnected",
                outageS=round(outage_s, 3), endpoint=self._active_ep)

    async def _sub_reader_loop(self) -> None:
        """Push-message pump for the subscriber connection."""
        backoff = 0.5
        proto_errors = 0
        while not self._closed:
            try:
                assert self._sub.reader is not None
                msg = await read_reply(self._sub.reader)
                backoff = 0.5
                proto_errors = 0
            except asyncio.CancelledError:
                return
            except RespProtocolError as e:
                # a pushed error frame (e.g. RESUME against a broker that
                # lost the ring channel) is not a dead connection — but a
                # run of them means the reply stream is desynced, and
                # that IS one
                proto_errors += 1
                if proto_errors < 10:
                    log.warning("subscriber push error frame",
                                error=str(e))
                    continue
                msg = None
                if not await self._handle_sub_loss(
                        f"protocol desync: {e}", backoff):
                    return
                backoff = min(backoff * 2, 30.0)
                proto_errors = 0
                continue
            except Exception as e:
                if self._closed:
                    return
                if not await self._handle_sub_loss(str(e), backoff):
                    return
                backoff = min(backoff * 2, 30.0)
                continue
            if not isinstance(msg, list) or not msg:
                continue
            kind = msg[0]
            if kind == "message" and len(msg) == 3:
                _, channel, payload = msg
                payload = self._dedupe(channel, payload)
                if payload is None:
                    continue
                for pump in list(self._subs.get(channel, [])):
                    pump.push(channel, payload)
            elif kind == "pmessage" and len(msg) == 4:
                _, pattern, channel, payload = msg
                payload = self._dedupe(channel, payload)
                if payload is None:
                    continue
                for pump in list(self._psubs.get(pattern, [])):
                    pump.push(channel, payload)
            elif (kind == "subscribe" and len(msg) == 3
                    and self._ha and isinstance(msg[2], int)):
                # gridbus acks durable-channel subscribes with the
                # channel's current seq — the resume BASELINE. Without
                # it, a channel that never delivered before an outage
                # (a job's result channel) could not RESUME and anything
                # published during the gap would be silently lost.
                channel = str(msg[1])
                if durable_channel(channel) \
                        and channel not in self._last_seq:
                    self._note_seq(channel, int(msg[2]))
            elif kind == "resume" and len(msg) == 4:
                # broker's replay ack: [resume, channel, replayed, lost]
                _, channel, replayed, lost = msg
                if int(replayed):
                    _REPLAYED.inc(int(replayed),
                                  channel=channel_class(str(channel)))
                if int(lost) < 0:
                    # the broker lost its seq history (restart with no
                    # standby, counter eviction) and we are AHEAD of it:
                    # void the watermark — keeping it would drop every
                    # new message as a "duplicate" until the broker's
                    # fresh counter overtook it, silently muting the
                    # channel. The gap itself is unknowable; the
                    # at-least-once sweeps own it.
                    self._last_seq.pop(str(channel), None)
                    log.warning("bus seq history lost; watermark voided",
                                channel=str(channel))
                    default_flight_recorder().record(
                        "bus", "seq_reset", channel=str(channel))
                elif int(lost):
                    # the outage outran the replay ring: at-least-once
                    # degrades to the sweep/retry machinery for the hole
                    log.warning("bus resume gap (ring outrun)",
                                channel=str(channel), lost=int(lost))
                    default_flight_recorder().record(
                        "bus", "resume_gap", channel=str(channel),
                        lost=int(lost))
            # subscribe/unsubscribe acks: ignore

    def _note_seq(self, channel: str, seq: int) -> None:
        if channel in self._last_seq:
            self._last_seq.move_to_end(channel)
        self._last_seq[channel] = seq
        while len(self._last_seq) > self.MAX_SEQ_TRACKED:
            self._last_seq.popitem(last=False)

    def _dedupe(self, channel: str, payload: str) -> str | None:
        """Strip the broker's seq framing and drop already-seen messages
        (replay overlap, duplicated deliveries across a failover). None
        means drop; a payload without framing passes through untouched."""
        seq, body = split_seq(payload)
        if seq is None:
            return payload
        last = self._last_seq.get(channel)
        if last is not None and seq <= last:
            return None  # duplicate of something already delivered
        self._note_seq(channel, seq)
        return body

    async def _handle_sub_loss(self, error: str, delay: float) -> bool:
        """One subscriber-session outage: mark the partition, fire the
        disconnect hook, reconnect forever (capped backoff, full jitter).
        Returns False only when the bus is being closed."""
        log.warning("subscriber connection lost, reconnecting", error=error)
        self._mark_partition()
        if self.on_disconnect is not None:
            try:
                await self.on_disconnect()
            except Exception:
                pass
        ok = await self._reconnect_sub(delay)
        if ok:
            self._mark_rejoin()
        return ok

    async def _reconnect_sub(self, delay: float) -> bool:
        """Never-give-up reconnect (ISSUE 10 satellite): full-jitter capped
        exponential backoff, looping until the bus closes. The old
        10-attempts-then-dead behavior turned a 30-second broker outage
        into a permanently deaf process with only a log line to show."""
        attempt = 0
        while not self._closed:
            attempt += 1
            await asyncio.sleep(delay * random.random())  # full jitter
            try:
                await self._sub.connect()  # closes the stale transport first
                await self._reissue_subscriptions()
                log.info("subscriber reconnected", attempt=attempt)
                return True
            except Exception as e:  # noqa: BLE001 — keep trying
                if attempt == self.reconnect_max_attempts:
                    log.error(
                        "subscriber still down; continuing to retry",
                        attempts=attempt, error=str(e))
                delay = min(max(delay, 0.25) * 2, 30.0)
        return False

    # -- KV -----------------------------------------------------------------
    async def get(self, key: str) -> str | None:
        return await self._main.command("GET", self._k(key))

    async def set(self, key: str, value: str) -> None:
        await self._main.command("SET", self._k(key), value)

    async def set_with_expiry(self, key: str, value: str, ttl_s: float) -> None:
        # PX for sub-second TTLs (heartbeat TTL = 2× interval)
        await self._main.command("SET", self._k(key), value, "PX", int(ttl_s * 1000))

    async def delete(self, key: str) -> None:
        await self._main.command("DEL", self._k(key))

    async def ttl(self, key: str) -> int:
        return int(await self._main.command("TTL", self._k(key)))

    # -- hash ---------------------------------------------------------------
    async def hget(self, key: str, field: str) -> str | None:
        return await self._main.command("HGET", self._k(key), field)

    async def hset(self, key: str, field: str, value: str) -> None:
        await self._main.command("HSET", self._k(key), field, value)

    async def hgetall(self, key: str) -> dict[str, str]:
        flat = await self._main.command("HGETALL", self._k(key)) or []
        return {flat[i]: flat[i + 1] for i in range(0, len(flat), 2)}

    async def hdel(self, key: str, field: str) -> None:
        await self._main.command("HDEL", self._k(key), field)

    # -- pub/sub ------------------------------------------------------------
    async def publish(self, channel: str, message: str) -> int:
        # HLC-framed by record_publish (ISSUE 17); the broker's seq
        # framing wraps OUTSIDE this, so _dedupe strips seq first and
        # the HandlerPump strips + merges the surviving HLC frame
        message = record_publish(channel, message) or message
        return int(await self._pub.command("PUBLISH", channel, message))

    async def subscribe(self, channel: str, handler: Handler) -> Subscription:
        pump = HandlerPump(handler)
        first = channel not in self._subs
        self._subs.setdefault(channel, []).append(pump)
        if first:
            await self._sub.send_only("SUBSCRIBE", channel)

        async def _unsub() -> None:
            lst = self._subs.get(channel, [])
            if pump in lst:
                lst.remove(pump)
            pump.stop()
            if not lst:
                self._subs.pop(channel, None)
                self._last_seq.pop(channel, None)
                try:
                    await self._sub.send_only("UNSUBSCRIBE", channel)
                except Exception:
                    pass

        return Subscription(_unsub, channel)

    async def psubscribe(self, pattern: str, handler: Handler) -> Subscription:
        pump = HandlerPump(handler)
        first = pattern not in self._psubs
        self._psubs.setdefault(pattern, []).append(pump)
        if first:
            await self._sub.send_only("PSUBSCRIBE", pattern)

        async def _unsub() -> None:
            lst = self._psubs.get(pattern, [])
            if pump in lst:
                lst.remove(pump)
            pump.stop()
            if not lst:
                self._psubs.pop(pattern, None)
                try:
                    await self._sub.send_only("PUNSUBSCRIBE", pattern)
                except Exception:
                    pass

        return Subscription(_unsub, pattern)
