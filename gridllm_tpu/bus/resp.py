"""RESP wire-protocol bus client (asyncio, no third-party deps).

Speaks RESP2 to any compatible broker: a real Redis 7 (the reference's bus,
docker-compose.yml service `redis`) or the bundled `gridbusd` broker
(gridllm_tpu/bus/broker.py). Mirrors the reference's 3-connection pattern —
main KV / subscriber / publisher — because a RESP connection in subscribe
mode cannot issue normal commands (server/src/services/RedisService.ts:19-53,
client/src/services/RedisConnectionManager.ts:36-92).

Failure handling:
- main/publisher connections reconnect lazily inside ``command`` (one retry
  per call) — a broker restart does not permanently poison KV/publish.
- the subscriber connection reconnects with exponential backoff in its push
  pump and re-issues all subscriptions; on loss it fires ``on_disconnect`` so
  the worker can publish `worker:disconnected` best-effort, mirroring
  RedisConnectionManager.ts:158-179.
- deliveries are strictly ordered per handler (HandlerPump).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

from gridllm_tpu.bus.base import (
    Handler,
    HandlerPump,
    MessageBus,
    Subscription,
    record_publish,
)
from gridllm_tpu.utils.logging import get_logger

log = get_logger("bus.resp")


def encode_command(*args: str | bytes | int | float) -> bytes:
    """RESP array-of-bulk-strings command encoding."""
    out = [f"*{len(args)}\r\n".encode()]
    for a in args:
        b = a if isinstance(a, bytes) else str(a).encode()
        out.append(f"${len(b)}\r\n".encode())
        out.append(b)
        out.append(b"\r\n")
    return b"".join(out)


class RespProtocolError(Exception):
    pass


async def read_reply(reader: asyncio.StreamReader):
    """Parse one RESP2 reply (simple/error/int/bulk/array, recursively)."""
    line = await reader.readuntil(b"\r\n")
    kind, rest = line[:1], line[1:-2]
    if kind == b"+":
        return rest.decode()
    if kind == b"-":
        raise RespProtocolError(rest.decode())
    if kind == b":":
        return int(rest)
    if kind == b"$":
        n = int(rest)
        if n == -1:
            return None
        data = await reader.readexactly(n + 2)
        return data[:-2].decode("utf-8", errors="replace")
    if kind == b"*":
        n = int(rest)
        if n == -1:
            return None
        return [await read_reply(reader) for _ in range(n)]
    raise RespProtocolError(f"bad RESP type byte: {line!r}")


_CONN_ERRORS = (ConnectionError, asyncio.IncompleteReadError, OSError, EOFError)


class _Conn:
    """One RESP connection with serialized request/reply and lazy reconnect."""

    def __init__(self, host: str, port: int, name: str,
                 password: str | None = None, db: int = 0):
        self.host, self.port, self.name = host, port, name
        self.password, self.db = password, db
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self._lock = asyncio.Lock()

    async def connect(self) -> None:
        async with self._lock:
            await self._connect_locked()

    async def _connect_locked(self) -> None:
        await self._close_locked()
        self.reader, self.writer = await asyncio.open_connection(self.host, self.port)
        # AUTH/SELECT inline (can't recurse into command(); lock already held)
        for cmd in ([("AUTH", self.password)] if self.password else []) + \
                   ([("SELECT", self.db)] if self.db else []):
            self.writer.write(encode_command(*cmd))
            await self.writer.drain()
            await read_reply(self.reader)

    async def close(self) -> None:
        async with self._lock:
            await self._close_locked()

    async def _close_locked(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except Exception:
                pass
        self.reader = self.writer = None

    def _abandon(self) -> None:
        """Synchronous transport drop for the cancellation path: no
        awaits, so a pending CancelledError cannot re-fire inside the
        cleanup itself."""
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:  # noqa: BLE001
                pass
        self.reader = self.writer = None

    async def command(self, *args: str | bytes | int | float):
        async with self._lock:
            for attempt in range(2):
                try:
                    if self.writer is None:
                        await self._connect_locked()
                    assert self.reader is not None and self.writer is not None
                    self.writer.write(encode_command(*args))
                    await self.writer.drain()
                    return await read_reply(self.reader)
                except asyncio.CancelledError:
                    # Cancelled mid-exchange (caller timeout, task
                    # teardown, a handler unsubscribing its own pump):
                    # the command may already be written and its reply in
                    # flight. Abandon the transport so the NEXT command
                    # reconnects cleanly instead of reading the orphaned
                    # reply as its own — a reply-stream desync poisons
                    # every subsequent command on the connection.
                    self._abandon()
                    raise
                except _CONN_ERRORS:
                    await self._close_locked()
                    if attempt == 1:
                        raise
                    log.warning("connection lost, retrying once",
                                conn=self.name, command=str(args[0]))

    async def send_only(self, *args: str | bytes | int | float) -> None:
        """Write a command without reading its reply. Used on the subscriber
        connection while the push-message pump owns the read side (the pump
        consumes and ignores subscribe/unsubscribe acks)."""
        async with self._lock:
            if self.writer is None:
                raise ConnectionError(f"{self.name}: not connected")
            self.writer.write(encode_command(*args))
            await self.writer.drain()


class RespBus(MessageBus):
    def __init__(self, host: str = "localhost", port: int = 6379,
                 key_prefix: str = "GridLLM:", password: str | None = None,
                 db: int = 0, reconnect_max_attempts: int = 10):
        super().__init__(key_prefix)
        self.host, self.port = host, port
        self.password, self.db = password, db
        self.reconnect_max_attempts = reconnect_max_attempts
        self._main = _Conn(host, port, "main", password, db)
        self._pub = _Conn(host, port, "publisher", password, db)
        self._sub = _Conn(host, port, "subscriber", password, db)
        self._subs: dict[str, list[HandlerPump]] = {}
        self._psubs: dict[str, list[HandlerPump]] = {}
        self._reader_task: asyncio.Task | None = None
        self._closed = False
        # Set by the worker runtime to publish `worker:disconnected` fast-path
        self.on_disconnect: Callable[[], Awaitable[None]] | None = None

    # -- lifecycle ----------------------------------------------------------
    async def connect(self) -> None:
        """Connect all three links; brief retry so a worker starting alongside
        the broker (compose-style bring-up) doesn't die on the race."""
        self._closed = False
        for conn in (self._main, self._pub, self._sub):
            delay = 0.3
            for attempt in range(5):
                try:
                    await conn.connect()
                    break
                except OSError:
                    if attempt == 4:
                        raise
                    await asyncio.sleep(delay)
                    delay = min(delay * 2, 3.0)
        self._reader_task = asyncio.create_task(self._sub_reader_loop())
        # Re-establish any subscriptions that predate a reconnect
        # (pump owns the read side now → write-only)
        for channel in self._subs:
            await self._sub.send_only("SUBSCRIBE", channel)
        for pattern in self._psubs:
            await self._sub.send_only("PSUBSCRIBE", pattern)

    async def disconnect(self) -> None:
        self._closed = True
        if self._reader_task is not None:
            self._reader_task.cancel()
            self._reader_task = None
        for registry in (self._subs, self._psubs):
            for pumps in registry.values():
                for p in pumps:
                    p.stop()
            registry.clear()
        for conn in (self._main, self._pub, self._sub):
            await conn.close()

    async def is_healthy(self) -> bool:
        try:
            return (await self._main.command("PING")) == "PONG"
        except Exception:
            return False

    async def _sub_reader_loop(self) -> None:
        """Push-message pump for the subscriber connection."""
        backoff = 0.5
        while not self._closed:
            try:
                assert self._sub.reader is not None
                msg = await read_reply(self._sub.reader)
                backoff = 0.5
            except asyncio.CancelledError:
                return
            except Exception as e:
                if self._closed:
                    return
                log.warning("subscriber connection lost, reconnecting", error=str(e))
                if self.on_disconnect is not None:
                    try:
                        await self.on_disconnect()
                    except Exception:
                        pass
                ok = await self._reconnect_sub(backoff)
                backoff = min(backoff * 2, 30.0)
                if not ok:
                    return
                continue
            if not isinstance(msg, list) or not msg:
                continue
            kind = msg[0]
            if kind == "message" and len(msg) == 3:
                _, channel, payload = msg
                for pump in list(self._subs.get(channel, [])):
                    pump.push(channel, payload)
            elif kind == "pmessage" and len(msg) == 4:
                _, pattern, channel, payload = msg
                for pump in list(self._psubs.get(pattern, [])):
                    pump.push(channel, payload)
            # subscribe/unsubscribe acks: ignore

    async def _reconnect_sub(self, delay: float) -> bool:
        for attempt in range(self.reconnect_max_attempts):
            await asyncio.sleep(delay)
            try:
                await self._sub.connect()  # closes the stale transport first
                for channel in self._subs:
                    await self._sub.send_only("SUBSCRIBE", channel)
                for pattern in self._psubs:
                    await self._sub.send_only("PSUBSCRIBE", pattern)
                log.info("subscriber reconnected", attempt=attempt + 1)
                return True
            except Exception:
                delay = min(delay * 2, 30.0)
        log.error("subscriber reconnect gave up", attempts=self.reconnect_max_attempts)
        return False

    # -- KV -----------------------------------------------------------------
    async def get(self, key: str) -> str | None:
        return await self._main.command("GET", self._k(key))

    async def set(self, key: str, value: str) -> None:
        await self._main.command("SET", self._k(key), value)

    async def set_with_expiry(self, key: str, value: str, ttl_s: float) -> None:
        # PX for sub-second TTLs (heartbeat TTL = 2× interval)
        await self._main.command("SET", self._k(key), value, "PX", int(ttl_s * 1000))

    async def delete(self, key: str) -> None:
        await self._main.command("DEL", self._k(key))

    async def ttl(self, key: str) -> int:
        return int(await self._main.command("TTL", self._k(key)))

    # -- hash ---------------------------------------------------------------
    async def hget(self, key: str, field: str) -> str | None:
        return await self._main.command("HGET", self._k(key), field)

    async def hset(self, key: str, field: str, value: str) -> None:
        await self._main.command("HSET", self._k(key), field, value)

    async def hgetall(self, key: str) -> dict[str, str]:
        flat = await self._main.command("HGETALL", self._k(key)) or []
        return {flat[i]: flat[i + 1] for i in range(0, len(flat), 2)}

    async def hdel(self, key: str, field: str) -> None:
        await self._main.command("HDEL", self._k(key), field)

    # -- pub/sub ------------------------------------------------------------
    async def publish(self, channel: str, message: str) -> int:
        record_publish(channel)
        return int(await self._pub.command("PUBLISH", channel, message))

    async def subscribe(self, channel: str, handler: Handler) -> Subscription:
        pump = HandlerPump(handler)
        first = channel not in self._subs
        self._subs.setdefault(channel, []).append(pump)
        if first:
            await self._sub.send_only("SUBSCRIBE", channel)

        async def _unsub() -> None:
            lst = self._subs.get(channel, [])
            if pump in lst:
                lst.remove(pump)
            pump.stop()
            if not lst:
                self._subs.pop(channel, None)
                try:
                    await self._sub.send_only("UNSUBSCRIBE", channel)
                except Exception:
                    pass

        return Subscription(_unsub, channel)

    async def psubscribe(self, pattern: str, handler: Handler) -> Subscription:
        pump = HandlerPump(handler)
        first = pattern not in self._psubs
        self._psubs.setdefault(pattern, []).append(pump)
        if first:
            await self._sub.send_only("PSUBSCRIBE", pattern)

        async def _unsub() -> None:
            lst = self._psubs.get(pattern, [])
            if pump in lst:
                lst.remove(pump)
            pump.stop()
            if not lst:
                self._psubs.pop(pattern, None)
                try:
                    await self._sub.send_only("PUNSUBSCRIBE", pattern)
                except Exception:
                    pass

        return Subscription(_unsub, pattern)
