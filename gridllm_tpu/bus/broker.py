"""gridbus: a minimal RESP2 broker (pure asyncio).

Drop-in replacement for the reference's Redis dependency
(docker-compose.yml service `redis`) covering exactly the command subset the
GridLLM protocol uses (SURVEY.md §2.6): PING, GET/SET(+PX/EX)/DEL/TTL,
HGET/HSET/HGETALL/HDEL, PUBLISH/SUBSCRIBE/UNSUBSCRIBE/PSUBSCRIBE/
PUNSUBSCRIBE, AUTH/SELECT (accepted, no-op). Real Redis remains fully
compatible (RespBus speaks standard RESP2); this broker exists so a
multi-process cluster can run with zero external dependencies.

``--aof PATH`` enables append-only persistence (the reference ran Redis
with ``--appendonly yes``, SURVEY.md §5.4 — the scheduler's crash-reload
of `workers`/`active_jobs`/`job_queue:*` state only survives a BROKER
restart if the broker persists). Mutating KV/hash commands append one
JSON line, flushed per write and fsync'd at most once per second
(Redis's `everysec` durability); on start the log is replayed (expiries
stored as absolute wall deadlines, already-expired keys dropped) and
compacted to a snapshot.

High availability (ISSUE 10) — three extensions beyond Redis's command
subset, all optional (RespBus degrades gracefully against real Redis):

- **Resumable channels.** Durable channel classes (``durable_channel`` in
  bus/base.py: job results, stream frames, ``job:snapshot``,
  ``job:handoff``, ``job:drain``, ``kvx:*``) get a per-channel monotonic
  sequence number framed into every delivered payload plus a bounded
  replay ring (``--ring-cap`` messages/channel). ``RESUME <ch> <seq>``
  on a subscriber connection replays everything after ``seq`` and acks
  with ``["resume", ch, replayed, lost]`` — a reconnecting subscriber
  recovers the outage gap instead of silently losing it.
- **Warm-standby replication.** ``--replicaof host:port`` starts the
  broker as a follower: it connects to the primary over the normal RESP
  port, issues ``SYNC``, applies the snapshot, then tails the live
  record stream (mutations AND durable publishes with their seqs, so
  RESUME works against the standby after failover). A replica answers
  reads/subscribes but rejects mutations with ``-READONLY``.
- **Fencing epochs.** The primary carries an epoch (persisted in the
  AOF). Clients learn it via ``EPOCH`` (→ [role, epoch]) and fence each
  connection with ``FENCE <epoch>``; a FENCE carrying a HIGHER epoch
  than the broker's proves a newer primary was elected while this one
  was away — the broker marks itself stale and refuses every further
  mutation/publish, so a resurrected stale primary cannot split-brain
  the KV state (``active_jobs``, registry hashes). ``FAILOVER <epoch>``
  promotes a replica: it stops tailing and becomes the primary at that
  epoch. Election is client-driven by endpoint-list order (no quorum):
  the operator lists the real primary first, and a client only promotes
  a standby after every earlier endpoint failed.

Run: ``python -m gridllm_tpu.bus.broker --port 6379 [--aof bus.aof]
[--replicaof host:port] [--ring-cap N]``
"""

from __future__ import annotations

import argparse
import asyncio
import fnmatch
import json
import os
import time
from collections import OrderedDict, deque

from gridllm_tpu import faults
from gridllm_tpu.bus.base import durable_channel, encode_seq
from gridllm_tpu.utils.logging import get_logger

log = get_logger("bus.broker")


def _bulk(s: str | None) -> bytes:
    if s is None:
        return b"$-1\r\n"
    b = s.encode()
    return b"$%d\r\n%s\r\n" % (len(b), b)


def _arr(items: list[bytes]) -> bytes:
    return b"*%d\r\n%s" % (len(items), b"".join(items))


def _int(n: int) -> bytes:
    return b":%d\r\n" % n


OK = b"+OK\r\n"
PONG = b"+PONG\r\n"

# commands that mutate KV/hash state — the fencing + replica gates apply
_MUTATING = frozenset(("SET", "SETEX", "DEL", "HSET", "HDEL"))


class GridBusBroker:
    def __init__(self, aof_path: str | None = None,
                 replica_of: tuple[str, int] | None = None,
                 ring_cap: int = 512) -> None:
        self._kv: dict[str, str] = {}
        self._expiry: dict[str, float] = {}
        self._hashes: dict[str, dict[str, str]] = {}
        # channel/pattern → set of client writers
        self._subs: dict[str, set[asyncio.StreamWriter]] = {}
        self._psubs: dict[str, set[asyncio.StreamWriter]] = {}
        self._clients: set[asyncio.StreamWriter] = {*()}
        self._server: asyncio.AbstractServer | None = None
        self._aof_path = aof_path
        self._aof = None  # open append handle when persistence is on
        self._last_fsync = 0.0
        # -- HA state (ISSUE 10) --------------------------------------------
        # per-durable-channel monotonic seq + bounded replay ring of
        # (seq, payload); channels LRU-capped so per-job channels don't
        # accumulate forever on a long-lived broker. The seq counters
        # outlive their rings (and at 16x the ring-channel cap): a
        # counter that reset while a long-lived subscriber still held
        # its old watermark would mute the channel — every new message
        # seq <= watermark, silently dropped as a duplicate.
        self.ring_cap = max(int(ring_cap), 1)
        self._rings: OrderedDict[str, deque[tuple[int, str]]] = OrderedDict()
        self._seq: OrderedDict[str, int] = OrderedDict()
        self.MAX_RING_CHANNELS = 4096
        self.MAX_SEQ_CHANNELS = 65536
        # fencing: role/epoch/stale plus each connection's fenced epoch
        self.role = "replica" if replica_of else "primary"
        self.epoch = 1
        self.stale = False
        self._conn_epoch: dict[asyncio.StreamWriter, int] = {}
        # replication: live follower links (SYNC'd connections) on the
        # primary; the follower's own tail task + upstream address
        self._replicas: set[asyncio.StreamWriter] = set()
        self._replica_of = replica_of
        self._repl_task: asyncio.Task | None = None
        self.repl_synced = False  # follower: snapshot fully applied

    # -- kv helpers ---------------------------------------------------------
    def _expired(self, key: str) -> bool:
        dl = self._expiry.get(key)
        if dl is not None and time.monotonic() >= dl:
            self._kv.pop(key, None)
            self._expiry.pop(key, None)
            return True
        return False

    # -- persistence (AOF) + replication forwarding -------------------------
    def _wall_deadline(self, key: str) -> float | None:
        """Monotonic expiry → absolute wall time for the log."""
        dl = self._expiry.get(key)
        return None if dl is None else time.time() + (dl - time.monotonic())

    def _log(self, rec: dict) -> None:
        if self._aof is None:
            return
        self._aof.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._aof.flush()
        now = time.monotonic()
        if now - self._last_fsync >= 1.0:  # Redis `everysec`
            if faults.check("broker.fsync"):
                # injected durability stall: the fsync blocks the event
                # loop the way a saturated disk does — every client's
                # command round-trip freezes for the stall window
                time.sleep(0.4)
            os.fsync(self._aof.fileno())
            self._last_fsync = now

    def _record(self, rec: dict) -> None:
        """One mutation record: persist (when AOF on) AND forward to every
        live replica link. Replication is independent of persistence —
        a diskless primary still feeds its warm standby."""
        self._log(rec)
        if self._replicas:
            frame = _arr([_bulk("repl"),
                          _bulk(json.dumps(rec, separators=(",", ":")))])
            for w in list(self._replicas):
                if not self._try_write(w, frame):
                    self._replicas.discard(w)

    def _apply(self, rec: dict) -> None:
        op = rec["op"]
        if op == "set":
            self._kv[rec["k"]] = rec["v"]
            self._expiry.pop(rec["k"], None)
            exp = rec.get("exp")
            if exp is not None:
                remaining = exp - time.time()
                if remaining <= 0:
                    self._kv.pop(rec["k"], None)
                else:
                    self._expiry[rec["k"]] = time.monotonic() + remaining
        elif op == "del":
            for k in rec["ks"]:
                self._kv.pop(k, None)
                self._expiry.pop(k, None)
                self._hashes.pop(k, None)
        elif op == "hset":
            self._hashes.setdefault(rec["k"], {}).update(rec["fv"])
        elif op == "hdel":
            h = self._hashes.get(rec["k"], {})
            for f in rec["fs"]:
                h.pop(f, None)
        elif op == "epoch":
            self.epoch = max(self.epoch, int(rec["v"]))
        elif op == "stale":
            # a fencing demotion survives restarts: without this a
            # supervisor-restarted old primary would come back willing
            # to take writes at its pre-failover epoch (split-brain)
            self.stale = True
        elif op == "pub":
            # replicated durable publish: adopt the primary's seq into
            # our own ring (RESUME keeps working after a failover) and
            # deliver to any local subscribers
            ch, msg, seq = rec["ch"], rec["m"], int(rec["seq"])
            cur = self._seq.get(ch, 0)
            if seq > cur:
                if ch in self._seq:
                    self._seq.move_to_end(ch)
                self._seq[ch] = seq
                while len(self._seq) > self.MAX_SEQ_CHANNELS:
                    self._seq.popitem(last=False)
                self._ring(ch).append((seq, msg))
                self._deliver(ch, encode_seq(seq, msg))

    # -- replay rings -------------------------------------------------------
    def _ring(self, channel: str) -> deque[tuple[int, str]]:
        ring = self._rings.get(channel)
        if ring is None:
            ring = deque(maxlen=self.ring_cap)
            self._rings[channel] = ring
            # evict the RING only, never its seq counter: a rarely-
            # published durable channel (job:drain) whose counter reset
            # would restart at seq 1 and long-lived subscribers would
            # drop every message as a stale duplicate
            while len(self._rings) > self.MAX_RING_CHANNELS:
                self._rings.popitem(last=False)
        else:
            self._rings.move_to_end(channel)
        return ring

    def _next_seq(self, channel: str) -> int:
        seq = self._seq.get(channel, 0) + 1
        if channel in self._seq:
            self._seq.move_to_end(channel)
        self._seq[channel] = seq
        while len(self._seq) > self.MAX_SEQ_CHANNELS:
            self._seq.popitem(last=False)
        return seq

    def _replay_and_compact(self) -> None:
        path = self._aof_path
        assert path is not None
        n = 0
        src = path
        if not os.path.exists(path) and os.path.exists(path + ".bak"):
            # A crash in a previous compaction's window between snapshotting
            # the log to .bak and publishing the compacted replacement can
            # leave no file at `path`. The .bak holds the full pre-compaction
            # state — replay it rather than silently starting empty.
            log.warning("aof: missing, recovering from .bak", path=path)
            src = path + ".bak"
        if os.path.exists(src):
            with open(src) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
            records = []
            bad_at = None
            for i, line in enumerate(lines):
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    bad_at = i
                    break
            if bad_at is not None and bad_at != len(lines) - 1:
                # Redis's aof-load-truncated policy: a torn FINAL line
                # (crash mid-append) is expected and dropped; corruption
                # in the middle means the file is damaged and replaying a
                # prefix (then compacting over the original!) would
                # silently destroy every good record after it. Refuse.
                raise RuntimeError(
                    f"aof: corrupt record {bad_at + 1}/{len(lines)} in "
                    f"{src} (not a torn tail) — refusing to start; "
                    "repair or remove the file (remove its .bak too, or "
                    "startup will recover the pre-compaction state from it)"
                )
            if bad_at is not None:
                log.warning("aof: dropping torn final record", path=src)
            for rec in records:
                try:
                    self._apply(rec)
                    n += 1
                except KeyError:
                    raise RuntimeError(
                        f"aof: malformed record in {src} — refusing to "
                        "start; repair or remove the file (remove its .bak "
                        "too, or startup will recover state from it)"
                    ) from None
        # Compact: current state as a fresh log. Ordering matters for crash
        # safety — the compacted snapshot is fully written + fsync'd BEFORE
        # the original is touched, so some replayable file exists at every
        # instant: a crash before the .bak rename leaves `path` intact; a
        # crash between the two renames leaves .bak (recovered above); the
        # final os.replace is atomic.
        tmp = path + ".compact"
        with open(tmp, "w") as f:
            for rec in self._snapshot_records():
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if src == path and os.path.exists(path):
            # the pre-compaction log survives as .bak until the NEXT
            # successful compaction — the snapshot rewrite must never be
            # the only copy of the state it was derived from
            os.replace(path, path + ".bak")
        os.replace(tmp, path)
        self._aof = open(path, "a")
        log.info("aof: replayed and compacted", path=path, records=n,
                 keys=len(self._kv), hashes=len(self._hashes))

    def _snapshot_records(self, include_rings: bool = False) -> list[dict]:
        """Current state as replayable records: the AOF compactor and the
        SYNC snapshot share this shape (SYNC adds the replay rings so a
        standby can serve RESUME for pre-attach messages)."""
        out: list[dict] = [{"op": "epoch", "v": self.epoch}]
        if self.stale:
            out.append({"op": "stale"})
        for k, v in list(self._kv.items()):  # _expired() pops from _kv
            if self._expired(k):
                continue
            rec = {"op": "set", "k": k, "v": v}
            exp = self._wall_deadline(k)
            if exp is not None:
                rec["exp"] = exp
            out.append(rec)
        for k, h in self._hashes.items():
            if h:
                out.append({"op": "hset", "k": k, "fv": h})
        if include_rings:
            for ch, ring in self._rings.items():
                for seq, msg in ring:
                    out.append({"op": "pub", "ch": ch, "m": msg, "seq": seq})
        return out

    # -- replication (follower side) ----------------------------------------
    async def _replicate_loop(self) -> None:
        """Tail the primary: SYNC, apply the snapshot, then stream live
        records. Reconnects with capped backoff while still a replica —
        promotion (FAILOVER) cancels this task."""
        from gridllm_tpu.bus.resp import encode_command, read_reply

        assert self._replica_of is not None
        host, port = self._replica_of
        delay = 0.3
        while self.role == "replica":
            try:
                reader, writer = await asyncio.open_connection(host, port)
            except OSError:
                await asyncio.sleep(delay)
                delay = min(delay * 2, 5.0)
                continue
            try:
                writer.write(encode_command("SYNC"))
                await writer.drain()
                # the incoming snapshot is the FULL primary state: start
                # from empty so keys deleted on the primary during a
                # replication gap cannot resurrect here after a failover.
                # repl_synced drops with it — an EMPTY standby whose
                # re-sync died mid-snapshot must refuse promotion until
                # a snapshot lands again (the -NOTSYNCED gate)
                self.repl_synced = False
                self._kv.clear()
                self._expiry.clear()
                self._hashes.clear()
                self._rings.clear()
                self._seq.clear()
                delay = 0.3
                while self.role == "replica":
                    frame = await read_reply(reader)
                    if (not isinstance(frame, list) or len(frame) != 2
                            or frame[0] != "repl"):
                        continue
                    rec = json.loads(frame[1])
                    if rec.get("op") == "synced":
                        self.repl_synced = True
                        log.info("replica: snapshot applied, tailing",
                                 primary=f"{host}:{port}")
                        continue
                    self._apply(rec)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — link loss: retry
                if self.role == "replica":
                    log.warning("replica: link to primary lost",
                                error=str(e))
            finally:
                try:
                    writer.close()
                except Exception:  # noqa: BLE001
                    pass
            await asyncio.sleep(delay)
            delay = min(delay * 2, 5.0)

    # -- server -------------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 6379) -> None:
        if self._aof_path:
            self._replay_and_compact()
        self._server = await asyncio.start_server(self._client, host, port)
        if self._replica_of is not None and self.role == "replica":
            self._repl_task = asyncio.create_task(self._replicate_loop())
        log.info("gridbus broker listening", host=host, port=port,
                 role=self.role, epoch=self.epoch)

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._repl_task is not None:
            self._repl_task.cancel()
            self._repl_task = None
        if self._server is not None:
            self._server.close()
            # Close live client connections too: since Python 3.12.1
            # Server.wait_closed() blocks until all handlers finish.
            for w in list(self._clients):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None
        if self._aof is not None:
            try:
                self._aof.flush()
                os.fsync(self._aof.fileno())
            finally:
                self._aof.close()
                self._aof = None

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    MAX_BULK = 64 * 1024 * 1024  # guard against absurd $<len> headers

    async def _read_command(self, reader: asyncio.StreamReader) -> list[str] | None:
        """Returns None to close the connection (EOF or malformed frame)."""
        try:
            line = await reader.readuntil(b"\r\n")
            if not line.startswith(b"*"):
                # inline command (telnet-style)
                parts = line.strip().split()
                return [p.decode("utf-8", errors="replace") for p in parts] if parts else []
            n = int(line[1:-2])
            if n < 0 or n > 1024:
                return None
            args: list[str] = []
            for _ in range(n):
                hdr = await reader.readuntil(b"\r\n")
                if not hdr.startswith(b"$"):
                    return None
                ln = int(hdr[1:-2])
                if ln < 0 or ln > self.MAX_BULK:
                    return None
                data = await reader.readexactly(ln + 2)
                args.append(data[:-2].decode("utf-8", errors="replace"))
            return args
        except (asyncio.IncompleteReadError, ConnectionResetError, ValueError,
                asyncio.LimitOverrunError):
            return None

    async def _client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        if faults.check("broker.accept"):
            # injected accept-drop: the TCP handshake succeeded but the
            # broker hangs up before reading a byte — what a dying broker
            # (or a connection-table-exhausted one) looks like to clients
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass
            return
        self._clients.add(writer)
        try:
            while True:
                args = await self._read_command(reader)
                if args is None:
                    break
                if not args:
                    continue
                reply = self._execute(args, writer)
                if reply is not None:
                    if faults.check("broker.reply"):
                        # injected mid-reply reset: half the reply lands,
                        # then the connection dies — the client's reply
                        # stream is torn exactly where a crashing broker
                        # tears it
                        writer.write(reply[: max(1, len(reply) // 2)])
                        break
                    writer.write(reply)
                    await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError, BrokenPipeError):
            pass
        finally:
            self._clients.discard(writer)
            self._replicas.discard(writer)
            self._conn_epoch.pop(writer, None)
            self._drop_client(writer)
            writer.close()

    def _drop_client(self, writer: asyncio.StreamWriter) -> None:
        for registry in (self._subs, self._psubs):
            empty = []
            for target, clients in registry.items():
                clients.discard(writer)
                if not clients:
                    empty.append(target)
            for t in empty:
                registry.pop(t, None)

    # -- command dispatch ---------------------------------------------------
    def _gate_mutation(self, writer: asyncio.StreamWriter) -> bytes | None:
        """Fencing + role gate for mutating commands/publishes: a stale
        primary refuses everything (a newer epoch exists somewhere), a
        replica refuses writes, and a connection fenced at an older epoch
        than the broker's is a laggard from before the failover."""
        if self.stale:
            return (b"-STALE write refused: fenced at epoch %d, a newer "
                    b"primary exists\r\n" % self.epoch)
        if self.role == "replica":
            return b"-READONLY replica; FAILOVER to promote\r\n"
        fenced = self._conn_epoch.get(writer)
        if fenced is not None and fenced < self.epoch:
            return (b"-FENCED connection epoch %d behind broker epoch "
                    b"%d\r\n" % (fenced, self.epoch))
        return None

    def _execute(self, args: list[str], writer: asyncio.StreamWriter) -> bytes | None:
        cmd = args[0].upper()
        a = args[1:]
        if cmd == "PING":
            return PONG
        if cmd in ("AUTH", "SELECT"):
            return OK
        if cmd == "EPOCH":
            return _arr([_bulk(self.role if not self.stale else "stale"),
                         _int(self.epoch)])
        if cmd == "FENCE":
            try:
                e = int(a[0])
            except (IndexError, ValueError):
                return b"-ERR FENCE requires an integer epoch\r\n"
            if e > self.epoch:
                # proof of a newer primary: demote self permanently (until
                # an operator rebuilds this broker from the new primary) —
                # persisted, so a supervisor restart cannot resurrect a
                # fenced-off primary as a willing write target
                if self.role == "primary" and not self.stale:
                    self.stale = True
                    self._record({"op": "stale"})
                    log.warning("fenced by newer epoch; now stale",
                                mine=self.epoch, theirs=e)
                return (b"-STALE fenced: my epoch %d < %d\r\n"
                        % (self.epoch, e))
            if self.stale:
                return (b"-STALE write refused: fenced at epoch %d\r\n"
                        % self.epoch)
            if e < self.epoch:
                return (b"-EPOCH behind: current epoch is %d\r\n"
                        % self.epoch)
            self._conn_epoch[writer] = e
            return OK
        if cmd == "FAILOVER":
            try:
                e = int(a[0]) if a else self.epoch + 1
            except ValueError:
                return b"-ERR FAILOVER requires an integer epoch\r\n"
            if self.stale:
                return (b"-STALE cannot promote a fenced broker "
                        b"(epoch %d)\r\n" % self.epoch)
            if self.role == "replica" and not self.repl_synced:
                # a standby that NEVER reached its primary holds no state
                # — promoting it during a bring-up race (client boots
                # before the primary) would split-brain an empty broker
                # against the real one. Clients keep walking the list
                # until the primary arrives or a synced standby exists.
                return (b"-NOTSYNCED replica never synced with its "
                        b"primary; refusing promotion\r\n")
            if self.role == "replica":
                self.role = "primary"
                self.epoch = max(self.epoch + 1, e)
                if self._repl_task is not None:
                    self._repl_task.cancel()
                    self._repl_task = None
                self._record({"op": "epoch", "v": self.epoch})
                log.info("promoted to primary", epoch=self.epoch)
            # already primary: idempotent — the raced second client just
            # learns the epoch the first promotion established
            return _int(self.epoch)
        if cmd == "SYNC":
            # follower attach: snapshot (state + rings + epoch), then this
            # connection becomes a live record stream
            self._replicas.add(writer)
            for rec in self._snapshot_records(include_rings=True):
                writer.write(_arr([
                    _bulk("repl"),
                    _bulk(json.dumps(rec, separators=(",", ":")))]))
            writer.write(_arr([_bulk("repl"), _bulk('{"op":"synced"}')]))
            log.info("replica attached", replicas=len(self._replicas))
            return None
        if cmd == "RESUME":
            try:
                ch, last = a[0], int(a[1])
            except (IndexError, ValueError):
                return b"-ERR RESUME requires <channel> <last_seq>\r\n"
            # RESUME IS a subscribe: registration + replay happen inside
            # one synchronous command execution, so no concurrent publish
            # can interleave between them — replayed ring entries always
            # precede the first live frame, which is what lets the client
            # dedupe by a monotonic per-channel watermark
            self._subs.setdefault(ch, set()).add(writer)
            cur = self._seq.get(ch, 0)
            if last > cur:
                # the subscriber is AHEAD of us: this broker lost its seq
                # history (restart with no standby, counter eviction).
                # Ack lost=-1 so the client VOIDS its watermark — keeping
                # it would mute the channel (every new message seq <=
                # watermark, silently dropped as a duplicate) until the
                # fresh counter overtook the stale one.
                writer.write(_arr([_bulk("resume"), _bulk(ch),
                                   _int(0), _int(-1)]))
                return None
            ring = self._rings.get(ch)
            replayed = 0
            lost = 0
            if ring:
                first = ring[0][0]
                if first > last + 1:
                    # the gap outran the ring: everything between the
                    # subscriber's watermark and the ring head is gone
                    lost = first - last - 1
                for seq, msg in ring:
                    if seq > last:
                        writer.write(_arr([
                            _bulk("message"), _bulk(ch),
                            _bulk(encode_seq(seq, msg))]))
                        replayed += 1
            elif cur > last:
                lost = cur - last
            writer.write(_arr([_bulk("resume"), _bulk(ch),
                               _int(replayed), _int(lost)]))
            return None
        if cmd == "GET":
            key = a[0]
            if self._expired(key):
                return _bulk(None)
            return _bulk(self._kv.get(key))
        if cmd in _MUTATING:
            gate = self._gate_mutation(writer)
            if gate is not None:
                return gate
        if cmd == "SET":
            key, val = a[0], a[1]
            self._kv[key] = val
            self._expiry.pop(key, None)
            i = 2
            while i < len(a):
                opt = a[i].upper()
                if opt == "PX":
                    self._expiry[key] = time.monotonic() + int(a[i + 1]) / 1000
                    i += 2
                elif opt == "EX":
                    self._expiry[key] = time.monotonic() + int(a[i + 1])
                    i += 2
                else:
                    i += 1
            if self._aof is not None or self._replicas:
                rec = {"op": "set", "k": key, "v": val}
                exp = self._wall_deadline(key)
                if exp is not None:
                    rec["exp"] = exp
                self._record(rec)
            return OK
        if cmd == "SETEX":
            self._kv[a[0]] = a[2]
            self._expiry[a[0]] = time.monotonic() + int(a[1])
            self._record({"op": "set", "k": a[0], "v": a[2],
                          "exp": time.time() + int(a[1])})
            return OK
        if cmd == "DEL":
            n = 0
            for key in a:
                if key in self._kv or key in self._hashes:
                    n += 1
                self._kv.pop(key, None)
                self._expiry.pop(key, None)
                self._hashes.pop(key, None)
            if n:
                self._record({"op": "del", "ks": list(a)})
            return _int(n)
        if cmd == "TTL":
            key = a[0]
            if self._expired(key) or (key not in self._kv and key not in self._hashes):
                return _int(-2)
            dl = self._expiry.get(key)
            return _int(-1 if dl is None else max(0, int(dl - time.monotonic())))
        if cmd == "EXISTS":
            return _int(sum(1 for k in a if not self._expired(k) and (k in self._kv or k in self._hashes)))
        if cmd == "HGET":
            return _bulk(self._hashes.get(a[0], {}).get(a[1]))
        if cmd == "HSET":
            h = self._hashes.setdefault(a[0], {})
            added = 0
            fv: dict[str, str] = {}
            for i in range(1, len(a) - 1, 2):
                if a[i] not in h:
                    added += 1
                h[a[i]] = a[i + 1]
                fv[a[i]] = a[i + 1]
            self._record({"op": "hset", "k": a[0], "fv": fv})
            return _int(added)
        if cmd == "HGETALL":
            h = self._hashes.get(a[0], {})
            flat: list[bytes] = []
            for k, v in h.items():
                flat.append(_bulk(k))
                flat.append(_bulk(v))
            return _arr(flat)
        if cmd == "HDEL":
            h = self._hashes.get(a[0], {})
            n = 0
            for f in a[1:]:
                if f in h:
                    h.pop(f)
                    n += 1
            if n:
                self._record({"op": "hdel", "k": a[0], "fs": list(a[1:])})
            return _int(n)
        if cmd == "PUBLISH":
            gate = self._gate_mutation(writer)
            if gate is not None:
                return gate
            return _int(self._publish(a[0], a[1]))
        if cmd == "SUBSCRIBE":
            for ch in a:
                self._subs.setdefault(ch, set()).add(writer)
                # durable channels ack with their CURRENT seq (0 = none
                # yet): the subscriber records it as its resume baseline,
                # so a later reconnect can RESUME even on channels that
                # never delivered a message before the outage (a result
                # channel subscribed at submit, result published mid-gap).
                # Plain channels keep Redis's subscription-count ack.
                n = self._seq.get(ch, 0) if durable_channel(ch) else 1
                writer.write(_arr([_bulk("subscribe"), _bulk(ch), _int(n)]))
            return None
        if cmd == "UNSUBSCRIBE":
            for ch in a:
                clients = self._subs.get(ch)
                if clients:
                    clients.discard(writer)
                    if not clients:
                        self._subs.pop(ch, None)
                writer.write(_arr([_bulk("unsubscribe"), _bulk(ch), _int(0)]))
            return None
        if cmd == "PSUBSCRIBE":
            for p in a:
                self._psubs.setdefault(p, set()).add(writer)
                writer.write(_arr([_bulk("psubscribe"), _bulk(p), _int(1)]))
            return None
        if cmd == "PUNSUBSCRIBE":
            for p in a:
                clients = self._psubs.get(p)
                if clients:
                    clients.discard(writer)
                    if not clients:
                        self._psubs.pop(p, None)
                writer.write(_arr([_bulk("punsubscribe"), _bulk(p), _int(0)]))
            return None
        return b"-ERR unknown command '%s'\r\n" % cmd.encode()

    def _publish(self, channel: str, message: str) -> int:
        payload = message
        if durable_channel(channel):
            # assign the seq and record in the replay ring even with zero
            # subscribers: the whole point is that a subscriber currently
            # disconnected can RESUME this exact window later
            seq = self._next_seq(channel)
            self._ring(channel).append((seq, message))
            payload = encode_seq(seq, message)
            if self._replicas:
                frame = _arr([_bulk("repl"), _bulk(json.dumps(
                    {"op": "pub", "ch": channel, "m": message, "seq": seq},
                    separators=(",", ":")))])
                for w in list(self._replicas):
                    if not self._try_write(w, frame):
                        self._replicas.discard(w)
        return self._deliver(channel, payload)

    def _deliver(self, channel: str, payload: str) -> int:
        n = 0
        frame = _arr([_bulk("message"), _bulk(channel), _bulk(payload)])
        for w in list(self._subs.get(channel, ())):
            if self._try_write(w, frame):
                n += 1
        for pattern, clients in list(self._psubs.items()):
            if fnmatch.fnmatchcase(channel, pattern):
                pframe = _arr([_bulk("pmessage"), _bulk(pattern), _bulk(channel), _bulk(payload)])
                for w in list(clients):
                    if self._try_write(w, pframe):
                        n += 1
        return n

    # Redis's client-output-buffer-limit for pubsub clients defaults to
    # 32mb hard; same idea — a subscriber that stops reading gets kicked
    # instead of growing the broker's memory unboundedly.
    MAX_SUB_BUFFER = 32 * 1024 * 1024

    def _try_write(self, writer: asyncio.StreamWriter, frame: bytes) -> bool:
        try:
            if writer.is_closing():
                return False
            transport = writer.transport
            if transport.get_write_buffer_size() > self.MAX_SUB_BUFFER:
                log.warning("kicking slow pub/sub subscriber (output buffer full)")
                self._drop_client(writer)
                writer.close()
                return False
            writer.write(frame)
            return True
        except Exception:
            return False


def main() -> None:  # pragma: no cover
    from gridllm_tpu.utils.config import env_int

    ap = argparse.ArgumentParser(description="gridbus RESP broker")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6379)
    ap.add_argument("--aof", default=os.environ.get("GRIDBUS_AOF") or None,
                    metavar="PATH",
                    help="append-only persistence file (scheduler state "
                         "survives broker restarts; Redis --appendonly "
                         "equivalent)")
    ap.add_argument("--replicaof", default=None, metavar="HOST:PORT",
                    help="start as a warm standby tailing this primary "
                         "over its RESP port (SYNC snapshot + live record "
                         "stream); a client FAILOVER promotes it")
    ap.add_argument("--ring-cap", type=int,
                    default=env_int("GRIDLLM_BUS_RING_CAP"),
                    help="replay-ring capacity per durable channel "
                         "(messages) — the RESUME window a reconnecting "
                         "subscriber can recover")
    ns = ap.parse_args()
    replica_of = None
    if ns.replicaof:
        host, _, port = ns.replicaof.rpartition(":")
        replica_of = (host or "127.0.0.1", int(port))

    async def run() -> None:
        broker = GridBusBroker(aof_path=ns.aof, replica_of=replica_of,
                               ring_cap=ns.ring_cap)
        await broker.start(ns.host, ns.port)
        await broker.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
