"""gridbus: a minimal RESP2 broker (pure asyncio).

Drop-in replacement for the reference's Redis dependency
(docker-compose.yml service `redis`) covering exactly the command subset the
GridLLM protocol uses (SURVEY.md §2.6): PING, GET/SET(+PX/EX)/DEL/TTL,
HGET/HSET/HGETALL/HDEL, PUBLISH/SUBSCRIBE/UNSUBSCRIBE/PSUBSCRIBE/
PUNSUBSCRIBE, AUTH/SELECT (accepted, no-op). Real Redis remains fully
compatible (RespBus speaks standard RESP2); this broker exists so a
multi-process cluster can run with zero external dependencies.

``--aof PATH`` enables append-only persistence (the reference ran Redis
with ``--appendonly yes``, SURVEY.md §5.4 — the scheduler's crash-reload
of `workers`/`active_jobs`/`job_queue:*` state only survives a BROKER
restart if the broker persists). Mutating KV/hash commands append one
JSON line, flushed per write and fsync'd at most once per second
(Redis's `everysec` durability); on start the log is replayed (expiries
stored as absolute wall deadlines, already-expired keys dropped) and
compacted to a snapshot. Pub/sub is not persisted — same as Redis.

Run: ``python -m gridllm_tpu.bus.broker --port 6379 [--aof bus.aof]``
"""

from __future__ import annotations

import argparse
import asyncio
import fnmatch
import json
import os
import time

from gridllm_tpu.utils.logging import get_logger

log = get_logger("bus.broker")


def _bulk(s: str | None) -> bytes:
    if s is None:
        return b"$-1\r\n"
    b = s.encode()
    return b"$%d\r\n%s\r\n" % (len(b), b)


def _arr(items: list[bytes]) -> bytes:
    return b"*%d\r\n%s" % (len(items), b"".join(items))


def _int(n: int) -> bytes:
    return b":%d\r\n" % n


OK = b"+OK\r\n"
PONG = b"+PONG\r\n"


class GridBusBroker:
    def __init__(self, aof_path: str | None = None) -> None:
        self._kv: dict[str, str] = {}
        self._expiry: dict[str, float] = {}
        self._hashes: dict[str, dict[str, str]] = {}
        # channel/pattern → set of client writers
        self._subs: dict[str, set[asyncio.StreamWriter]] = {}
        self._psubs: dict[str, set[asyncio.StreamWriter]] = {}
        self._clients: set[asyncio.StreamWriter] = {*()}
        self._server: asyncio.AbstractServer | None = None
        self._aof_path = aof_path
        self._aof = None  # open append handle when persistence is on
        self._last_fsync = 0.0

    # -- kv helpers ---------------------------------------------------------
    def _expired(self, key: str) -> bool:
        dl = self._expiry.get(key)
        if dl is not None and time.monotonic() >= dl:
            self._kv.pop(key, None)
            self._expiry.pop(key, None)
            return True
        return False

    # -- persistence (AOF) --------------------------------------------------
    def _wall_deadline(self, key: str) -> float | None:
        """Monotonic expiry → absolute wall time for the log."""
        dl = self._expiry.get(key)
        return None if dl is None else time.time() + (dl - time.monotonic())

    def _log(self, rec: dict) -> None:
        if self._aof is None:
            return
        self._aof.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._aof.flush()
        now = time.monotonic()
        if now - self._last_fsync >= 1.0:  # Redis `everysec`
            os.fsync(self._aof.fileno())
            self._last_fsync = now

    def _apply(self, rec: dict) -> None:
        op = rec["op"]
        if op == "set":
            self._kv[rec["k"]] = rec["v"]
            self._expiry.pop(rec["k"], None)
            exp = rec.get("exp")
            if exp is not None:
                remaining = exp - time.time()
                if remaining <= 0:
                    self._kv.pop(rec["k"], None)
                else:
                    self._expiry[rec["k"]] = time.monotonic() + remaining
        elif op == "del":
            for k in rec["ks"]:
                self._kv.pop(k, None)
                self._expiry.pop(k, None)
                self._hashes.pop(k, None)
        elif op == "hset":
            self._hashes.setdefault(rec["k"], {}).update(rec["fv"])
        elif op == "hdel":
            h = self._hashes.get(rec["k"], {})
            for f in rec["fs"]:
                h.pop(f, None)

    def _replay_and_compact(self) -> None:
        path = self._aof_path
        assert path is not None
        n = 0
        src = path
        if not os.path.exists(path) and os.path.exists(path + ".bak"):
            # A crash in a previous compaction's window between snapshotting
            # the log to .bak and publishing the compacted replacement can
            # leave no file at `path`. The .bak holds the full pre-compaction
            # state — replay it rather than silently starting empty.
            log.warning("aof: missing, recovering from .bak", path=path)
            src = path + ".bak"
        if os.path.exists(src):
            with open(src) as f:
                lines = [ln.strip() for ln in f if ln.strip()]
            records = []
            bad_at = None
            for i, line in enumerate(lines):
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    bad_at = i
                    break
            if bad_at is not None and bad_at != len(lines) - 1:
                # Redis's aof-load-truncated policy: a torn FINAL line
                # (crash mid-append) is expected and dropped; corruption
                # in the middle means the file is damaged and replaying a
                # prefix (then compacting over the original!) would
                # silently destroy every good record after it. Refuse.
                raise RuntimeError(
                    f"aof: corrupt record {bad_at + 1}/{len(lines)} in "
                    f"{src} (not a torn tail) — refusing to start; "
                    "repair or remove the file (remove its .bak too, or "
                    "startup will recover the pre-compaction state from it)"
                )
            if bad_at is not None:
                log.warning("aof: dropping torn final record", path=src)
            for rec in records:
                try:
                    self._apply(rec)
                    n += 1
                except KeyError:
                    raise RuntimeError(
                        f"aof: malformed record in {src} — refusing to "
                        "start; repair or remove the file (remove its .bak "
                        "too, or startup will recover state from it)"
                    ) from None
        # Compact: current state as a fresh log. Ordering matters for crash
        # safety — the compacted snapshot is fully written + fsync'd BEFORE
        # the original is touched, so some replayable file exists at every
        # instant: a crash before the .bak rename leaves `path` intact; a
        # crash between the two renames leaves .bak (recovered above); the
        # final os.replace is atomic.
        tmp = path + ".compact"
        with open(tmp, "w") as f:
            for k, v in list(self._kv.items()):  # _expired() pops from _kv
                if self._expired(k):
                    continue
                rec = {"op": "set", "k": k, "v": v}
                exp = self._wall_deadline(k)
                if exp is not None:
                    rec["exp"] = exp
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
            for k, h in self._hashes.items():
                if h:
                    f.write(json.dumps(
                        {"op": "hset", "k": k, "fv": h},
                        separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        if src == path and os.path.exists(path):
            # the pre-compaction log survives as .bak until the NEXT
            # successful compaction — the snapshot rewrite must never be
            # the only copy of the state it was derived from
            os.replace(path, path + ".bak")
        os.replace(tmp, path)
        self._aof = open(path, "a")
        log.info("aof: replayed and compacted", path=path, records=n,
                 keys=len(self._kv), hashes=len(self._hashes))

    # -- server -------------------------------------------------------------
    async def start(self, host: str = "127.0.0.1", port: int = 6379) -> None:
        if self._aof_path:
            self._replay_and_compact()
        self._server = await asyncio.start_server(self._client, host, port)
        log.info("gridbus broker listening", host=host, port=port)

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Close live client connections too: since Python 3.12.1
            # Server.wait_closed() blocks until all handlers finish.
            for w in list(self._clients):
                try:
                    w.close()
                except Exception:
                    pass
            await self._server.wait_closed()
            self._server = None
        if self._aof is not None:
            try:
                self._aof.flush()
                os.fsync(self._aof.fileno())
            finally:
                self._aof.close()
                self._aof = None

    async def serve_forever(self) -> None:
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    MAX_BULK = 64 * 1024 * 1024  # guard against absurd $<len> headers

    async def _read_command(self, reader: asyncio.StreamReader) -> list[str] | None:
        """Returns None to close the connection (EOF or malformed frame)."""
        try:
            line = await reader.readuntil(b"\r\n")
            if not line.startswith(b"*"):
                # inline command (telnet-style)
                parts = line.strip().split()
                return [p.decode("utf-8", errors="replace") for p in parts] if parts else []
            n = int(line[1:-2])
            if n < 0 or n > 1024:
                return None
            args: list[str] = []
            for _ in range(n):
                hdr = await reader.readuntil(b"\r\n")
                if not hdr.startswith(b"$"):
                    return None
                ln = int(hdr[1:-2])
                if ln < 0 or ln > self.MAX_BULK:
                    return None
                data = await reader.readexactly(ln + 2)
                args.append(data[:-2].decode("utf-8", errors="replace"))
            return args
        except (asyncio.IncompleteReadError, ConnectionResetError, ValueError,
                asyncio.LimitOverrunError):
            return None

    async def _client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        self._clients.add(writer)
        try:
            while True:
                args = await self._read_command(reader)
                if args is None:
                    break
                if not args:
                    continue
                reply = self._execute(args, writer)
                if reply is not None:
                    writer.write(reply)
                    await writer.drain()
        except (ConnectionResetError, asyncio.IncompleteReadError, BrokenPipeError):
            pass
        finally:
            self._clients.discard(writer)
            self._drop_client(writer)
            writer.close()

    def _drop_client(self, writer: asyncio.StreamWriter) -> None:
        for registry in (self._subs, self._psubs):
            empty = []
            for target, clients in registry.items():
                clients.discard(writer)
                if not clients:
                    empty.append(target)
            for t in empty:
                registry.pop(t, None)

    # -- command dispatch ---------------------------------------------------
    def _execute(self, args: list[str], writer: asyncio.StreamWriter) -> bytes | None:
        cmd = args[0].upper()
        a = args[1:]
        if cmd == "PING":
            return PONG
        if cmd in ("AUTH", "SELECT"):
            return OK
        if cmd == "GET":
            key = a[0]
            if self._expired(key):
                return _bulk(None)
            return _bulk(self._kv.get(key))
        if cmd == "SET":
            key, val = a[0], a[1]
            self._kv[key] = val
            self._expiry.pop(key, None)
            i = 2
            while i < len(a):
                opt = a[i].upper()
                if opt == "PX":
                    self._expiry[key] = time.monotonic() + int(a[i + 1]) / 1000
                    i += 2
                elif opt == "EX":
                    self._expiry[key] = time.monotonic() + int(a[i + 1])
                    i += 2
                else:
                    i += 1
            if self._aof is not None:  # skip record+deadline math when off
                rec = {"op": "set", "k": key, "v": val}
                exp = self._wall_deadline(key)
                if exp is not None:
                    rec["exp"] = exp
                self._log(rec)
            return OK
        if cmd == "SETEX":
            self._kv[a[0]] = a[2]
            self._expiry[a[0]] = time.monotonic() + int(a[1])
            self._log({"op": "set", "k": a[0], "v": a[2],
                       "exp": time.time() + int(a[1])})
            return OK
        if cmd == "DEL":
            n = 0
            for key in a:
                if key in self._kv or key in self._hashes:
                    n += 1
                self._kv.pop(key, None)
                self._expiry.pop(key, None)
                self._hashes.pop(key, None)
            if n:
                self._log({"op": "del", "ks": list(a)})
            return _int(n)
        if cmd == "TTL":
            key = a[0]
            if self._expired(key) or (key not in self._kv and key not in self._hashes):
                return _int(-2)
            dl = self._expiry.get(key)
            return _int(-1 if dl is None else max(0, int(dl - time.monotonic())))
        if cmd == "EXISTS":
            return _int(sum(1 for k in a if not self._expired(k) and (k in self._kv or k in self._hashes)))
        if cmd == "HGET":
            return _bulk(self._hashes.get(a[0], {}).get(a[1]))
        if cmd == "HSET":
            h = self._hashes.setdefault(a[0], {})
            added = 0
            fv: dict[str, str] = {}
            for i in range(1, len(a) - 1, 2):
                if a[i] not in h:
                    added += 1
                h[a[i]] = a[i + 1]
                fv[a[i]] = a[i + 1]
            self._log({"op": "hset", "k": a[0], "fv": fv})
            return _int(added)
        if cmd == "HGETALL":
            h = self._hashes.get(a[0], {})
            flat: list[bytes] = []
            for k, v in h.items():
                flat.append(_bulk(k))
                flat.append(_bulk(v))
            return _arr(flat)
        if cmd == "HDEL":
            h = self._hashes.get(a[0], {})
            n = 0
            for f in a[1:]:
                if f in h:
                    h.pop(f)
                    n += 1
            if n:
                self._log({"op": "hdel", "k": a[0], "fs": list(a[1:])})
            return _int(n)
        if cmd == "PUBLISH":
            return _int(self._publish(a[0], a[1]))
        if cmd == "SUBSCRIBE":
            for ch in a:
                self._subs.setdefault(ch, set()).add(writer)
                writer.write(_arr([_bulk("subscribe"), _bulk(ch), _int(1)]))
            return None
        if cmd == "UNSUBSCRIBE":
            for ch in a:
                clients = self._subs.get(ch)
                if clients:
                    clients.discard(writer)
                    if not clients:
                        self._subs.pop(ch, None)
                writer.write(_arr([_bulk("unsubscribe"), _bulk(ch), _int(0)]))
            return None
        if cmd == "PSUBSCRIBE":
            for p in a:
                self._psubs.setdefault(p, set()).add(writer)
                writer.write(_arr([_bulk("psubscribe"), _bulk(p), _int(1)]))
            return None
        if cmd == "PUNSUBSCRIBE":
            for p in a:
                clients = self._psubs.get(p)
                if clients:
                    clients.discard(writer)
                    if not clients:
                        self._psubs.pop(p, None)
                writer.write(_arr([_bulk("punsubscribe"), _bulk(p), _int(0)]))
            return None
        return b"-ERR unknown command '%s'\r\n" % cmd.encode()

    def _publish(self, channel: str, message: str) -> int:
        n = 0
        frame = _arr([_bulk("message"), _bulk(channel), _bulk(message)])
        for w in list(self._subs.get(channel, ())):
            if self._try_write(w, frame):
                n += 1
        for pattern, clients in list(self._psubs.items()):
            if fnmatch.fnmatchcase(channel, pattern):
                pframe = _arr([_bulk("pmessage"), _bulk(pattern), _bulk(channel), _bulk(message)])
                for w in list(clients):
                    if self._try_write(w, pframe):
                        n += 1
        return n

    # Redis's client-output-buffer-limit for pubsub clients defaults to
    # 32mb hard; same idea — a subscriber that stops reading gets kicked
    # instead of growing the broker's memory unboundedly.
    MAX_SUB_BUFFER = 32 * 1024 * 1024

    def _try_write(self, writer: asyncio.StreamWriter, frame: bytes) -> bool:
        try:
            if writer.is_closing():
                return False
            transport = writer.transport
            if transport.get_write_buffer_size() > self.MAX_SUB_BUFFER:
                log.warning("kicking slow pub/sub subscriber (output buffer full)")
                self._drop_client(writer)
                writer.close()
                return False
            writer.write(frame)
            return True
        except Exception:
            return False


def main() -> None:  # pragma: no cover
    ap = argparse.ArgumentParser(description="gridbus RESP broker")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6379)
    ap.add_argument("--aof", default=os.environ.get("GRIDBUS_AOF") or None,
                    metavar="PATH",
                    help="append-only persistence file (scheduler state "
                         "survives broker restarts; Redis --appendonly "
                         "equivalent)")
    ns = ap.parse_args()

    async def run() -> None:
        broker = GridBusBroker(aof_path=ns.aof)
        await broker.start(ns.host, ns.port)
        await broker.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":  # pragma: no cover
    main()
